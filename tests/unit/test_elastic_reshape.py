"""Reshape-on-resume (ISSUE 7 tentpole b): a checkpoint saved under one
dp×tp×ep topology loads onto a different one — optimizer state
re-partitions from the global logical tensors, gradient-accumulation
steps rescale to preserve the GLOBAL batch size, the sampler position
carries over, and the RNG folds deterministically for the new mesh.

Fast tests cover the pure plan/diff arithmetic (no jit); the engine
parity runs (dp=4 save -> dp=2 / dp=1 load, zero-3 -> zero-1 cross-stage
load, trajectories matching the same-topology resume) are compile-heavy
and ride in the slow set.
"""

import os

import numpy as np
import jax
import pytest
from jax.sharding import PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.models import GPT2, GPT2Config
from deepspeed_tpu.utils import fault_injection, groups
from deepspeed_tpu.utils.groups import TopologyConfig
from deepspeed_tpu.runtime.zero.partitioning import (ZeroShardingPlan,
                                                     reshape_diff)

CFG = GPT2Config(n_layer=1, n_head=2, d_model=32, max_seq_len=16,
                 vocab_size=64, remat=False, dtype="float32")


def _plan(ndev, stage=1):
    groups.reset()
    topo = groups.initialize(TopologyConfig(),
                             devices=jax.devices()[:ndev], force=True)
    shapes = {"w": (8, 32), "b": (32,)}
    tp_specs = {"w": P(), "b": P()}
    return ZeroShardingPlan(stage, topo.mesh, tp_specs, shapes)


def _engine(ndev, stage=1, micro=2, extra_cfg=None):
    groups.reset()
    topo = groups.initialize(TopologyConfig(),
                             devices=jax.devices()[:ndev], force=True)
    cfg = {"train_micro_batch_size_per_gpu": micro,
           "steps_per_print": 0,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
           "zero_optimization": {"stage": stage}}
    cfg.update(extra_cfg or {})
    engine, _, _, _ = deepspeed_tpu.initialize(model=GPT2(CFG),
                                               topology=topo, config=cfg)
    return engine


def _batch(bsz, seed=0):
    rng = np.random.RandomState(seed)
    return {"input_ids": rng.randint(
        0, CFG.vocab_size, (bsz, CFG.max_seq_len)).astype(np.int32)}


# ----------------------------------------------------------- fast: plans
class TestPlanDescribe:
    def test_describe_is_jsonable_and_names_leaves(self):
        import json
        plan = _plan(4, stage=2)
        desc = plan.describe()
        json.dumps(desc)                       # must serialize
        assert desc["stage"] == 2
        assert desc["partition_group"] == 4
        assert set(desc["master_specs"]) == {"w", "b"}
        # the 8x32 leaf partitions over DP; 32 % 4 == 0 on last dim
        assert any(e is not None for e in desc["master_specs"]["w"])

    def test_reshape_diff_reports_group_change(self):
        old = _plan(4, stage=2).describe()
        new_plan = _plan(2, stage=2)
        diff = reshape_diff(old, new_plan)
        assert diff["old_partition_group"] == 4
        assert diff["new_partition_group"] == 2
        assert diff["old_stage"] == diff["new_stage"] == 2

    def test_reshape_diff_flags_replicated_leaves(self):
        """A leaf no mesh dim divides is REPORTED as replicated on the
        new mesh, not silently mis-sharded (specs always re-derive from
        shapes — the match_partition_rules discipline)."""
        groups.reset()
        topo = groups.initialize(TopologyConfig(),
                                 devices=jax.devices()[:8], force=True)
        plan = ZeroShardingPlan(1, topo.mesh, {"odd": P()},
                                {"odd": (7, 3)})
        diff = reshape_diff(None, plan)
        assert diff["replicated"] == ["odd"]

    def test_reshape_diff_handles_missing_saved_desc(self):
        plan = _plan(2, stage=1)
        diff = reshape_diff(None, plan)
        assert diff["old_partition_group"] is None
        assert diff["new_partition_group"] == 2


# --------------------------------------------- slow: engine parity runs
@pytest.mark.slow
class TestReshapeParity:
    """Acceptance: save at dp=4, load at dp=2 and dp=1 (and a zero-3 ->
    zero-1 cross-stage load), step both worlds — optimizer state,
    grad-accum rescale, and RNG fold produce loss trajectories matching
    the same-topology resume."""

    def _save_dp4(self, tmp_path, stage=1, steps=2):
        e = _engine(4, stage=stage)
        assert e.config.train_batch_size == 8
        b = _batch(8)
        for _ in range(steps):
            e.train_batch(b)
        e.save_checkpoint(str(tmp_path))
        return e

    def _resume_trajectory(self, tmp_path, ndev, stage=1, steps=3):
        e = _engine(ndev, stage=stage)
        path, _ = e.load_checkpoint(str(tmp_path))
        assert path is not None
        # the global batch is PRESERVED: gas rescaled so
        # micro * gas * dp == 8 everywhere
        assert e.config.train_batch_size == 8
        assert (e.config.train_micro_batch_size_per_gpu
                * e.config.gradient_accumulation_steps
                * e.topology.get_data_parallel_world_size()) == 8
        b = _batch(8)
        return e, [float(e.train_batch(b)) for _ in range(steps)]

    @pytest.mark.parametrize("ndev,expect_gas", [(2, 2), (1, 4)])
    def test_shrunk_world_matches_same_topology_resume(
            self, tmp_path, ndev, expect_gas):
        self._save_dp4(tmp_path)
        ref_engine, ref = self._resume_trajectory(tmp_path, 4)
        assert ref_engine.config.gradient_accumulation_steps == 1
        eng, got = self._resume_trajectory(tmp_path, ndev)
        assert eng.config.gradient_accumulation_steps == expect_gas
        assert eng.global_step == ref_engine.global_step
        np.testing.assert_allclose(got, ref, rtol=2e-4)

    def test_zero3_to_zero1_cross_stage_reshaped_world(self, tmp_path):
        """Cross-STAGE and cross-TOPOLOGY at once: zero-3 dp=4 state
        lands on a zero-1 dp=2 plan and the trajectory still matches
        the same-topology resume."""
        self._save_dp4(tmp_path, stage=3)
        _, ref = self._resume_trajectory(tmp_path, 4, stage=3)
        eng, got = self._resume_trajectory(tmp_path, 2, stage=1)
        assert eng.zero_stage == 1
        np.testing.assert_allclose(got, ref, rtol=2e-4)

    def test_rng_fold_is_deterministic_per_topology(self, tmp_path):
        """Two identical dp=2 resumes derive the SAME folded key; a
        same-topology resume keeps the saved key bitwise."""
        saver = self._save_dp4(tmp_path)
        saved_key = np.asarray(jax.random.key_data(saver.state["rng"]))
        e_a = _engine(2)
        e_a.load_checkpoint(str(tmp_path))
        e_b = _engine(2)
        e_b.load_checkpoint(str(tmp_path))
        ka = np.asarray(jax.random.key_data(e_a.state["rng"]))
        kb = np.asarray(jax.random.key_data(e_b.state["rng"]))
        np.testing.assert_array_equal(ka, kb)      # deterministic fold
        assert not np.array_equal(ka, saved_key)   # folded, not reused
        e_same = _engine(4)
        e_same.load_checkpoint(str(tmp_path))
        np.testing.assert_array_equal(
            np.asarray(jax.random.key_data(e_same.state["rng"])),
            saved_key)                             # bitwise on same topo

    def test_micro_steps_realign_to_new_gas(self, tmp_path):
        self._save_dp4(tmp_path, steps=3)
        e, _ = self._resume_trajectory(tmp_path, 2, steps=1)
        # after the resume step: boundaries aligned to the new gas
        assert e.is_gradient_accumulation_boundary()

    def test_reshaped_runs_checkpoint_resumes_same_topology(
            self, tmp_path):
        """Regression (found by driving the full disaster cycle): a run
        that was itself reshaped saves gas=2 under its own topology; a
        fresh SAME-topology engine built from the micro-batch-only
        config must preserve that global batch instead of silently
        halving it — while an EXPLICIT train_batch_size in the raw
        config still wins."""
        self._save_dp4(tmp_path)
        e_shrunk, _ = self._resume_trajectory(tmp_path, 2)   # gas 1->2
        e_shrunk.save_checkpoint(str(tmp_path))              # dp=2 ckpt
        # same topology (dp=2), derived batch: preserved
        e_again = _engine(2)
        assert e_again.config.train_batch_size == 4          # derived
        e_again.load_checkpoint(str(tmp_path))
        assert e_again.config.train_batch_size == 8
        assert e_again.config.gradient_accumulation_steps == 2
        # explicit train_batch_size: the user's call, NOT overridden
        e_explicit = _engine(2, extra_cfg={"train_batch_size": 4})
        e_explicit.load_checkpoint(str(tmp_path))
        assert e_explicit.config.train_batch_size == 4
        assert e_explicit.config.gradient_accumulation_steps == 1

    def test_indivisible_global_batch_raises(self, tmp_path):
        """dp=3 cannot hold global batch 8 with micro=2 — the resume
        refuses loudly instead of silently training at a different
        effective batch."""
        self._save_dp4(tmp_path)
        e = _engine(3, micro=2)
        with pytest.raises(ValueError, match="global batch"):
            e.load_checkpoint(str(tmp_path))


@pytest.mark.slow
@pytest.mark.chaos
class TestReshapeChaos:
    def test_kill_at_reshape_boundary_costs_nothing(self, tmp_path):
        """SimulatedKill at the reshape fault point aborts the resume
        mid-flight; the durable checkpoint stays fully loadable and a
        clean retry succeeds."""
        e = _engine(4)
        e.train_batch(_batch(8))
        e.save_checkpoint(str(tmp_path))
        e2 = _engine(2)
        fault_injection.arm("reshape", kill=True)
        try:
            with pytest.raises(fault_injection.SimulatedKill):
                e2.load_checkpoint(str(tmp_path))
        finally:
            fault_injection.reset()
        e3 = _engine(2)
        path, _ = e3.load_checkpoint(str(tmp_path))
        assert path is not None and e3.global_step == 1

    def test_sampler_position_survives_reshape(self, tmp_path):
        """The data-efficiency sampler's consumed-samples position is
        GLOBAL: it carries to the shrunken world so no sample is
        replayed or skipped."""
        de = {"data_efficiency": {"enabled": True, "seed": 7}}
        e = _engine(4, extra_cfg=de)
        dataset = [{"input_ids": np.full((CFG.max_seq_len,), i % 64,
                                         np.int32)} for i in range(64)]
        loader = e.deepspeed_io(dataset, shuffle=False)
        it = iter(loader)
        for _ in range(2):
            e.train_batch(next(it))
        assert e.data_sampler.consumed_samples == 16
        e.save_checkpoint(str(tmp_path))

        e2 = _engine(2, extra_cfg=de)
        e2.load_checkpoint(str(tmp_path))
        # sampler built AFTER the resume picks the stashed position up
        loader2 = e2.deepspeed_io(dataset, shuffle=False)
        assert e2.data_sampler.consumed_samples == 16
        nxt = next(iter(loader2))
        # global batch preserved -> the next 8 samples are 16..23
        np.testing.assert_array_equal(
            nxt["input_ids"][:, 0], np.arange(16, 24) % 64)
