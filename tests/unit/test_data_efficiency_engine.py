"""Engine-wired data efficiency (reference engine.py:336-367 +
deepspeed_io:1715): the curriculum schedule must change the batches the
jitted step actually sees, and random-LTD must change the middle-layer
token counts — reachable purely from initialize(config=...)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models import GPT2, GPT2Config
from deepspeed_tpu.utils import groups

# compile-heavy: excluded from the fast core set (pytest -m 'not slow')
pytestmark = pytest.mark.slow


CFG = GPT2Config(n_layer=3, n_head=4, d_model=64, max_seq_len=128,
                 vocab_size=512, remat=False, dtype="float32")


def _engine(extra):
    groups.reset()
    model = GPT2(CFG)
    cfg = {"train_micro_batch_size_per_gpu": 1,
           "steps_per_print": 0,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
           "zero_optimization": {"stage": 0}}
    cfg.update(extra)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg)
    return engine


class TestCurriculumEngine:
    def _cfg(self):
        return {"data_efficiency": {
            "enabled": True,
            "data_sampling": {
                "enabled": True,
                "curriculum_learning": {
                    "enabled": True,
                    "curriculum_type": "seqlen",
                    "min_difficulty": 32,
                    "max_difficulty": 128,
                    "schedule_type": "fixed_discrete",
                    "schedule_config": {"difficulty": [32, 64, 128],
                                        "max_step": [2, 4]}}}}}

    def test_difficulty_truncates_batches(self):
        engine = _engine(self._cfg())
        rng = np.random.RandomState(0)
        bsz = engine.config.train_batch_size
        batch = {"input_ids": rng.randint(0, 512, (bsz, 128))
                 .astype(np.int32)}
        seen = []
        for _ in range(6):
            engine.train_batch(batch)
            seen.append(engine.curriculum_difficulty)
        # the schedule really advanced and the engine recorded it
        assert seen[0] == 32 and seen[-1] == 128
        assert sorted(set(seen)) == [32, 64, 128]

    def test_distinct_programs_per_difficulty(self):
        engine = _engine(self._cfg())
        rng = np.random.RandomState(0)
        bsz = engine.config.train_batch_size
        batch = {"input_ids": rng.randint(0, 512, (bsz, 128))
                 .astype(np.int32)}
        for _ in range(6):
            engine.train_batch(batch)
        # the jitted step compiled one program per difficulty bucket
        # (an extra entry can appear for the first-call specialization) —
        # proof the truncation reached the compiled computation
        assert engine._train_step_jit._cache_size() >= 3

    def test_deepspeed_io_sampler(self):
        engine = _engine(self._cfg())
        data = [{"input_ids": np.full((128,), i, np.int32)}
                for i in range(8)]
        loader = engine.deepspeed_io(data, shuffle=False)
        it = iter(loader)
        b0 = next(it)
        assert b0["input_ids"].shape == (engine.config.train_batch_size,
                                         128)
        # sampler is resumable state
        sd = engine.data_sampler.state_dict()
        assert "consumed_samples" in sd

    def test_legacy_top_level_curriculum_key(self):
        engine = _engine({"curriculum_learning": {
            "enabled": True, "curriculum_type": "seqlen",
            "min_difficulty": 32, "max_difficulty": 128,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 4,
                                "difficulty_step": 32}}})
        assert engine.curriculum_scheduler is not None


class TestRandomLTDEngine:
    def _cfg(self):
        return {"data_efficiency": {
            "enabled": True,
            "data_routing": {
                "enabled": True,
                "random_ltd": {
                    "enabled": True,
                    "random_ltd_min_value": 64,
                    "random_ltd_max_value": 128,
                    "random_ltd_schedule": {"seq_step": 32,
                                            "require_steps": 4}}}}}

    def test_keep_count_ramps_and_trains(self):
        engine = _engine(self._cfg())
        rng = np.random.RandomState(0)
        bsz = engine.config.train_batch_size
        batch = {"input_ids": rng.randint(0, 512, (bsz, 128))
                 .astype(np.int32)}
        keeps = []
        losses = []
        for _ in range(6):
            losses.append(float(engine.train_batch(batch)))
            keeps.append(engine.random_ltd_scheduler.get_current_seq())
        assert keeps[0] == 64                  # ramp start
        assert keeps[-1] == 128                # ramped to full
        assert len(set(keeps)) >= 2            # schedule moved
        assert losses[-1] < losses[0]          # still trains

    def test_ltd_loss_differs_from_full(self):
        # with keep < T the middle layer sees fewer tokens -> different
        # loss value than the full forward on identical params/batch
        groups.reset()
        model = GPT2(CFG)
        params = model.init(jax.random.key(0))
        ids = jnp.asarray(np.random.RandomState(1).randint(
            0, 512, (2, 128)), jnp.int32)
        rng = jax.random.key(7)
        full = float(model.loss(params, {"input_ids": ids}, rng=rng,
                                train=True))
        ltd = float(model.loss(params, {"input_ids": ids}, rng=rng,
                               train=True, ltd_keep=64))
        assert full != ltd

    def test_rejects_model_without_ltd(self):
        groups.reset()
        from deepspeed_tpu.models import Llama
        from deepspeed_tpu.models.llama import LLAMA_TINY
        from dataclasses import replace
        model = Llama(replace(LLAMA_TINY, dtype="float32"))
        cfg = {"train_micro_batch_size_per_gpu": 1,
               "steps_per_print": 0,
               "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}}}
        cfg.update(self._cfg())
        with pytest.raises(ValueError, match="ltd_keep"):
            deepspeed_tpu.initialize(model=model, config=cfg)
