"""Elasticity + autotuning tests (reference tests/unit/elasticity/,
tests/unit/autotuning/)."""

import json

import numpy as np
import pytest

from deepspeed_tpu.elasticity import (compute_elastic_config,
                                      get_compatible_chips_v01,
                                      get_compatible_chips_v02,
                                      ElasticityError,
                                      ElasticityIncompatibleWorldSize)


class TestElasticityV01:
    def test_basic_candidates(self):
        batch, valid = get_compatible_chips_v01(
            micro_batches=[2, 4], max_acceptable_batch_size=16)
        # 16 is compatible with many chip counts for mb in {2,4}
        assert batch == 16
        assert 1 in valid and 2 in valid and 4 in valid and 8 in valid

    def test_batch_divisible_constraint(self):
        batch, valid = get_compatible_chips_v01(
            micro_batches=[3], max_acceptable_batch_size=10)
        assert batch == 9
        assert valid == [1, 3]

    def test_micro_batch_too_big_raises(self):
        with pytest.raises(ElasticityError):
            get_compatible_chips_v01([32], max_acceptable_batch_size=16)

    def test_min_max_chips_window(self):
        batch, valid = get_compatible_chips_v01(
            [2, 4], 16, min_chips=2, max_chips=4)
        assert all(2 <= v <= 4 for v in valid)


class TestElasticityV02:
    def test_model_parallel_scaling(self):
        batch, valid = get_compatible_chips_v02(
            [2, 4], 16, current_num_chips=8, model_parallel_size=2,
            chips_per_slice=1)
        # chip counts are DP counts scaled by mp=2 -> all even
        assert all(v % 2 == 0 for v in valid)

    def test_bad_world_size_raises(self):
        with pytest.raises(ElasticityIncompatibleWorldSize):
            get_compatible_chips_v02([2], 8, current_num_chips=3,
                                     model_parallel_size=2,
                                     chips_per_slice=2)


class TestComputeElasticConfig:
    CFG = {"elasticity": {"enabled": True, "max_train_batch_size": 32,
                          "micro_batch_sizes": [2, 4], "min_gpus": 1,
                          "max_gpus": 16, "version": 0.1}}

    def test_resolves(self):
        batch, valid = compute_elastic_config(self.CFG)
        assert batch == 32 and 8 in valid

    def test_world_size_check(self):
        batch, valid, micro = compute_elastic_config(
            self.CFG, world_size=8, return_microbatch=True)
        assert batch % 8 == 0
        assert micro in (2, 4)
        with pytest.raises(ElasticityIncompatibleWorldSize):
            compute_elastic_config(self.CFG, world_size=7)

    def test_disabled_raises(self):
        with pytest.raises(ElasticityError):
            compute_elastic_config({"elasticity": {"enabled": False}})
        with pytest.raises(ElasticityError):
            compute_elastic_config({})


class TestAutotuner:
    def test_grid_and_random_tuners(self):
        from deepspeed_tpu.autotuning import GridSearchTuner, RandomTuner
        space = {"zero_stage": [0, 2], "micro_batch": [1, 2]}
        grid = list(GridSearchTuner(space))
        assert len(grid) == 4
        rnd = list(RandomTuner(space, seed=1, max_trials=3))
        assert len(rnd) == 3
        assert all(e in grid for e in rnd)

    def test_tune_picks_working_config(self, tmp_path):
        from deepspeed_tpu.autotuning import Autotuner
        from deepspeed_tpu.models import GPT2, GPT2Config
        cfg = GPT2Config(n_layer=1, n_head=2, d_model=32, max_seq_len=32,
                         vocab_size=64, remat=False, dtype="float32")
        tuner = Autotuner(
            GPT2(cfg),
            base_config={"optimizer": {"type": "AdamW",
                                       "params": {"lr": 1e-3}}},
            steps=2, warmup=1, results_dir=str(tmp_path))
        best_config, results = tuner.tune(
            space={"zero_stage": [0, 1], "micro_batch": [1, 2]})
        assert len(results) == 4
        ok = [r for r in results if not r["error"]]
        assert ok, results
        best = max(ok, key=lambda r: r["samples_per_sec"])
        assert best_config["zero_optimization"]["stage"] == \
            best["zero_stage"]
        saved = json.loads((tmp_path / "best_config.json").read_text())
        assert saved["result"]["samples_per_sec"] > 0

    def test_memory_estimates_ordered(self):
        from deepspeed_tpu.autotuning import ModelInfo
        mi = ModelInfo(num_params=1_000_000)
        ests = [mi.memory_per_chip(s, dp_world=8) for s in (0, 1, 2, 3)]
        assert ests[0] > ests[1] > ests[2] > ests[3]


class TestElasticityV02Fixes:
    def test_scale_up_beyond_current_world(self):
        from deepspeed_tpu.elasticity import get_compatible_chips_v02
        batch, valid = get_compatible_chips_v02(
            [2, 4], 64, current_num_chips=8, max_chips=64,
            model_parallel_size=2)
        assert 16 in valid and 32 in valid  # scale-up allowed

    def test_micro_batch_uses_dp_share(self):
        cfg = {"elasticity": {"enabled": True, "max_train_batch_size": 16,
                              "micro_batch_sizes": [4], "min_gpus": 1,
                              "max_gpus": 64, "version": 0.2,
                              "model_parallel_size": 2}}
        batch, valid, micro = compute_elastic_config(
            cfg, world_size=8, return_microbatch=True)
        assert micro == 4  # dp=4 replicas, 16/4=4 per replica

    def test_min_chips_rescaled_by_mp(self):
        from deepspeed_tpu.elasticity import get_compatible_chips_v02
        batch, valid = get_compatible_chips_v02(
            [2, 4], 16, current_num_chips=16, min_chips=4,
            model_parallel_size=2)
        assert 4 in valid  # 4 chips = dp 2, satisfies min_gpus=4


class TestAutotunerCustomSpace:
    def test_user_axis_only_space(self, tmp_path):
        from deepspeed_tpu.autotuning import Autotuner
        from deepspeed_tpu.models import GPT2, GPT2Config
        cfg = GPT2Config(n_layer=1, n_head=2, d_model=32, max_seq_len=32,
                         vocab_size=64, remat=False, dtype="float32")
        tuner = Autotuner(
            GPT2(cfg),
            base_config={"optimizer": {"type": "AdamW",
                                       "params": {"lr": 1e-3}},
                         "train_micro_batch_size_per_gpu": 1},
            steps=1, warmup=1, results_dir=str(tmp_path))
        best_config, results = tuner.tune(
            space={"gradient_accumulation_steps": [1, 2]})
        assert len(results) == 2
        assert all(not r["error"] for r in results), results
        assert best_config["gradient_accumulation_steps"] in (1, 2)

    def test_zero_suboptions_preserved(self):
        from deepspeed_tpu.autotuning import Autotuner
        from deepspeed_tpu.models import GPT2, GPT2Config
        cfg = GPT2Config(n_layer=1, n_head=2, d_model=32, max_seq_len=32,
                         vocab_size=64)
        t = Autotuner(GPT2(cfg), base_config={
            "zero_optimization": {"stage": 1, "overlap_comm": False}})
        c = t._exp_config({"zero_stage": 2, "micro_batch": 2})
        assert c["zero_optimization"] == {"stage": 2,
                                          "overlap_comm": False}



class TestResourceScheduler:
    """reference autotuning/scheduler.py ResourceManager: slot
    reservation over a node pool, concurrent trial execution, and the
    model-based tuner driven in capacity-sized rounds."""

    def test_concurrent_capacity_respected(self):
        import threading, time
        from deepspeed_tpu.autotuning import ResourceManager
        rm = ResourceManager([("h0", 2), ("h1", 1)])
        assert rm.capacity == 3
        live = []
        peak = []
        lock = threading.Lock()

        def run_fn(exp, res):
            with lock:
                live.append(exp)
                peak.append(len(live))
            time.sleep(0.05)
            with lock:
                live.remove(exp)
            return {"samples_per_sec": exp["v"], "host": res.node.host}

        results = rm.run([{"v": i} for i in range(7)], run_fn)
        assert [r["samples_per_sec"] for r in results] == list(range(7))
        assert max(peak) <= 3            # never above pool capacity
        assert max(peak) >= 2            # and actually concurrent
        # every slot returned to the pool
        assert sum(len(n.free) for n in rm.nodes) == 3

    def test_trial_failure_is_data(self):
        from deepspeed_tpu.autotuning import ResourceManager
        rm = ResourceManager([("h0", 1)])

        def run_fn(exp, res):
            if exp["v"] == 1:
                raise RuntimeError("oom")
            return {"samples_per_sec": 1.0}

        results = rm.run([{"v": 0}, {"v": 1}, {"v": 2}], run_fn)
        assert results[1]["error"].startswith("RuntimeError")
        assert results[0]["samples_per_sec"] == 1.0
        assert len(rm.nodes[0].free) == 1

    def test_model_based_rounds_find_optimum(self):
        from deepspeed_tpu.autotuning import ResourceManager
        rm = ResourceManager([("h0", 2)])
        space = {"micro_bs": [1, 2, 4, 8, 16, 32], "stage": [0, 1, 2, 3]}

        def run_fn(exp, res):
            return {"samples_per_sec":
                    -abs(exp["micro_bs"] - 16) - 3 * abs(exp["stage"] - 2)}

        best_exp, best_res, all_r = rm.run_model_based(
            space, run_fn, max_trials=14)
        assert best_exp == {"micro_bs": 16, "stage": 2}
        assert len(all_r) <= 14

    def test_subprocess_runner_parses_json_line(self, tmp_path):
        from deepspeed_tpu.autotuning import (Node, Reservation,
                                              SubprocessRunner)
        script = tmp_path / "exp.py"
        script.write_text(
            "import json, sys, os\n"
            "exp = json.loads(sys.argv[sys.argv.index('--exp')+1])\n"
            "print('noise')\n"
            "print(json.dumps({'samples_per_sec': exp['v'] * 2,\n"
            "                  'slots': os.environ['DSTPU_EXP_SLOTS']}))\n")
        run = SubprocessRunner(str(script), timeout_s=60)
        res = Reservation(Node("localhost", 4), [0, 1])
        out = run({"v": 21}, res)
        assert out["samples_per_sec"] == 42
        assert out["slots"] == "0,1"


    def test_failed_trials_do_not_poison_model(self):
        """A crashed trial must neither rank as best (negative-metric
        spaces) nor enter the cost-model fit (-inf observations NaN the
        ridge solve and silently degrade every later pick)."""
        from deepspeed_tpu.autotuning import ResourceManager
        rm = ResourceManager([("h0", 2)])
        space = {"micro_bs": [1, 2, 4, 8, 16, 32], "stage": [0, 1, 2, 3]}

        def run_fn(exp, res):
            if exp["micro_bs"] == 32:
                raise MemoryError("oom")
            return {"samples_per_sec":
                    -abs(exp["micro_bs"] - 16) - 3 * abs(exp["stage"] - 2)}

        best_exp, best_res, all_r = rm.run_model_based(
            space, run_fn, max_trials=20)
        assert best_exp == {"micro_bs": 16, "stage": 2}
        assert "error" not in best_res


    def test_autotuner_model_guided(self):
        """tuner_type='model' drives the Autotuner loop end to end with
        the cost model recording each trial (run_experiment faked)."""
        from deepspeed_tpu.autotuning import Autotuner

        class _M:
            class config:
                @staticmethod
                def num_params():
                    return 1000
        at = Autotuner(_M(), {"train_micro_batch_size_per_gpu": 1},
                       tuner_type="model", max_trials=10)
        calls = []

        def fake_run(exp):
            calls.append(exp)
            v = -abs(exp["train_micro_batch_size_per_gpu"] - 8) \
                - 2 * abs(exp["zero_stage"] - 2)
            return dict(exp, samples_per_sec=v, error=None)

        at.run_experiment = fake_run
        space = {"train_micro_batch_size_per_gpu": [1, 2, 4, 8, 16],
                 "zero_stage": [0, 1, 2, 3]}
        import tempfile
        at.results_dir = tempfile.mkdtemp()
        best_config, results = at.tune(space)
        best = max((r for r in results if not r["error"]),
                   key=lambda r: r["samples_per_sec"])
        assert best["train_micro_batch_size_per_gpu"] == 8
        assert best["zero_stage"] == 2
        assert len(calls) <= 10
