"""Tier-1 pipeline coverage (no SPMD partitioning required).

The slow-tier SPMD pipeline tests (test_pipe.py) xfail on legacy jaxlib
because their meshes carry auto axes > 1 (the partial-manual partitioner
gap). Everything here runs ANYWHERE: the schedule streams are pure
python, and the executor tests use a pipe-ONLY virtual mesh (every
non-pipe axis size 1), which legacy jaxlib partitions fine — so the
pipeline path is no longer xfail-only.

Covers ISSUE-10's structural acceptance bars on the legacy-jax path:
the ZB-H1 tick order (schedule stream vs the executor's index maps),
W-pass work occupying the drain ticks, the executor bubble model
strictly below the GPipe figure, and pp=2 loss/grad parity of the
1F1B and zero-bubble executors against the single-stage program.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.runtime.pipe import (
    TrainSchedule, ZeroBubbleSchedule, ForwardPass, BackwardActGrad,
    BackwardWeightGrad, ReduceGrads, OptimizerStep,
    executor_bubble_fraction, executor_tick_units,
    pipeline_1f1b_grads, pipeline_zb_grads, PipeOffload)
from deepspeed_tpu.runtime.pipe.spmd import (
    zb_b_index, zb_deferred_window, zb_f_index, zb_num_ticks,
    zb_w_deferred_index)
from deepspeed_tpu.runtime.swap_tensor import host_stage
from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.groups import TopologyConfig

SHAPES = [(2, 2), (2, 4), (2, 8), (4, 4), (4, 8), (3, 5), (4, 3)]


# ------------------------------------------------------ schedule stream
class TestZeroBubbleStream:
    @pytest.mark.parametrize("S,M", SHAPES)
    def test_tick_parity_with_executor_maps(self, S, M):
        """The acceptance-bar tick test: the imperative ZB-H1 stream
        (schedule.py, written in the reference phase style) and the SPMD
        executor's affine index maps (spmd.py zb_*_index — the traced
        masks) must describe the SAME per-(stage, tick) op multiset."""
        for s in range(S):
            sched = ZeroBubbleSchedule(M, S, s)
            K = zb_deferred_window(s, M, S)
            assert K == sched.deferred_window()
            for t in range(zb_num_ticks(M, S)):
                want = []
                f = zb_f_index(t, s, M, S)
                if 0 <= f < M:
                    want.append(("F", f))
                b = zb_b_index(t, s, M, S)
                if 0 <= b < M:
                    want.append(("B", b))
                    if b < M - K:
                        want.append(("W", b))
                w = zb_w_deferred_index(t, s, M, S)
                if max(M - K, 0) <= w < M:
                    want.append(("W", w))
                assert sched.tick_ops(t) == want, (s, t)

    @pytest.mark.parametrize("S,M", SHAPES)
    def test_complete_and_causal(self, S, M):
        """Every microbatch gets exactly one F, one B and one W per
        stage; B(m) never precedes F(m); W(m) never precedes B(m)."""
        for s in range(S):
            sched = ZeroBubbleSchedule(M, S, s)
            seen = {"F": {}, "B": {}, "W": {}}
            for t in range(sched.num_ticks()):
                for kind, m in sched.tick_ops(t):
                    assert m not in seen[kind], (kind, m)
                    seen[kind][m] = t
            for kind in seen:
                assert set(seen[kind]) == set(range(M)), (s, kind)
            for m in range(M):
                assert seen["F"][m] <= seen["B"][m] <= seen["W"][m]

    @pytest.mark.parametrize("S,M", [(2, 4), (4, 8), (3, 6)])
    def test_w_occupies_drain_ticks(self, S, M):
        """The zero-bubble property, structurally: each non-final
        stage's forward-drain ticks (t >= s + M, where 1F1B burns
        garbage forwards) carry deferred W work instead."""
        for s in range(S - 1):
            sched = ZeroBubbleSchedule(M, S, s)
            drain = range(s + M, sched.num_ticks())
            assert len(list(drain)) > 0
            for t in drain:
                kinds = [k for k, _ in sched.tick_ops(t)]
                assert "F" not in kinds
                assert "W" in kinds, (s, t)

    def test_steps_instruction_stream(self):
        scheds = [ZeroBubbleSchedule(4, 2, s) for s in range(2)]
        for sched in scheds:
            steps = list(sched)
            assert steps[-1] == [ReduceGrads(), OptimizerStep()]
            flat = [i for st in steps for i in st]
            assert sum(isinstance(i, ForwardPass) for i in flat) == 4
            assert sum(isinstance(i, BackwardActGrad)
                       for i in flat) == 4
            assert sum(isinstance(i, BackwardWeightGrad)
                       for i in flat) == 4

    def test_buffers_bounded_by_stages_not_microbatches(self):
        assert ZeroBubbleSchedule(64, 4, 0).num_pipe_buffers() == \
            ZeroBubbleSchedule(8, 4, 0).num_pipe_buffers()


# ------------------------------------------------------- bubble model
class TestBubbleModel:
    @pytest.mark.parametrize("S,M", [(2, 4), (2, 8), (4, 8), (4, 16),
                                     (8, 16)])
    def test_zb_strictly_below_gpipe(self, S, M):
        """The acceptance bar: the zero-bubble executor's bubble
        fraction is strictly below the GPipe (S-1)/(M+S-1) figure."""
        gp = executor_bubble_fraction("gpipe", M, S)
        assert gp == pytest.approx((S - 1) / (M + S - 1))
        assert executor_bubble_fraction("zb", M, S) < gp

    def test_1f1b_executor_is_flat(self):
        # the unconditional-lane executor: 3 units every tick
        assert executor_tick_units("1f1b", 8, 4) == [3] * (8 + 6)

    def test_known_point(self):
        # hand-checked S=4, M=8: gpipe wall 33, zb wall 30
        assert sum(executor_tick_units("gpipe", 8, 4)) == 33
        assert sum(executor_tick_units("zb", 8, 4)) == 30
        assert executor_bubble_fraction("zb", 8, 4) == \
            pytest.approx(1 - 24 / 30)

    def test_train_schedule_bubble_unchanged(self):
        assert TrainSchedule(8, 4, 0).bubble_fraction() == \
            pytest.approx(3 / 11)


# ------------------------------------------------ executor parity pp=2
def _pipe_only_mesh(S):
    groups.reset()
    topo = groups.initialize(
        TopologyConfig(pipe_parallel_size=S, data_parallel_size=1),
        devices=jax.devices()[:S], force=True)
    return topo.mesh


def _toy_problem(S, M, L=4, D=8, B=2, seed=0):
    rng = np.random.RandomState(seed)
    w = jnp.asarray(rng.randn(L, D, D) * 0.2, jnp.float32)
    x = jnp.asarray(rng.randn(M, B, D), jnp.float32)
    tgt = jnp.asarray(rng.randn(M, B, D), jnp.float32)
    aux = jnp.zeros((L, 1), jnp.uint32)
    hp = jnp.asarray(rng.randn(D) * 0.3, jnp.float32)

    def block(c, wi, a):
        return jnp.tanh(c @ wi)

    def head_loss(h, y, t):
        return jnp.mean((y * h - jax.lax.stop_gradient(t)) ** 2)

    def ref_loss(w, hp, x):
        def f(c, wi):
            return block(c, wi, None), None

        def run(mb):
            y, _ = jax.lax.scan(f, mb, w)
            return y
        y = jax.vmap(run)(x)
        return jnp.mean(jax.vmap(
            lambda ym, tm: head_loss(hp, ym, tm))(y, tgt))

    return w, x, tgt, aux, hp, block, head_loss, ref_loss


class TestSteadyStateExecutorsPP2:
    """pp=2 loss/grad parity on a pipe-only virtual mesh — runnable on
    legacy jaxlib (no auto axis > 1 in the partial-manual program)."""

    @pytest.mark.parametrize("fn,kw", [
        (pipeline_1f1b_grads, {}),
        (pipeline_zb_grads, {}),
        (pipeline_zb_grads, {"offload": PipeOffload(activations=True)}),
        (pipeline_zb_grads, {"offload": PipeOffload(
            activations=True, double_buffer=False)}),
    ], ids=["1f1b", "zb", "zb_offload", "zb_offload_nodb"])
    def test_matches_sequential(self, fn, kw):
        S, M = 2, 4
        mesh = _pipe_only_mesh(S)
        (w, x, tgt, aux, hp, block, head_loss,
         ref_loss) = _toy_problem(S, M)
        l_ref, g_ref = jax.value_and_grad(ref_loss, (0, 1, 2))(w, hp, x)
        with jax.set_mesh(mesh):
            ws = jax.device_put(w, NamedSharding(mesh, P("pipe")))
            auxs = jax.device_put(aux, NamedSharding(mesh, P("pipe")))
            xs = jax.device_put(x, NamedSharding(mesh, P()))
            loss, (dl, dh, dx) = jax.jit(
                lambda w_, a_, h_, x_: fn(
                    block, head_loss, w_, a_, h_, x_, tgt, **kw))(
                        ws, auxs, hp, xs)
        assert float(loss) == pytest.approx(float(l_ref), abs=1e-5)
        for got, want, name in ((dl, g_ref[0], "dlayers"),
                                (dh, g_ref[1], "dhead"),
                                (dx, g_ref[2], "dx")):
            np.testing.assert_allclose(np.asarray(got),
                                       np.asarray(want),
                                       rtol=1e-5, atol=1e-6,
                                       err_msg=name)

    def test_zb_odd_microbatches_small_m(self):
        """M < 2(S-1) clamps the deferral window; parity must hold."""
        S, M = 2, 2
        mesh = _pipe_only_mesh(S)
        (w, x, tgt, aux, hp, block, head_loss,
         ref_loss) = _toy_problem(S, M)
        l_ref = float(ref_loss(w, hp, x))
        with jax.set_mesh(mesh):
            ws = jax.device_put(w, NamedSharding(mesh, P("pipe")))
            auxs = jax.device_put(aux, NamedSharding(mesh, P("pipe")))
            loss, _ = jax.jit(lambda w_, a_, h_, x_: pipeline_zb_grads(
                block, head_loss, w_, a_, h_, x_, tgt))(
                    ws, auxs, hp, x)
        assert float(loss) == pytest.approx(l_ref, abs=1e-5)


# ------------------------------------------------------ engine-level
class TestGPT2PipeEnginePP2:
    """End-to-end pp=2 engine parity on the pipe-only mesh: the
    tier-1-runnable slice of what test_pipe.py's slow xfail tests cover
    at data > 1."""

    def _run(self, model_cls, pipe, sched=None, batches=2):
        import deepspeed_tpu
        from deepspeed_tpu.models import GPT2, GPT2Pipe  # noqa: F401
        from deepspeed_tpu.models.gpt2 import GPT2Config
        cfg = GPT2Config(n_layer=2, n_head=4, d_model=64, max_seq_len=32,
                         vocab_size=256, dtype="float32", remat=True,
                         pipe_microbatches=4)
        groups.reset()
        topo = groups.initialize(
            TopologyConfig(pipe_parallel_size=pipe,
                           data_parallel_size=1),
            devices=jax.devices()[:max(pipe, 1)], force=True)
        conf = {"train_micro_batch_size_per_gpu": 8,
                "gradient_accumulation_steps": 1,
                "steps_per_print": 0,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 0}}
        if sched:
            conf["pipeline"] = {"schedule": sched}
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model_cls(cfg), topology=topo, config=conf)
        ids = np.random.RandomState(0).randint(
            0, 256, (batches, 8, 32)).astype(np.int32)
        return engine, [float(engine.train_batch({"input_ids": b}))
                        for b in ids]

    def test_zb_engine_matches_dense(self):
        from deepspeed_tpu.models import GPT2, GPT2Pipe
        _, ref = self._run(GPT2, 1)
        engine, zb = self._run(GPT2Pipe, 2, "zb")
        np.testing.assert_allclose(zb, ref, rtol=2e-4)
        rep = engine.pipeline_report()
        assert rep["schedule"] == "zb" and rep["stages"] == 2
        assert rep["bubble_pct"] < rep["gpipe_bubble_pct"]

    def test_verify_report_has_pipeline_and_rotation(self):
        from deepspeed_tpu.models import GPT2Pipe
        engine, _ = self._run(GPT2Pipe, 2, "zb", batches=1)
        ids = np.random.RandomState(1).randint(
            0, 256, (8, 32)).astype(np.int32)
        rep = engine.verify_comm_overlap({"input_ids": ids})
        # the steady-state stage rotation is IN the scan loop
        assert rep["in_loop_by_op"].get("collective-permute", 0) >= 1
        assert "host_copies" in rep
        p = rep["pipeline"]
        assert p["bubble_pct"] < p["gpipe_bubble_pct"]


# -------------------------------------------------------- host staging
class TestHostStage:
    def test_platform_contract(self):
        default, host = host_stage.memory_kinds()
        if host is None:
            assert not host_stage.available()
            x = jnp.ones((4,))
            # identity degradation: same value, usable under jit
            np.testing.assert_array_equal(
                np.asarray(host_stage.to_host(x)), np.asarray(x))
            y = jax.jit(lambda v: host_stage.to_device(
                host_stage.to_host(v)) * 2)(x)
            np.testing.assert_array_equal(np.asarray(y), 2 * np.ones(4))
        else:
            assert host != default
            assert host_stage.available() == \
                (host_stage.to_host is not None)

    def test_with_host_memory_kind_passthrough_on_single_space(self):
        mesh = _pipe_only_mesh(2)
        sh = NamedSharding(mesh, P())
        out = host_stage.with_host_memory_kind(sh)
        if host_stage.host_memory_kind() is None:
            assert out is sh
        else:
            assert out.memory_kind == host_stage.host_memory_kind()

    def test_offload_policy_degrades_cleanly(self):
        from deepspeed_tpu.runtime.activation_checkpointing import (
            checkpointing as ckpt)
        pol = ckpt.offload_policy()
        if host_stage.host_memory_kind() is None:
            assert pol is None
            # cpu_checkpointing falls back to the remat policy
            assert ckpt.resolve_policy("nothing_saveable",
                                       cpu_checkpointing=True) is not None
        else:
            assert pol is not None


# ------------------------------------------------------- 13B tracing
class Test13BConfig:
    def test_13b_traces_pp2_zb_with_offload(self):
        """The 13B point traces (shape-level) at pp=2 under the
        zero-bubble schedule with activation offload requested — the
        'traces' half of the acceptance bar; the 'runs' half is the
        multichip artifact's pipe row and the probe's offload A/B
        (real byte movement needs a backend with a host memory kind;
        on CPU the staging is identity by design)."""
        import types
        from dataclasses import replace
        from deepspeed_tpu.models import GPT2Pipe
        from deepspeed_tpu.models.gpt2 import PRESETS
        cfg = replace(PRESETS["13B"], dtype="bfloat16", remat=True,
                      pipe_microbatches=4, use_flash_attention=False)
        assert cfg.num_params() > 12e9
        model = GPT2Pipe(cfg)
        model._pipe_cfg = types.SimpleNamespace(
            schedule="zb", micro_batches=4, offload_activations=True,
            offload_moments=False, offload_double_buffer=True)
        groups.reset()
        topo = groups.initialize(
            TopologyConfig(pipe_parallel_size=2, data_parallel_size=1),
            devices=jax.devices()[:2], force=True)
        ids = jax.ShapeDtypeStruct((8, cfg.max_seq_len), jnp.int32)
        with jax.set_mesh(topo.mesh):
            params = jax.eval_shape(model.init, jax.random.key(0))
            out = jax.eval_shape(
                lambda p, i: model.loss(p, {"input_ids": i},
                                        rng=jax.random.key(1)),
                params, ids)
        assert out.shape == () and out.dtype == jnp.float32

    def test_hbm_fit_heuristic_flags_13b_on_small_chip(self):
        """The offload 'auto' decision chain: a 13B state estimate
        does not fit a 16 GB chip at pp=2, so with a host memory kind
        present 'auto' turns offload on; an unknown budget never
        does."""
        from deepspeed_tpu.runtime.config import PipelineConfig
        p = PipelineConfig()
        n = 12.85e9
        est = n * (2 + 4) / 2 + n * 12 / 2   # bf16+fp32grad, fp32 opt
        hbm = 16 << 30
        assert not p.hbm_fits(est, hbm)
        assert p.resolve_offload_activations(
            True, pipe_world=2, est_state_bytes=est, hbm_bytes=hbm)
        # unknown HBM -> fits -> auto stays off; unavailable -> off
        assert not p.resolve_offload_activations(
            True, pipe_world=2, est_state_bytes=est, hbm_bytes=None)
        assert not p.resolve_offload_activations(
            False, pipe_world=2, est_state_bytes=est, hbm_bytes=hbm)


# ------------------------------------------------- flight recorder pp
class TestPipeRestoreFlightRecorder:
    def test_pp2_restore_after_reshape_recorded(self, tmp_path):
        """Save under dp=1, restore onto a pp=2 topology: the flight
        recorder must carry the reshape (with the pp>1 topology) and
        the restore tier — the record a post-restore crash dump needs."""
        import deepspeed_tpu
        from deepspeed_tpu.models import GPT2, GPT2Pipe
        from deepspeed_tpu.models.gpt2 import GPT2Config
        cfg = GPT2Config(n_layer=2, n_head=4, d_model=64,
                         max_seq_len=32, vocab_size=256,
                         dtype="float32", remat=False,
                         pipe_microbatches=2)
        base = {"train_micro_batch_size_per_gpu": 4,
                "gradient_accumulation_steps": 1,
                "steps_per_print": 0,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 0},
                "telemetry": {"enabled": True, "interval_steps": 1}}
        ids = np.random.RandomState(0).randint(
            0, 256, (4, 32)).astype(np.int32)
        groups.reset()
        topo = groups.initialize(
            TopologyConfig(data_parallel_size=1),
            devices=jax.devices()[:1], force=True)
        e1, _, _, _ = deepspeed_tpu.initialize(
            model=GPT2(cfg), topology=topo, config=base)
        e1.train_batch({"input_ids": ids})
        e1.save_checkpoint(str(tmp_path))

        groups.reset()
        topo2 = groups.initialize(
            TopologyConfig(pipe_parallel_size=2, data_parallel_size=1),
            devices=jax.devices()[:2], force=True)
        e2, _, _, _ = deepspeed_tpu.initialize(
            model=GPT2Pipe(cfg), topology=topo2,
            config={**base, "pipeline": {"schedule": "zb"}})
        path, _ = e2.load_checkpoint(str(tmp_path))
        assert path is not None
        events = e2.telemetry.flight.events()
        kinds = [e["kind"] for e in events]
        assert "restore" in kinds
        reshapes = [e for e in events if e["kind"] == "reshape"]
        assert reshapes, kinds
        assert reshapes[-1]["current"]["pipe"] == 2
        # and the pp=2 engine still trains after the reshaped restore
        loss = float(e2.train_batch({"input_ids": ids}))
        assert np.isfinite(loss)
