"""Universal checkpoint + AutoTP tests (reference tests/unit/checkpoint/
+ module_inject coverage)."""

import json
import os

import numpy as np
import jax
import pytest

import deepspeed_tpu
from deepspeed_tpu.checkpoint import (consolidate_to_fp32, ds_to_universal,
                                      inspect_checkpoint, load_consolidated,
                                      load_universal_param)
from deepspeed_tpu.models import GPT2, GPT2Config
from deepspeed_tpu.module_inject import AutoTP, autotp_partition_specs
from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.groups import TopologyConfig

# compile-heavy: excluded from the fast core set (pytest -m 'not slow')
pytestmark = pytest.mark.slow



TINY = GPT2Config(n_layer=2, n_head=2, d_model=32, max_seq_len=32,
                  vocab_size=64, remat=False, dtype="float32")


@pytest.fixture()
def ckpt_dir(tmp_path):
    groups.reset()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2(TINY),
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 2}, "steps_per_print": 0})
    data = np.zeros((engine.config.train_batch_size, 16), np.int32)
    engine.train_batch({"input_ids": data})
    engine.save_checkpoint(str(tmp_path / "ck"))
    return str(tmp_path / "ck"), engine


class TestUniversalCheckpoint:
    def test_consolidate_fp32(self, ckpt_dir, tmp_path):
        ck, engine = ckpt_dir
        out = str(tmp_path / "fp32.npz")
        n = consolidate_to_fp32(ck, out)
        assert n == TINY.num_params()
        weights = load_consolidated(out)
        trained = jax.device_get(engine.state["master"])
        np.testing.assert_allclose(weights["wte"],
                                   np.asarray(trained["wte"], np.float32),
                                   rtol=1e-6)
        # no optimizer state leaked
        assert all(not k.startswith("opt") for k in weights)

    def test_ds_to_universal_and_stream(self, ckpt_dir, tmp_path):
        ck, engine = ckpt_dir
        out = str(tmp_path / "uni")
        index = ds_to_universal(ck, out)
        assert "master/wte" in index
        one = load_universal_param(out, "master/wte")
        assert one.shape == (64, 32)
        with pytest.raises(KeyError):
            load_universal_param(out, "master/nope")
        idx = json.loads(open(os.path.join(out, "index.json")).read())
        assert idx["extra"]["zero_stage"] == 2

    def test_inspect(self, ckpt_dir, capsys):
        ck, _ = ckpt_dir
        total = inspect_checkpoint(ck)
        out = capsys.readouterr().out
        assert "master/wte" in out and total > 0

    def test_cross_topology_reshard(self, ckpt_dir, tmp_path):
        """Save under dp=8/stage2, load under tp=4/stage3 — the universal
        property the reference needs offline conversion for."""
        ck, engine = ckpt_dir
        ref = jax.device_get(engine.state["master"])
        groups.reset()
        topo = groups.initialize(TopologyConfig(tensor_parallel_size=4))
        engine2, _, _, _ = deepspeed_tpu.initialize(
            model=GPT2(TINY), topology=topo,
            config={"train_micro_batch_size_per_gpu": 1,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 3},
                    "steps_per_print": 0})
        path, _ = engine2.load_checkpoint(ck)
        assert path
        got = jax.device_get(engine2.state["master"])
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6), got, ref)


class TestAutoTP:
    def test_heuristics_on_hf_style_names(self):
        params = {
            "model": {
                "embed_tokens": np.zeros((64, 32)),
                "layers": {
                    "q_proj": np.zeros((32, 32)),
                    "k_proj": np.zeros((32, 16)),
                    "o_proj": np.zeros((32, 32)),
                    "gate_proj": np.zeros((32, 128)),
                    "down_proj": np.zeros((128, 32)),
                    "input_layernorm": np.zeros((32,)),
                },
                "lm_head": np.zeros((64, 32)),
            }
        }
        specs = autotp_partition_specs(params, tp_size=4)
        lay = specs["model"]["layers"]
        assert lay["q_proj"][-1] == "tensor"       # column
        assert lay["gate_proj"][-1] == "tensor"
        assert lay["o_proj"][-2] == "tensor"       # row
        assert lay["down_proj"][-2] == "tensor"
        assert all(e is None for e in lay["input_layernorm"])
        assert all(e is None for e in specs["model"]["embed_tokens"])

    def test_indivisible_replicates(self):
        params = {"q_proj": np.zeros((32, 30))}   # 30 % 4 != 0
        specs = autotp_partition_specs(params, tp_size=4)
        assert all(e is None for e in specs["q_proj"])

    def test_autotp_drives_engine(self):
        """An arbitrary (non-zoo) param tree + AutoTP trains under TP."""
        from jax.sharding import NamedSharding
        groups.reset()
        topo = groups.initialize(TopologyConfig(tensor_parallel_size=2))

        class FlatModel:
            config = TINY

            def init(self, rng):
                return GPT2(TINY).init(rng)

            def loss(self, params, batch, **kw):
                return GPT2(TINY).loss(params, batch, **kw)

            def partition_specs(self, topology=None):
                import jax as _jax
                abstract = _jax.eval_shape(self.init, _jax.random.key(0))
                return AutoTP(abstract).partition_specs(topology)

        engine, _, _, _ = deepspeed_tpu.initialize(
            model=FlatModel(), topology=topo,
            config={"train_micro_batch_size_per_gpu": 2,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                    "steps_per_print": 0})
        data = np.zeros((engine.config.train_batch_size, 16), np.int32)
        l0 = float(engine.train_batch({"input_ids": data}))
        l1 = float(engine.train_batch({"input_ids": data}))
        assert l1 < l0
        # at least one tensor actually sharded on 'tensor'
        report = AutoTP(jax.eval_shape(
            FlatModel().init, jax.random.key(0))).report(topo)
        assert any(v != "replicate" for v in report.values()), report

    def test_report(self):
        params = {"q_proj": np.zeros((32, 32)), "norm": np.zeros((32,))}
        rep = AutoTP(params).report()
        assert rep == {"q_proj": "replicate", "norm": "replicate"}  # tp=1
