"""Prefix cache (tier-1): allocator refcount hardening, radix-tree
match/claim/insert/evict invariants, the release-path exactly-once
audit, engine greedy byte-identity with the cache on vs off (shared
prefixes, divergent prompts, partial-tail CoW), eviction-under-pressure
admission, the sliding-window loud refusal, and warm/cold winner-cache
dispatch for the prefix_cache policy op (a cold "auto" engine is
byte-identical to prefix_cache=False)."""

import os

import numpy as np
import pytest

import jax

from deepspeed_tpu.autotuning import KernelCache, kernel_dispatch
from deepspeed_tpu.inference.v2 import InferenceEngineV2, PrefixCache
from deepspeed_tpu.inference.v2.blocked_allocator import BlockedAllocator
from deepspeed_tpu.inference.v2.prefix_cache import (PREFIX_CACHE_DEFAULTS,
                                                     prefix_cache_bucket)
from deepspeed_tpu.models import GPT2, GPT2Config
from deepspeed_tpu.utils import groups


@pytest.fixture(autouse=True)
def _pristine_dispatch(tmp_path, monkeypatch):
    """Private winner cache + reset process-global dispatch state."""
    monkeypatch.setenv("DSTPU_AUTOTUNE_CACHE",
                       str(tmp_path / "kernel_autotune.json"))
    monkeypatch.delenv("DSTPU_AUTOTUNE", raising=False)
    kernel_dispatch.reset()
    yield
    kernel_dispatch.reset()


# ---------------------------------------------------------------------------
# allocator hardening (satellite: double-free / free-while-referenced raise)
# ---------------------------------------------------------------------------

class TestAllocatorHardening:
    def test_double_free_raises(self):
        a = BlockedAllocator(8)
        blocks = a.allocate(2)
        a.free(blocks)
        with pytest.raises(ValueError, match="double-free"):
            a.free(blocks)

    def test_duplicate_in_one_free_raises(self):
        a = BlockedAllocator(8)
        (b,) = a.allocate(1)
        with pytest.raises(ValueError, match="double-free"):
            a.free([b, b])

    def test_free_while_referenced_raises(self):
        a = BlockedAllocator(8)
        (b,) = a.allocate(1)
        a.ref(b)                       # shared (e.g. adopted by the tree)
        with pytest.raises(ValueError, match="still referenced"):
            a.free([b])
        a.unref(b)
        a.free([b])                    # sole ownership again: fine

    def test_free_validates_whole_list_before_mutating(self):
        a = BlockedAllocator(8)
        good, = a.allocate(1)
        with pytest.raises(ValueError):
            a.free([good, 999])        # bad id later in the list
        assert a.refcount(good) == 1   # nothing half-applied
        a.free([good])

    def test_unref_past_zero_raises(self):
        a = BlockedAllocator(8)
        (b,) = a.allocate(1)
        assert a.unref(b) is True      # freed at zero
        with pytest.raises(ValueError, match="double-free"):
            a.unref(b)

    def test_ref_of_unallocated_raises(self):
        a = BlockedAllocator(8)
        with pytest.raises(ValueError, match="not allocated"):
            a.ref(3)

    def test_scratch_block_is_reserved(self):
        a = BlockedAllocator(4)
        assert BlockedAllocator.SCRATCH not in a.allocate(3)
        with pytest.raises(ValueError, match="scratch"):
            a.free([BlockedAllocator.SCRATCH])

    def test_allocate_reclaims_from_evictor_under_pressure(self):
        class Evictor:
            def __init__(self, alloc, held):
                self.alloc, self.held = alloc, held

            @property
            def evictable_blocks(self):
                return len(self.held)

            def evict(self, n):
                for _ in range(min(n, len(self.held))):
                    self.alloc.unref(self.held.pop())

        a = BlockedAllocator(6)        # 5 usable
        held = a.allocate(5)           # pool exhausted, held by "tree"
        a.set_evictor(Evictor(a, held))
        assert a.free_blocks == 0 and a.available_blocks == 5
        got = a.allocate(3)            # must evict 3, then succeed
        assert len(got) == 3 and a.available_blocks == 2
        with pytest.raises(RuntimeError, match="out of KV blocks"):
            a.allocate(5)              # 2 evictable + 0 free < 5


# ---------------------------------------------------------------------------
# radix tree: match / claim / insert / evict
# ---------------------------------------------------------------------------

BS = 4


def _mk(num_blocks=32, mm=1, max_blocks=0):
    a = BlockedAllocator(num_blocks)
    return a, PrefixCache(a, BS, min_match_blocks=mm,
                          max_blocks=max_blocks)


def _toks(*ints):
    return np.asarray(ints, np.int32)


class TestRadixTree:
    def test_empty_tree_is_a_miss(self):
        _, c = _mk()
        m = c.match(_toks(*range(10)))
        assert not m.hit and m.blocks == [] and m.cached_len == 0

    def test_full_block_match_after_release(self):
        a, c = _mk()
        toks = np.arange(3 * BS, dtype=np.int32)
        blocks = a.allocate(3)
        c.release(toks, blocks)
        assert c.tree_blocks == 3
        # tree holds its own refs; the sequence's were dropped
        assert all(a.refcount(b) == 1 for b in blocks)
        m = c.match(np.concatenate([toks, _toks(77)]))
        assert m.blocks == blocks and m.cached_len == 3 * BS
        assert m.cow_src is None       # divergent token, no partial tail

    def test_last_prompt_token_is_always_recomputed(self):
        a, c = _mk()
        toks = np.arange(3 * BS, dtype=np.int32)
        c.release(toks, a.allocate(3))
        # identical prompt: the T-1 cap turns the last block into a
        # BS-1 partial tail served by CoW, never a full-block match
        m = c.match(toks)
        assert len(m.blocks) == 2 and m.cow_plen == BS - 1
        assert m.cached_len == 3 * BS - 1 == len(toks) - 1

    def test_partial_tail_cow_on_mid_block_divergence(self):
        a, c = _mk()
        toks = np.arange(2 * BS, dtype=np.int32)
        blocks = a.allocate(2)
        c.release(toks, blocks)
        probe = np.concatenate([toks[:BS + 2], _toks(90, 91, 92, 93)])
        m = c.match(probe)
        assert m.blocks == blocks[:1] and m.cow_src == blocks[1]
        assert m.cow_plen == 2 and m.cached_len == BS + 2

    def test_min_match_blocks_gates_short_hits(self):
        a, c = _mk(mm=2)
        c.release(np.arange(BS, dtype=np.int32), a.allocate(1))
        m = c.match(np.concatenate([np.arange(BS, dtype=np.int32),
                                    _toks(50, 51)]))
        assert not m.hit and m.blocks == [] and m.cow_src is None

    def test_claim_refs_blocks_and_cow_release_drops_source(self):
        a, c = _mk()
        toks = np.arange(2 * BS, dtype=np.int32)
        blocks = a.allocate(2)
        c.release(toks, blocks)
        m = c.match(toks)              # 1 full block + BS-1 CoW tail
        c.claim(m)
        assert a.refcount(blocks[0]) == 2     # tree + sequence
        assert a.refcount(blocks[1]) == 2     # tree + CoW claim
        c.cow_release(m.cow_src)
        assert a.refcount(blocks[1]) == 1 and c.cow_copies == 1
        assert c.hits == 1 and c.lookups == 1

    def test_match_is_pure(self):
        a, c = _mk()
        toks = np.arange(2 * BS, dtype=np.int32)
        blocks = a.allocate(2)
        c.release(toks, blocks)
        before = [a.refcount(b) for b in blocks]
        c.match(toks)                  # admission probe, not claimed
        assert [a.refcount(b) for b in blocks] == before
        assert c.lookups == 0 and c.hits == 0

    def test_insert_dedups_against_existing_nodes(self):
        a, c = _mk()
        toks = np.arange(2 * BS, dtype=np.int32)
        first = a.allocate(2)
        c.release(toks, first)
        dup = a.allocate(2)            # a second seq recomputed the same KV
        c.release(toks, dup)
        assert c.tree_blocks == 2      # nothing adopted twice
        assert all(a.refcount(b) == 0 for b in dup)   # dup died at unref
        assert all(a.refcount(b) == 1 for b in first)

    def test_eviction_is_lru_over_unreferenced_leaves(self):
        a, c = _mk()
        t1 = np.arange(2 * BS, dtype=np.int32)
        t2 = np.concatenate([t1[:BS], _toks(60, 61, 62, 63)])
        c.release(t1, a.allocate(2))   # chain: n0 -> n1
        c.release(t2, a.allocate(2))   # n0 -> n2 (n0 deduped, older n1)
        assert c.tree_blocks == 3 and c.evictable_blocks == 3
        c.evict(1)
        # leaves only: the shared parent n0 (has children) survives
        assert c.tree_blocks == 2
        m = c.match(np.concatenate([t1[:BS], _toks(99, 98)]))
        assert len(m.blocks) == 1     # parent still matchable
        # a claimed leaf is pinned; eviction walks past it
        m2 = c.match(np.concatenate([t2, _toks(7)]))
        c.claim(m2)
        assert c.evictable_blocks == 0  # every remaining node on t2 path
        assert c.evict(5) == 0

    def test_max_blocks_caps_tree_growth(self):
        a, c = _mk(mm=1, max_blocks=2)
        c.release(np.arange(4 * BS, dtype=np.int32), a.allocate(4))
        assert c.tree_blocks <= 2


class TestReleaseExactlyOnce:
    def test_release_unrefs_every_sequence_block_once(self):
        a, c = _mk()
        counts = {}
        inner = a.unref

        def audited(b):
            counts[b] = counts.get(b, 0) + 1
            return inner(b)

        a.unref = audited
        toks = np.arange(2 * BS, dtype=np.int32)
        first = a.allocate(3)          # 2 full + 1 partial tail block
        c.release(np.concatenate([toks, _toks(5, 6)]), first)
        for b in first:
            assert counts.get(b, 0) == 1, f"block {b}: {counts}"
        # duplicate-content release: adopted nothing, still exactly once
        # (a block the first release freed may be REallocated here — a
        # new ownership epoch, so the audit restarts)
        counts.clear()
        dup = a.allocate(3)
        c.release(np.concatenate([toks, _toks(5, 6)]), dup)
        for b in dup:
            assert counts.get(b, 0) == 1, f"block {b}: {counts}"
        # pool accounting closes: free + tree == total
        assert a.free_blocks + c.tree_blocks == a.total_blocks


# ---------------------------------------------------------------------------
# engine end-to-end: greedy byte-identity, eviction, refusals
# ---------------------------------------------------------------------------

_CFG = GPT2Config(n_layer=2, n_head=4, d_model=64, max_seq_len=128,
                  vocab_size=256, remat=False, dtype="float32")
_PARAMS = None


def _params():
    global _PARAMS
    if _PARAMS is None:
        _PARAMS = GPT2(_CFG).init(jax.random.key(0))
    return _PARAMS


_BASE = {"dtype": "float32", "kv_block_size": 8, "prompt_bucket": 16,
         "max_batch_size": 4, "splitfuse_tokens": 16,
         "decode_steps_per_dispatch": 2,   # small unroll = fast compiles
         "prefix_cache_min_match": 1}


def _engine(**kw):
    groups.reset()
    return InferenceEngineV2(GPT2(_CFG), params=_params(),
                             config=dict(_BASE, **kw))


def _run_sequential(eng, prompts, max_new=6):
    """One prompt at a time, each to completion — later prompts see the
    prefixes earlier ones released into the cache."""
    out = []
    for p in prompts:
        out.append(eng.generate_all([p], max_new_tokens=max_new)[0])
    return out


@pytest.fixture(scope="module")
def off_ref():
    """ONE shared cache-off reference engine for every identity test:
    with the cache off a finished request leaves no state behind, so
    its greedy outputs depend only on the prompt — safe to reuse the
    compiled programs across scenarios instead of paying a fresh
    engine compile per test."""
    eng = _engine(prefix_cache=False)

    def run(prompts, max_new=6):
        return _run_sequential(eng, prompts, max_new)

    return run


class TestEngineGreedyIdentity:
    def _identity(self, prompts, off_ref, **on_kw):
        on = _engine(prefix_cache=True, **on_kw)
        got = _run_sequential(on, prompts)
        for a, b in zip(got, off_ref(prompts)):
            np.testing.assert_array_equal(a, b)
        return on.prefix_cache.stats()

    def test_shared_prefix_hits_and_stays_byte_identical(self, off_ref):
        rs = np.random.RandomState(0)
        template = rs.randint(0, 256, (17,)).astype(np.int32)
        prompts = [np.concatenate([template,
                                   rs.randint(0, 256, (6,)).astype(np.int32)])
                   for _ in range(3)]
        s = self._identity(prompts, off_ref)
        assert s["hits"] >= 2 and s["cached_tokens"] >= 2 * 16

    def test_divergent_prompts_stay_byte_identical(self, off_ref):
        rs = np.random.RandomState(1)
        prompts = [rs.randint(0, 256, (n,)).astype(np.int32)
                   for n in (5, 21, 33)]
        s = self._identity(prompts, off_ref)
        assert s["lookups"] == 3       # every admission consulted the tree

    def test_partial_tail_cow_byte_identical(self, off_ref):
        rs = np.random.RandomState(2)
        p1 = rs.randint(0, 256, (20,)).astype(np.int32)
        # diverges 4 tokens into p1's second block -> CoW slice copy
        p2 = np.concatenate([p1[:12], rs.randint(0, 256, (8,))]) \
            .astype(np.int32)
        s = self._identity([p1, p2], off_ref)
        assert s["cow_copies"] == 1 and s["hits"] == 1

    def test_identical_prompt_resubmitted_byte_identical(self, off_ref):
        # the T-1 cap end-to-end: the whole prompt is cached except the
        # recomputed last token, and decode still matches exactly
        rs = np.random.RandomState(3)
        p = rs.randint(0, 256, (24,)).astype(np.int32)
        s = self._identity([p, p], off_ref)
        assert s["hits"] == 1 and s["cow_copies"] == 1
        assert s["cached_tokens"] == len(p) - 1

    def test_legacy_bucketed_prefill_path_byte_identical(self):
        # splitfuse off: misses keep the legacy whole-prompt prefill,
        # hits route through the chunk path with an offset — outputs
        # must agree with the cache-off engine either way
        rs = np.random.RandomState(4)
        template = rs.randint(0, 256, (17,)).astype(np.int32)
        prompts = [np.concatenate([template,
                                   rs.randint(0, 256, (5,)).astype(np.int32)])
                   for _ in range(2)]
        on = _engine(prefix_cache=True, splitfuse_tokens=0)
        got = _run_sequential(on, prompts)
        off = _engine(prefix_cache=False, splitfuse_tokens=0)
        ref = _run_sequential(off, prompts)
        for a, b in zip(got, ref):
            np.testing.assert_array_equal(a, b)
        assert on.prefix_cache.stats()["hits"] >= 1


class TestEvictionUnderPressure:
    def test_full_pool_of_cached_leaves_still_admits(self, off_ref):
        rs = np.random.RandomState(5)
        p1 = rs.randint(0, 256, (40,)).astype(np.int32)
        p2 = rs.randint(0, 256, (40,)).astype(np.int32)
        eng = _engine(prefix_cache=True, num_kv_blocks=8)  # 7 usable
        got = _run_sequential(eng, [p1, p2])
        s = eng.prefix_cache.stats()
        # p1's release filled most of the pool with tree blocks; p2
        # (unshared, needs 6 of the 7) could only admit by evicting
        assert s["evicted_blocks"] >= 1
        # pool size only gates admission — with one request in flight
        # at a time the greedy outputs match the shared reference
        for a, b in zip(got, off_ref([p1, p2])):
            np.testing.assert_array_equal(a, b)
        # exactly-once audit at engine scale: after everything retired,
        # every surviving ref belongs to the tree and accounting closes
        alloc = eng.state_mgr.allocator
        assert alloc.free_blocks + eng.prefix_cache.tree_blocks \
            == alloc.total_blocks


_WCFG = GPT2Config(n_layer=2, n_head=4, d_model=64, max_seq_len=128,
                   vocab_size=256, remat=False, dtype="float32",
                   attn_layer_windows=(8, 8))


class TestRefusals:
    def test_sliding_window_model_refuses_forced_cache(self):
        groups.reset()
        with pytest.raises(ValueError, match="sliding-window"):
            InferenceEngineV2(GPT2(_WCFG),
                              config=dict(_BASE, prefix_cache=True))

    def test_sliding_window_model_resolves_auto_off(self):
        groups.reset()
        eng = InferenceEngineV2(GPT2(_WCFG),
                                config=dict(_BASE, prefix_cache="auto"))
        assert eng.prefix_cache is None

    def test_kv_host_offload_is_incompatible(self):
        with pytest.raises(ValueError, match="kv_host_offload"):
            _engine(prefix_cache=True, kv_host_offload=True,
                    device_kv_blocks=8)

    def test_config_junk_rejected(self):
        with pytest.raises(ValueError):
            _engine(prefix_cache="yes-please")
        with pytest.raises(ValueError):
            _engine(prefix_cache=True, prefix_cache_min_match=0)
        with pytest.raises(ValueError):
            _engine(prefix_cache=True, prefix_cache_blocks=-1)


# ---------------------------------------------------------------------------
# warm/cold winner-cache dispatch (test_paged_kernel.py style)
# ---------------------------------------------------------------------------

def _lower_step_programs(eng):
    """Byte-level text of the engine's OWN jitted decode + chunk-only
    programs, lowered with fixed shapes."""
    B = eng.config.max_batch_size
    MB = eng.max_blocks_per_seq
    i32, f32 = np.int32, np.float32
    z = np.zeros
    rng = jax.random.key(0)
    with jax.set_mesh(eng.mesh):
        dec = eng._get_decode().lower(
            eng.params, eng.cache, z((B,), i32), z((B,), i32),
            z((B, MB), i32), rng, z((B,), f32), z((B,), i32),
            True).as_text()
        C = eng.config.splitfuse_tokens
        chk = eng._get_chunk_only().lower(
            eng.params, eng.cache, z((1, C), i32), z((C,), i32),
            z((C,), i32), i32(0), i32(0), z((MB,), i32), f32(0),
            i32(0), rng, True).as_text()
    return dec, chk


class TestPrefixDispatchColdWarm:
    def test_cold_auto_is_byte_identical_to_disabled(self):
        """Acceptance: prefix_cache="auto" on a cold winner cache must
        not perturb the engine — no PrefixCache constructed, and the
        compiled step programs lower byte-identical to
        prefix_cache=False."""
        kernel_dispatch.configure(mode="cache_only")   # empty cache
        auto = _engine(prefix_cache="auto", prefix_cache_min_match="auto")
        assert auto.prefix_cache is None
        t_auto = _lower_step_programs(auto)
        kernel_dispatch.configure(mode="cache_only")
        off = _engine(prefix_cache=False)
        assert t_auto == _lower_step_programs(off)

    def test_warm_cache_enables_with_cached_policy(self):
        path = os.environ["DSTPU_AUTOTUNE_CACHE"]
        dk = kernel_dispatch.device_kind()
        NB = 1 + _BASE["max_batch_size"] * (128 // _BASE["kv_block_size"])
        c = KernelCache()
        c.put(dk, "prefix_cache",
              prefix_cache_bucket(_BASE["max_batch_size"], NB,
                                  _BASE["kv_block_size"]), "float32",
              {"enabled": 1, "min_match_blocks": 2,
               "evict_watermark_pct": 25})
        c.save(path)
        kernel_dispatch.configure(mode="cache_only")
        eng = _engine(prefix_cache="auto", prefix_cache_min_match="auto")
        assert eng.prefix_cache is not None
        assert eng.prefix_cache.min_match_blocks == 2
        assert eng.prefix_cache.evict_watermark_pct == 25

    def test_explicit_false_never_consults_dispatch(self):
        kernel_dispatch.configure(mode="cache_only")
        _engine(prefix_cache=False)
        assert not any("prefix_cache" in str(k)
                       for k in kernel_dispatch._STATE["resolved"])

    def test_cold_defaults_are_the_hand_set_values(self):
        assert PREFIX_CACHE_DEFAULTS == {"enabled": 0,
                                         "min_match_blocks": 1,
                                         "evict_watermark_pct": 0}
