"""Paged-serving fast path (tier-1): the chunked-prefill Pallas kernel
vs the dense-gather reference (interpret mode), the compiled chunk
program's no-dense-gather guarantee, engine split-fuse greedy identity
with the kernel on vs off, warm/cold winner-cache dispatch HLO identity
for the serving autotune ops, and mixtral's ragged-EP serving routing."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.autotuning import KernelCache, kernel_dispatch
from deepspeed_tpu.models import GPT2, GPT2Config
from deepspeed_tpu.ops.pallas._common import (paged_chunk_bucket,
                                              paged_decode_bucket)
from deepspeed_tpu.ops.pallas.paged_attention import (
    paged_chunk_attention, paged_chunk_attention_reference)
from deepspeed_tpu.utils import groups


@pytest.fixture(autouse=True)
def _pristine_dispatch(tmp_path, monkeypatch):
    """Private winner cache + reset process-global dispatch state."""
    monkeypatch.setenv("DSTPU_AUTOTUNE_CACHE",
                       str(tmp_path / "kernel_autotune.json"))
    monkeypatch.delenv("DSTPU_AUTOTUNE", raising=False)
    kernel_dispatch.reset()
    yield
    kernel_dispatch.reset()


def _chunk_case(C, H, KVH, d, NB, BS, MB, start, true_len, window,
                block_c, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.key(seed), 4)
    q = jax.random.normal(ks[0], (C, H, d), dtype)
    kc = jax.random.normal(ks[1], (NB, KVH, BS, d), dtype)
    vc = jax.random.normal(ks[2], (NB, KVH, BS, d), dtype)
    tbl = jax.random.randint(ks[3], (MB,), 0, NB, jnp.int32)
    out = paged_chunk_attention(q, kc, vc, tbl, jnp.int32(start),
                                jnp.int32(true_len), window=window,
                                block_c=block_c)
    ref = paged_chunk_attention_reference(
        q, kc, vc, tbl, jnp.int32(start), jnp.int32(true_len),
        window=window)
    tol = dict(rtol=5e-2, atol=5e-2) if dtype == jnp.bfloat16 \
        else dict(rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(out, np.float32)[:true_len],
        np.asarray(ref, np.float32)[:true_len], **tol)


class TestChunkKernelParity:
    """paged_chunk_attention (interpret mode) vs the dense-gather
    reference — the ISSUE-named cases."""

    def test_chunk_mid_sequence(self):
        # chunk starts mid-sequence, not block-aligned, fully real
        _chunk_case(16, 4, 4, 32, 12, 16, 4, start=30, true_len=16,
                    window=0, block_c=8)

    def test_chunk_crossing_block_boundary(self):
        # start + true_len straddles a BS boundary; chunk partly padded
        _chunk_case(16, 4, 4, 32, 12, 16, 4, start=26, true_len=9,
                    window=0, block_c=16)

    def test_sliding_window_layer(self):
        # mistral-style trailing window smaller than the history
        _chunk_case(16, 4, 2, 32, 12, 16, 4, start=33, true_len=16,
                    window=20, block_c=8)

    def test_gqa_heads(self):
        # G = 4 query heads per kv head, bf16 (the serving dtype)
        _chunk_case(16, 8, 2, 64, 12, 16, 4, start=17, true_len=16,
                    window=0, block_c=8, dtype=jnp.bfloat16)

    def test_block_c_padding_and_prefill_start(self):
        # block_c not dividing C (pad rows), and the prefill-shaped
        # start=0 call over the chunk's own blocks
        _chunk_case(20, 8, 2, 32, 12, 16, 4, start=0, true_len=20,
                    window=0, block_c=8)
        _chunk_case(24, 4, 2, 32, 12, 16, 4, start=0, true_len=17,
                    window=0, block_c=128)


_CFG = GPT2Config(n_layer=2, n_head=4, d_model=64, max_seq_len=128,
                  vocab_size=256, remat=False, dtype="float32")


def _abstract_params(model):
    ab = jax.eval_shape(model.init, jax.random.PRNGKey(0))
    return jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype), ab)


def _lower_chunk(model, MB=4, BS=16, NB=9, C=16):
    cfg = model.config
    params = _abstract_params(model)
    cache = {
        "k": [jax.ShapeDtypeStruct((NB, cfg.n_head, BS, cfg.d_head),
                                   jnp.float32)] * cfg.n_layer,
        "v": [jax.ShapeDtypeStruct((NB, cfg.n_head, BS, cfg.d_head),
                                   jnp.float32)] * cfg.n_layer,
    }
    i32 = jnp.int32
    return jax.jit(model.apply_paged_chunk).lower(
        params, jax.ShapeDtypeStruct((1, C), i32), cache,
        jax.ShapeDtypeStruct((C,), i32), jax.ShapeDtypeStruct((C,), i32),
        jax.ShapeDtypeStruct((), i32), jax.ShapeDtypeStruct((), i32),
        jax.ShapeDtypeStruct((MB,), i32)).as_text()


def _lower_decode(model, B=2, MB=4, BS=16, NB=9):
    cfg = model.config
    params = _abstract_params(model)
    cache = {
        "k": [jax.ShapeDtypeStruct((NB, cfg.n_head, BS, cfg.d_head),
                                   jnp.float32)] * cfg.n_layer,
        "v": [jax.ShapeDtypeStruct((NB, cfg.n_head, BS, cfg.d_head),
                                   jnp.float32)] * cfg.n_layer,
    }
    i32 = jnp.int32
    return jax.jit(model.apply_paged_decode).lower(
        params, jax.ShapeDtypeStruct((B,), i32),
        jax.ShapeDtypeStruct((B,), i32), cache,
        jax.ShapeDtypeStruct((B, MB), i32)).as_text()


class TestChunkProgramHLO:
    def test_kernel_path_never_gathers_dense_kv(self):
        """Acceptance: on the kernel path the chunk program no longer
        materializes the (MB, H, BS, hd) table-gather (the dense copy
        that became the (S, H, hd) attention operand). The dense
        variant of the SAME program contains it — proving the probe
        actually detects the gather."""
        MB, BS = 4, 16
        # the dense gather's result type in the lowered text
        sig = f"tensor<{MB}x{_CFG.n_head}x{BS}x{_CFG.d_head}xf32>"

        dense = GPT2(_CFG)
        dense._paged_kernel = False
        dense._paged_block_c = 8
        assert sig in _lower_chunk(dense, MB=MB, BS=BS)

        kern = GPT2(_CFG)
        kern._paged_kernel = True
        kern._paged_block_c = 8
        assert sig not in _lower_chunk(kern, MB=MB, BS=BS)


class TestPagedDispatchHLO:
    """Winner-cache dispatch for the serving ops, same assertion style
    as test_autotune.TestHLOIdentity: warm cache lowers byte-identical
    to the hand-set config; a cold cache is byte-identical to the
    proven defaults (dense chunk off-TPU, kernel decode)."""

    def test_warm_cache_matches_hand_set(self):
        path = os.environ["DSTPU_AUTOTUNE_CACHE"]
        dk = kernel_dispatch.device_kind()
        C, MB, BS, B = 16, 4, 16, 2
        H, hd = _CFG.n_head, _CFG.d_head
        c = KernelCache()
        c.put(dk, "paged_chunk",
              paged_chunk_bucket(C, MB, BS, H, 1, hd), "float32",
              {"mode": "kernel", "block_c": 8})
        c.put(dk, "paged_decode",
              paged_decode_bucket(B, MB, BS, H, 1, hd), "float32",
              {"mode": "kernel"})
        c.save(path)

        kernel_dispatch.configure(mode="cache_only")
        auto = GPT2(_CFG)                      # attrs default to "auto"
        t_auto = (_lower_chunk(auto, MB=MB, BS=BS, C=C),
                  _lower_decode(auto, B=B, MB=MB, BS=BS))
        assert len(kernel_dispatch._STATE["resolved"]) >= 2

        kernel_dispatch.configure(mode="off")
        hand = GPT2(_CFG)
        hand._paged_kernel = True
        hand._paged_block_c = 8
        t_hand = (_lower_chunk(hand, MB=MB, BS=BS, C=C),
                  _lower_decode(hand, B=B, MB=MB, BS=BS))
        assert t_auto == t_hand

    def test_cold_cache_matches_proven_defaults(self):
        from deepspeed_tpu.ops.pallas.paged_attention import (
            paged_chunk_tune_defaults)
        kernel_dispatch.configure(mode="cache_only")   # empty cache
        auto = GPT2(_CFG)
        t_auto = (_lower_chunk(auto), _lower_decode(auto))

        kernel_dispatch.configure(mode="off")
        hand = GPT2(_CFG)
        defaults = paged_chunk_tune_defaults()
        hand._paged_kernel = defaults["mode"] == "kernel"
        hand._paged_block_c = defaults["block_c"]
        t_chunk = _lower_chunk(hand)
        # decode's proven default is the kernel on every backend
        hand_dec = GPT2(_CFG)
        hand_dec._paged_kernel = True
        hand_dec._paged_block_c = defaults["block_c"]
        assert t_auto == (t_chunk, _lower_decode(hand_dec))


class TestEngineKernelOnOff:
    def test_splitfuse_greedy_identical_kernel_on_off(self):
        """Acceptance e2e: the split-fuse engine produces IDENTICAL
        greedy tokens with the paged kernels forced on (chunk +
        prefill + decode through Pallas, interpret mode here) vs forced
        off (dense-gather parity path)."""
        from deepspeed_tpu.inference.v2 import InferenceEngineV2
        params = GPT2(_CFG).init(jax.random.key(0))
        rs = np.random.RandomState(0)
        # < 1 chunk, exactly 1 chunk, several chunks crossing blocks
        prompts = [rs.randint(0, 256, (n,)).astype(np.int32)
                   for n in (5, 16, 37)]
        base = {"dtype": "float32", "kv_block_size": 8,
                "prompt_bucket": 16, "max_batch_size": 4,
                "splitfuse_tokens": 16}

        def run(pk):
            groups.reset()
            eng = InferenceEngineV2(GPT2(_CFG), params=params,
                                    config=dict(base, paged_kernel=pk))
            return eng.generate_all(prompts, max_new_tokens=6)

        on = run(True)
        off = run(False)
        for a, b in zip(on, off):
            np.testing.assert_array_equal(a, b)


class TestMixtralEPRouting:
    def test_serving_programs_route_ragged_ep_alltoall(self):
        """Mixtral with expert_parallel > 1 serves through the manual
        shard_map ragged-EP all_to_all (moe/sharded_moe.py) in BOTH the
        decode and the SplitFuse chunk program — and through the plain
        grouped-GEMM path at ep=1 (trace-level; the e2e greedy parity
        lives in test_inference_v2's slow tier)."""
        from deepspeed_tpu.models.mixtral import Mixtral, MixtralConfig
        from deepspeed_tpu.utils.groups import TopologyConfig
        mcfg = MixtralConfig(n_layer=2, n_head=4, n_kv_heads=2,
                             d_model=64, max_seq_len=128, vocab_size=512,
                             remat=False, num_experts=4, moe_top_k=2,
                             dtype="float32")
        NB, BS, MB, B, C = 9, 16, 4, 2, 16
        i32 = jnp.int32

        def lower(ep):
            groups.reset()
            topo = groups.initialize(TopologyConfig(
                expert_parallel_size=ep))
            model = Mixtral(mcfg)
            params = _abstract_params(model)
            cache = {
                "k": [jax.ShapeDtypeStruct(
                    (NB, mcfg.n_kv_heads, BS, mcfg.d_head),
                    jnp.float32)] * mcfg.n_layer,
                "v": [jax.ShapeDtypeStruct(
                    (NB, mcfg.n_kv_heads, BS, mcfg.d_head),
                    jnp.float32)] * mcfg.n_layer,
            }
            with jax.set_mesh(topo.mesh):
                dec = jax.jit(model.apply_paged_decode).lower(
                    params, jax.ShapeDtypeStruct((B,), i32),
                    jax.ShapeDtypeStruct((B,), i32), cache,
                    jax.ShapeDtypeStruct((B, MB), i32)).as_text()
                chk = jax.jit(model.apply_paged_chunk).lower(
                    params, jax.ShapeDtypeStruct((1, C), i32), cache,
                    jax.ShapeDtypeStruct((C,), i32),
                    jax.ShapeDtypeStruct((C,), i32),
                    jax.ShapeDtypeStruct((), i32),
                    jax.ShapeDtypeStruct((), i32),
                    jax.ShapeDtypeStruct((MB,), i32)).as_text()
            groups.reset()
            return dec, chk

        dec_ep, chk_ep = lower(2)
        assert "all_to_all" in dec_ep or "all-to-all" in dec_ep
        assert "all_to_all" in chk_ep or "all-to-all" in chk_ep
        dec_1, chk_1 = lower(1)
        assert "all_to_all" not in dec_1 and "all-to-all" not in dec_1
        assert "all_to_all" not in chk_1 and "all-to-all" not in chk_1
