"""Step-anatomy trace parser tests (ISSUE 13 tentpole): canned Chrome
trace fixtures (device tracks, async collective start/done pairs, host
copies, Pallas kernel names, replica-group axes) driving
``profiling/step_trace.py``, the stable JSON schema, the CPU-client
fallback, the never-raise degrade path, and the refactored
``benchmarks/trace_summary.py`` CLI (``--json`` + human table)."""

import gzip
import json
import os

import numpy as np
import pytest

import deepspeed_tpu  # noqa: F401 - compat shims before jax use
import jax

from deepspeed_tpu.profiling import step_trace
from deepspeed_tpu.profiling.step_trace import (
    StepDecomposition, decompose, decompose_dir, family_of,
    find_trace_file, kernel_op_for, DECOMP_TERMS, UNMODELED_KEYS)

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


# ----------------------------------------------------------- fixtures
def proc(pid, name):
    return {"ph": "M", "pid": pid, "name": "process_name",
            "args": {"name": name}}


def thread(pid, tid, name):
    return {"ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
            "args": {"name": name}}


def ev(name, ts, dur, pid=1, tid=10, **args):
    return {"ph": "X", "pid": pid, "tid": tid, "name": name,
            "ts": ts, "dur": dur, "args": args}


def device_meta():
    """One TPU-style device track with the leaf-op thread."""
    return [proc(1, "/device:TPU:0 (Core 0)"), thread(1, 10, "XLA Ops"),
            thread(1, 11, "Steps")]


def write_trace(root, events):
    """Nest a gzipped trace the way jax.profiler lays them out."""
    d = os.path.join(root, "plugins", "profile", "2026_08_04")
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, "host.trace.json.gz")
    with gzip.open(path, "wt") as f:
        json.dump({"traceEvents": events}, f)
    return path


def tensor_mesh():
    """2x4 mesh over (data, tensor) on the conftest virtual devices."""
    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    return jax.sharding.Mesh(devs, ("data", "tensor"))


def outer_mesh():
    """2x4 mesh over (data_outer, data) — the DCN-crossing layout."""
    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    return jax.sharding.Mesh(devs, ("data_outer", "data"))


# --------------------------------------------------------- classification
class TestClassifiers:
    def test_family_of(self):
        assert family_of("all-reduce.3") == "collective"
        assert family_of("custom-call.7") == "pallas/custom-call"
        assert family_of("dot.2") == "matmul"
        assert family_of("fusion.11") == "fusion(elementwise/other)"
        assert family_of("transpose.4") == "copy/layout"
        assert family_of("dynamic-update-slice.1") == \
            "gather/scatter/DUS"
        assert family_of("parameter.0") == "other"

    def test_kernel_op_hints_name_registry_ops(self):
        from deepspeed_tpu.autotuning.kernel_registry import REGISTRY
        for op, _ in step_trace.KERNEL_OP_HINTS:
            assert op in REGISTRY, (
                f"KERNEL_OP_HINTS names {op!r} which is not a "
                f"registered tunable op")
        assert kernel_op_for("flash_attention_fwd_kernel") == \
            "flash_attention"
        assert kernel_op_for("gmm_kernel call") == "moe_grouped_mm"
        assert kernel_op_for("plain_matmul") is None


# ------------------------------------------------------------ self time
class TestSelfTime:
    def test_nested_envelope_never_double_counts(self):
        events = device_meta() + [
            ev("fusion.1", 0, 100),
            ev("dot.2", 10, 40),
        ]
        d = decompose(events, steps=1)
        per = {r["op"]: r["ms"] for r in d.per_op}
        assert per["fusion.1"] == pytest.approx(0.060)   # 100 - 40 us
        assert per["dot.2"] == pytest.approx(0.040)
        assert d.terms["compute"] == pytest.approx(0.100)
        assert d.total_device_ms == pytest.approx(0.100)

    def test_steps_normalization(self):
        events = device_meta() + [ev("dot.1", 0, 200)]
        d = decompose(events, steps=2)
        assert d.terms["compute"] == pytest.approx(0.100)
        assert d.steps == 2


# ----------------------------------------------------------- collectives
class TestCollectives:
    def test_async_pair_exposed_vs_hidden(self):
        events = device_meta() + [
            ev("all-reduce-start.5", 0, 10),
            ev("dot.1", 10, 90),
            ev("all-reduce-done.5", 100, 5),
        ]
        d = decompose(events, steps=1)
        (row,) = d.collectives
        assert row["op"] == "all-reduce"
        assert row["term"] == "grad_reduce"
        # window 105us, gap 100-10=90 hidden, 15 exposed
        assert row["total_ms"] == pytest.approx(0.105)
        assert row["hidden_ms"] == pytest.approx(0.090)
        assert row["exposed_ms"] == pytest.approx(0.015)
        # terms carry EXPOSED time only
        assert d.terms["grad_reduce"] == pytest.approx(0.015)
        assert d.collective_hidden_ms == pytest.approx(0.090)

    def test_sync_collective_fully_exposed(self):
        events = device_meta() + [ev("all-reduce.2", 0, 50)]
        d = decompose(events, steps=1)
        (row,) = d.collectives
        assert row["exposed_ms"] == pytest.approx(0.050)
        assert row["hidden_ms"] == 0.0
        assert d.terms["grad_reduce"] == pytest.approx(0.050)

    def test_unmatched_start_counts_exposed(self):
        events = device_meta() + [ev("all-reduce-start.9", 0, 30)]
        d = decompose(events, steps=1)
        assert d.terms["grad_reduce"] == pytest.approx(0.030)

    def test_replica_groups_pick_tensor_axis(self):
        mesh = tensor_mesh()
        rg = "replica_groups={{0,1,2,3},{4,5,6,7}}"
        events = device_meta() + [
            ev("all-reduce.1", 0, 40, long_name=f"all-reduce.1 {rg}")]
        d = decompose(events, steps=1, mesh=mesh)
        (row,) = d.collectives
        assert row["axes"] == ["tensor"]
        assert row["term"] == "tp_reduce"
        assert row["leg"] == "ici"
        assert d.terms["tp_reduce"] == pytest.approx(0.040)

    def test_data_outer_groups_are_the_dcn_leg(self):
        mesh = outer_mesh()
        rg = "replica_groups={{0,4},{1,5},{2,6},{3,7}}"
        events = device_meta() + [
            ev("all-reduce.1", 0, 40, long_name=f"all-reduce.1 {rg}")]
        d = decompose(events, steps=1, mesh=mesh)
        (row,) = d.collectives
        assert row["axes"] == ["data_outer"]
        assert row["leg"] == "dcn"
        assert row["term"] == "grad_reduce"

    def test_all_to_all_is_expert_term(self):
        events = device_meta() + [ev("all-to-all.4", 0, 20)]
        d = decompose(events, steps=1)
        assert d.terms["expert_a2a"] == pytest.approx(0.020)

    def test_permute_defaults_by_mesh_shape(self):
        events = device_meta() + [ev("collective-permute.2", 0, 10)]
        # seq-parallel mesh, no pipe -> ring rotation
        devs = np.array(jax.devices()[:2]).reshape(2)
        seq_mesh = jax.sharding.Mesh(devs, ("seq",))
        d = decompose(events, steps=1, mesh=seq_mesh)
        assert d.terms["ring_rotate"] == pytest.approx(0.010)
        # no mesh knowledge -> pipe handoff default
        d2 = decompose(events, steps=1)
        assert d2.terms["pipe_handoff"] == pytest.approx(0.010)


# ------------------------------------------------------------ host copies
class TestHostCopies:
    def test_host_copy_async_window_is_offload(self):
        events = device_meta() + [
            ev("copy-start.3", 0, 10, long_name="copy-start.3 S(5)"),
            ev("copy-done.3", 40, 5, long_name="copy-done.3 S(5)"),
        ]
        d = decompose(events, steps=1)
        # window 45, gap 30 hidden -> 15us exposed staging
        assert d.terms["host_offload"] == pytest.approx(0.015)
        assert d.host_copy_ms == pytest.approx(0.015)

    def test_sync_host_copy(self):
        events = device_meta() + [
            ev("copy.7", 0, 25, long_name="copy.7 S(5){1,0}")]
        d = decompose(events, steps=1)
        assert d.terms["host_offload"] == pytest.approx(0.025)

    def test_device_copy_is_unmodeled_layout(self):
        events = device_meta() + [
            ev("copy.8", 0, 25), ev("transpose.2", 30, 15)]
        d = decompose(events, steps=1)
        assert d.unmodeled["copy_layout"] == pytest.approx(0.040)
        assert d.terms["host_offload"] == 0.0
        # unmodeled time drags coverage below 100
        assert d.coverage_pct == 0.0


# ---------------------------------------------------------------- kernels
class TestKernels:
    def test_pallas_time_keyed_by_registry_op(self):
        events = device_meta() + [
            ev("custom-call.7", 0, 80,
               long_name="custom-call.7 flash_attention_fwd_kernel"),
            ev("custom-call.9", 100, 20,
               long_name="custom-call.9 gmm_kernel"),
        ]
        d = decompose(events, steps=1)
        assert d.kernels == {
            "flash_attention": pytest.approx(0.080),
            "moe_grouped_mm": pytest.approx(0.020)}
        # kernel time is still compute (a breakdown, not a new term)
        assert d.terms["compute"] == pytest.approx(0.100)


# ---------------------------------------------------------- track selection
class TestTracks:
    def test_cpu_client_fallback_filters_runtime_frames(self):
        events = [
            proc(2, "/host:CPU"), thread(2, 20, "tf_XLATfrtCpuClient/5"),
            ev("dot.3", 0, 50, pid=2, tid=20),
            ev("TfrtCpuExecutable::Execute", 0, 500, pid=2, tid=20),
            ev("ParseArguments", 60, 10, pid=2, tid=20),
        ]
        d = decompose(events, steps=1)
        assert d.cpu_fallback is True
        assert d.terms["compute"] == pytest.approx(0.050)
        ops = {r["op"] for r in d.per_op}
        assert "TfrtCpuExecutable::Execute" not in ops

    def test_device_track_wins_over_cpu_threads(self):
        events = device_meta() + [
            proc(2, "/host:CPU"), thread(2, 20, "tf_XLATfrtCpuClient/1"),
            ev("dot.1", 0, 50),
            ev("dot.9", 0, 999, pid=2, tid=20),
        ]
        d = decompose(events, steps=1)
        assert d.cpu_fallback is False
        assert d.terms["compute"] == pytest.approx(0.050)

    def test_no_tracks_returns_none(self):
        assert decompose([proc(3, "python")], steps=1) is None
        assert decompose([], steps=1) is None


# ------------------------------------------------------------- JSON schema
class TestSchema:
    def test_stable_field_set(self):
        events = device_meta() + [ev("dot.1", 0, 10)]
        d = decompose(events, steps=1)
        got = set(d.to_dict())
        assert got == {
            "schema", "steps", "trace_path", "device_tracks",
            "cpu_fallback", "total_device_ms", "terms", "unmodeled",
            "collectives", "kernels", "per_op", "host_copy_ms",
            "collective_total_ms", "collective_exposed_ms",
            "collective_hidden_ms", "occupancy_pct", "span_ms",
            "coverage_pct"}
        assert d.to_dict()["schema"] == step_trace.SCHEMA_VERSION
        parsed = json.loads(d.to_json())
        assert parsed["terms"]["compute"] == pytest.approx(0.010)

    def test_terms_keys_are_the_full_vocabulary(self):
        d = decompose(device_meta() + [ev("dot.1", 0, 10)], steps=1)
        assert set(d.terms) == set(DECOMP_TERMS)
        assert set(d.unmodeled) == set(UNMODELED_KEYS)


# ----------------------------------------------------------- io + degrade
class TestTraceIO:
    def test_find_and_decompose_dir(self, tmp_path):
        path = write_trace(str(tmp_path),
                           device_meta() + [ev("dot.1", 0, 10)])
        assert find_trace_file(str(tmp_path)) == path
        assert find_trace_file(path) == path
        d = decompose_dir(str(tmp_path), steps=1)
        assert d is not None and d.trace_path == path

    def test_missing_trace_degrades_to_none(self, tmp_path, caplog):
        assert decompose_dir(str(tmp_path / "nope")) is None
        assert find_trace_file(str(tmp_path)) is None

    def test_corrupt_trace_never_raises(self, tmp_path):
        d = os.path.join(str(tmp_path), "plugins", "profile", "x")
        os.makedirs(d)
        with gzip.open(os.path.join(d, "bad.trace.json.gz"), "wt") as f:
            f.write("{not json")
        assert decompose_dir(str(tmp_path)) is None


# ----------------------------------------------------------- CLI surfaces
def _load_trace_summary():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "trace_summary", os.path.join(REPO, "benchmarks",
                                      "trace_summary.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestTraceSummaryCLI:
    def _trace(self, tmp_path):
        return write_trace(str(tmp_path), device_meta() + [
            ev("fusion.1", 0, 100),
            ev("dot.2", 10, 40),
            ev("all-reduce.3", 120, 30),
        ])

    def test_human_table_default(self, tmp_path, capsys):
        ts = _load_trace_summary()
        self._trace(tmp_path)
        assert ts.main([str(tmp_path), "--steps", "1"]) == 0
        out = capsys.readouterr().out
        assert "fusion.1" in out
        assert "families (ms/step):" in out
        assert "planner terms (exposed ms/step):" in out
        assert "grad_reduce" in out

    def test_json_output_is_the_decomposition(self, tmp_path, capsys):
        ts = _load_trace_summary()
        self._trace(tmp_path)
        assert ts.main([str(tmp_path), "--steps", "1", "--json"]) == 0
        parsed = json.loads(capsys.readouterr().out)
        assert parsed["schema"] == step_trace.SCHEMA_VERSION
        assert parsed["terms"]["grad_reduce"] == pytest.approx(0.030)

    def test_positional_steps_compat(self, tmp_path, capsys):
        ts = _load_trace_summary()
        self._trace(tmp_path)
        assert ts.main([str(tmp_path), "2"]) == 0
        assert "over 2 steps" in capsys.readouterr().out
