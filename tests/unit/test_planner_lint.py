"""Two-direction coverage lint for the auto-parallelism knob table.

The planner's KNOB_TABLE claims to be the single source of truth tying
every ``"auto"``-accepting config knob to its resolver. This lint keeps
the claim honest in both directions, mechanically:

  1. every config-block field that ACCEPTS "auto" (discovered by
     construction probes, not by reading the table) appears in
     KNOB_TABLE — a new auto knob cannot land without declaring who
     resolves it;
  2. every op in the tunable-op REGISTRY is reachable from some
     KNOB_TABLE entry — a new registry op cannot land orphaned, with no
     config surface that could ever consult its winners.
"""

import dataclasses

import pytest

from deepspeed_tpu.autotuning.planner import KNOB_TABLE
from deepspeed_tpu.runtime import config as cfg_mod
from deepspeed_tpu.runtime.config import DeepSpeedConfig

# config key -> block dataclass, mirroring how DeepSpeedConfig wires its
# sub-blocks (the lint probes the CLASSES so discovery needs no engine)
_BLOCKS = {
    "fp16": cfg_mod.FP16Config,
    "bf16": cfg_mod.BF16Config,
    "zero_optimization": cfg_mod.ZeroConfig,
    "tensor_parallel": cfg_mod.TensorParallelConfig,
    "pipeline": cfg_mod.PipelineConfig,
    "checkpoint_engine": cfg_mod.CheckpointEngineConfig,
    "comm_overlap": cfg_mod.CommOverlapConfig,
    "sequence": cfg_mod.SequenceConfig,
    "moe": cfg_mod.MoEConfig,
    "quantize": cfg_mod.QuantizeConfig,
    "autotune": cfg_mod.AutotuneConfig,
    "telemetry": cfg_mod.TelemetryConfig,
}

# auto-sentinel exceptions: knobs whose 'auto' spelling is not the
# string "auto" (pipeline.micro_batches uses 0, the reference idiom)
_SENTINELS = {("pipeline", "micro_batches"): 0}

_JUNK = "___definitely_not_a_knob_value___"


def _accepts(cls, field, value):
    try:
        cls(**{field: value})
        return True
    except Exception:  # noqa: BLE001 - any validation error counts
        return False


def discovered_auto_knobs():
    """Every (block, field) whose dataclass constructs with "auto" AND
    rejects a junk value — i.e. validated fields where "auto" is a
    deliberately admitted spelling, not an unvalidated pass-through."""
    found = set()
    for key, cls in _BLOCKS.items():
        for f in dataclasses.fields(cls):
            if _accepts(cls, f.name, "auto") \
                    and not _accepts(cls, f.name, _JUNK):
                found.add((key, f.name))
    for (key, fname), sentinel in _SENTINELS.items():
        cls = _BLOCKS[key]
        if _accepts(cls, fname, sentinel) \
                and not _accepts(cls, fname, _JUNK):
            found.add((key, fname))
    return found


def test_every_auto_knob_is_in_the_table():
    missing = {f"{b}.{f}" for b, f in discovered_auto_knobs()} \
        - set(KNOB_TABLE)
    assert not missing, (
        f"config knobs accept 'auto' but declare no resolver in "
        f"planner.KNOB_TABLE: {sorted(missing)} — add an entry naming "
        f"the registry op or heuristic that resolves each")


def test_table_block_knobs_really_accept_auto():
    """The reverse of discovery for the block-level entries: a table row
    must not claim an auto knob that the config no longer validates
    (stale table rows would make the lint vacuous)."""
    discovered = {f"{b}.{f}" for b, f in discovered_auto_knobs()}
    block_rows = {k for k in KNOB_TABLE
                  if k.split(".", 1)[0] in _BLOCKS and "." in k}
    stale = block_rows - discovered
    assert not stale, (
        f"KNOB_TABLE rows name config fields that do not accept 'auto' "
        f"(or are unvalidated): {sorted(stale)}")


def discovered_serving_auto_knobs():
    """The serving-side construction probes: every
    RaggedInferenceEngineConfig field that accepts "auto" AND rejects
    junk (same discovery rule as the training blocks) — the v2 engine's
    auto knobs (paged_kernel, paged_block_c, prefix_cache,
    prefix_cache_min_match) cannot land without a KNOB_TABLE row."""
    from deepspeed_tpu.inference.v2.engine_v2 import (
        RaggedInferenceEngineConfig)
    found = set()
    for f in dataclasses.fields(RaggedInferenceEngineConfig):
        if _accepts(RaggedInferenceEngineConfig, f.name, "auto") \
                and not _accepts(RaggedInferenceEngineConfig,
                                 f.name, _JUNK):
            found.add(f.name)
    return found


def test_every_serving_auto_knob_is_in_the_table():
    missing = {f"serving.{f}" for f in discovered_serving_auto_knobs()} \
        - set(KNOB_TABLE)
    assert not missing, (
        f"serving config knobs accept 'auto' but declare no resolver "
        f"in planner.KNOB_TABLE: {sorted(missing)} — add a "
        f"serving.<field> entry naming the registry op that resolves "
        f"each")


def test_table_serving_knobs_really_accept_auto():
    discovered = {f"serving.{f}"
                  for f in discovered_serving_auto_knobs()}
    rows = {k for k in KNOB_TABLE if k.startswith("serving.")}
    stale = rows - discovered
    assert not stale, (
        f"KNOB_TABLE serving rows name engine-config fields that do "
        f"not accept 'auto' (or are unvalidated): {sorted(stale)}")


def discovered_router_auto_knobs():
    """Construction probes over the serving-fleet RouterConfig
    (inference/v2/router.py), same discovery rule: a router auto knob
    (router_queue_depth, shed_policy, prefix_affinity) cannot land an
    "auto" spelling without a router.<field> KNOB_TABLE row."""
    from deepspeed_tpu.inference.v2.router import RouterConfig
    found = set()
    for f in dataclasses.fields(RouterConfig):
        if _accepts(RouterConfig, f.name, "auto") \
                and not _accepts(RouterConfig, f.name, _JUNK):
            found.add(f.name)
    return found


def test_every_router_auto_knob_is_in_the_table():
    missing = {f"router.{f}" for f in discovered_router_auto_knobs()} \
        - set(KNOB_TABLE)
    assert not missing, (
        f"router config knobs accept 'auto' but declare no resolver "
        f"in planner.KNOB_TABLE: {sorted(missing)} — add a "
        f"router.<field> entry naming the heuristic that resolves each")


def test_table_router_knobs_really_accept_auto():
    discovered = {f"router.{f}"
                  for f in discovered_router_auto_knobs()}
    rows = {k for k in KNOB_TABLE if k.startswith("router.")}
    stale = rows - discovered
    assert not stale, (
        f"KNOB_TABLE router rows name RouterConfig fields that do not "
        f"accept 'auto' (or are unvalidated): {sorted(stale)}")


def test_router_expected_knobs_are_discovered():
    """Pin the ISSUE-17 knob set so a refactor cannot silently drop a
    knob's validation (which would drop it from discovery and make the
    reverse lint delete its row instead of failing)."""
    assert {"router_queue_depth", "shed_policy", "prefix_affinity",
            "disaggregate"} <= discovered_router_auto_knobs()


def test_disaggregation_knobs_are_in_the_table():
    """Pin the disaggregated-serving rows: the router's disaggregate
    knob and the replica role choice must both route to the kv_handoff
    registry op (the cost model pricing KV wire bytes against stolen
    decode iterations)."""
    assert KNOB_TABLE["router.disaggregate"]["op"] == "kv_handoff"
    assert KNOB_TABLE["replica.role"]["op"] == "kv_handoff"


def test_top_level_parallelism_accepts_auto():
    """The one auto knob living outside any block: top-level
    ``parallelism`` — "" and "auto" pass, junk raises."""
    DeepSpeedConfig({"train_batch_size": 1, "parallelism": "auto"},
                    dp_world_size=1)
    with pytest.raises(cfg_mod.DeepSpeedConfigError):
        DeepSpeedConfig({"train_batch_size": 1, "parallelism": _JUNK},
                        dp_world_size=1)
    assert "parallelism" in KNOB_TABLE


def test_every_registry_op_is_reachable_from_the_table():
    from deepspeed_tpu.autotuning.kernel_registry import REGISTRY
    table_ops = {v.get("op") for v in KNOB_TABLE.values()} - {None}
    orphaned = set(REGISTRY) - table_ops
    assert not orphaned, (
        f"registry ops with no config knob that could consult their "
        f"winners: {sorted(orphaned)} — add a KNOB_TABLE entry")


def test_every_table_op_exists_in_the_registry():
    from deepspeed_tpu.autotuning.kernel_registry import REGISTRY
    table_ops = {v.get("op") for v in KNOB_TABLE.values()} - {None}
    phantom = table_ops - set(REGISTRY)
    assert not phantom, (
        f"KNOB_TABLE names ops that are not in the registry: "
        f"{sorted(phantom)} (note comm_link is cache-file-only by "
        f"design and must never appear in the table)")
