"""Telemetry layer tests (ISSUE 9 tentpole): metric-tag schema lint
(both directions, the fault-points-lint discipline), step analytics /
MFU / goodput, cluster aggregation + straggler detection, the crash
flight recorder (chaos: kill mid-save, read the black box), on-demand
profiling arming, serving TTFT/TPOT accounting, and the
off-the-critical-path guarantee (dp=2 virtual mesh, telemetry on vs
off within noise)."""

import json
import os
import re
import signal
import time

import numpy as np
import pytest

import deepspeed_tpu  # noqa: F401 - compat shims before jax use
import jax

from deepspeed_tpu.monitor import flight_recorder
from deepspeed_tpu.monitor.flight_recorder import FlightRecorder
from deepspeed_tpu.monitor.tag_schema import TAG_SCHEMA, check_tag
from deepspeed_tpu.monitor.telemetry import (
    TelemetryCollector, ClusterAggregator, ServingTelemetry,
    ProfilerControl, aggregate_cluster, collective_breakdown,
    peak_flops_per_chip, percentile)
from deepspeed_tpu.runtime.config import TelemetryConfig
from deepspeed_tpu.utils import fault_injection

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
PKG = os.path.join(REPO, "deepspeed_tpu")

_TAG_RE = re.compile(
    r"""["']((?:Train|Serve)/[A-Za-z0-9_]+/[A-Za-z0-9_]+)["']""")


def _py_files(root):
    for dirpath, _, names in os.walk(root):
        if "__pycache__" in dirpath:
            continue
        for n in names:
            if n.endswith(".py"):
                yield os.path.join(dirpath, n)


class _StubMonitor:
    """Duck-typed MonitorMaster stand-in for collectors."""

    enabled = True

    def __init__(self):
        self.events = []

    def write_events(self, event_list):
        self.events.extend(event_list)


# ------------------------------------------------------------ schema lint
class TestTagSchemaLint:
    """The test_fault_points_lint.py discipline applied to metric tags:
    every tag production code emits is documented in TAG_SCHEMA, and
    every TAG_SCHEMA entry is emitted somewhere — neither half can rot
    under a refactor."""

    def _emitted(self):
        tags = set()
        for path in _py_files(PKG):
            if os.path.basename(path) == "tag_schema.py":
                continue   # the registry itself never counts
            with open(path, encoding="utf-8") as f:
                tags.update(_TAG_RE.findall(f.read()))
        return tags

    def test_every_emitted_tag_is_documented(self):
        undocumented = self._emitted() - set(TAG_SCHEMA)
        assert not undocumented, (
            f"tags emitted in production code but missing from "
            f"monitor/tag_schema.py TAG_SCHEMA: {sorted(undocumented)}")

    def test_every_documented_tag_is_emitted(self):
        dead = set(TAG_SCHEMA) - self._emitted()
        assert not dead, (
            f"TAG_SCHEMA entries no production code emits (stale "
            f"registry or lost emission site): {sorted(dead)}")

    def test_check_tag(self):
        assert check_tag("Train/Samples/lr") == "Train/Samples/lr"
        with pytest.raises(KeyError):
            check_tag("Train/Bogus/nope")


# ------------------------------------------------------------- pure math
class TestAggregation:
    def test_percentile_guard(self):
        assert percentile([], 50) is None
        assert percentile([3.0], 99) == 3.0

    def test_straggler_detection(self):
        agg = aggregate_cluster({
            "h0": {"mean_step_ms": 100.0},
            "h1": {"mean_step_ms": 101.0},
            "h2": {"mean_step_ms": 180.0},
            "h3": {"mean_step_ms": 99.0}})
        assert agg["hosts"] == 4
        assert agg["straggler_node"] == "h2"
        assert agg["straggler_host"] == 2
        # slowest minus the pod median (of 99, 100, 101, 180)
        assert agg["straggler_delta_ms"] == pytest.approx(
            180.0 - 100.5)
        assert agg["cluster_step_ms_p99"] <= 180.0

    def test_ring_order_beats_lexical_sort(self):
        """Regression (review finding): string process ids sort
        lexically ('10' before '2'), misnumbering the straggler on
        pods >= 10 hosts — the ring ``order`` is authoritative."""
        ring = [str(i) for i in range(12)]
        by_host = {h: {"mean_step_ms": 100.0} for h in ring}
        by_host["9"] = {"mean_step_ms": 500.0}
        agg = aggregate_cluster(by_host, order=ring)
        assert agg["straggler_host"] == 9
        assert agg["straggler_node"] == "9"
        # order also drops hosts not in the ring and missing metrics
        agg2 = aggregate_cluster(by_host, order=ring[:4] + ["ghost"])
        assert agg2["hosts"] == 4

    def test_empty_and_partial_hosts(self):
        assert aggregate_cluster({}) is None
        agg = aggregate_cluster({"h0": {"mean_step_ms": 10.0},
                                 "h1": {}, "h2": None})
        assert agg["hosts"] == 1

    def test_straggler_index_survives_missing_host(self):
        """Regression (review finding): a host whose publish is lost
        for a round must not renumber the straggler — the index is the
        RING position, not the position in the filtered list."""
        ring = [str(i) for i in range(12)]
        by_host = {h: {"mean_step_ms": 100.0} for h in ring}
        by_host["9"] = {"mean_step_ms": 500.0}
        del by_host["3"]                    # lost publish
        agg = aggregate_cluster(by_host, order=ring)
        assert agg["hosts"] == 11
        assert agg["straggler_node"] == "9"
        assert agg["straggler_host"] == 9   # ring index, not 8

    def test_collective_breakdown_counts_pairs_once(self):
        """Regression (review finding): overlap_report's n_collectives
        counts HLO entries — an async collective is a -start AND a
        -done entry. 1 sync + 1 async = 3 entries, 1 pair: 2 logical
        collectives, 50% exposed (dividing by entries read 33%)."""
        assert collective_breakdown(3, 1) == (2, 50.0)
        assert collective_breakdown(4, 2) == (2, 0.0)    # fully async
        assert collective_breakdown(2, 0) == (2, 100.0)  # fully exposed
        assert collective_breakdown(0, 0) == (0, 0.0)

    def test_peak_flops_table(self, monkeypatch):
        monkeypatch.delenv("DSTPU_PEAK_FLOPS", raising=False)
        v5e, assumed = peak_flops_per_chip("TPU v5 lite")
        assert v5e == 197e12 and not assumed
        v5p, _ = peak_flops_per_chip("TPU v5p")
        assert v5p == 459e12
        cpu, assumed = peak_flops_per_chip("cpu")
        assert assumed
        monkeypatch.setenv("DSTPU_PEAK_FLOPS", "1e15")
        forced, assumed = peak_flops_per_chip("cpu")
        assert forced == 1e15 and not assumed


# -------------------------------------------------------- fs cluster ring
class TestClusterAggregatorFS:
    def _pair(self, tmp_path):
        peers = ["h0", "h1"]
        return [ClusterAggregator(node=p, peers=peers,
                                  root=str(tmp_path)) for p in peers]

    def test_two_node_gather(self, tmp_path):
        a0, a1 = self._pair(tmp_path)
        assert a0.transport == "fs" and a0.is_root and not a1.is_root
        a1.gather({"node": "h1", "step": 3, "mean_step_ms": 50.0})
        got = a0.gather({"node": "h0", "step": 3, "mean_step_ms": 20.0},
                        wait_s=2.0)
        assert set(got) == {"h0", "h1"}
        agg = aggregate_cluster(got)
        assert agg["straggler_node"] == "h1"
        assert agg["straggler_delta_ms"] == pytest.approx(15.0)

    def test_missing_peer_is_partial_not_fatal(self, tmp_path):
        a0, _ = self._pair(tmp_path)
        got = a0.gather({"node": "h0", "step": 1, "mean_step_ms": 9.0},
                        wait_s=0.0)
        assert list(got) == ["h0"]

    def test_single_process_no_ring(self, monkeypatch):
        for v in ("DSTPU_TELEM_DIR", "DSTPU_TELEM_NODE",
                  "DSTPU_TELEM_PEERS", "DSTPU_HOT_NODE",
                  "DSTPU_HOT_PEERS"):
            monkeypatch.delenv(v, raising=False)
        agg = ClusterAggregator()
        assert agg.transport is None
        got = agg.gather({"step": 1, "mean_step_ms": 5.0})
        assert len(got) == 1


# --------------------------------------------------------- flight recorder
class TestFlightRecorder:
    def test_ring_is_bounded(self):
        rec = FlightRecorder(size=8, node="t")
        for i in range(50):
            rec.record("step", step=i)
        ev = rec.events()
        assert len(ev) == 8
        assert ev[-1]["step"] == 49 and ev[0]["step"] == 42

    def test_dump_and_read(self, tmp_path):
        rec = FlightRecorder(size=16, node="n7")
        rec.set_root(str(tmp_path))
        rec.record("restore", tier="hot", tag="global_step5")
        path = rec.dump(reason="test")
        assert path == flight_recorder.dump_path(str(tmp_path), "n7")
        back = flight_recorder.read_dump(str(tmp_path), "n7")
        assert back["reason"] == "test" and back["node"] == "n7"
        assert back["events"][-1]["kind"] == "restore"
        assert back["events"][-1]["tier"] == "hot"

    def test_concurrent_dumps_never_tear(self, tmp_path):
        """Regression (review finding): a main-thread crash dump can
        race a pool-thread interval dump in the same process — a shared
        pid-only tmp name interleaved both writers' JSON. Per-call
        unique tmp names make each os.replace publish one complete
        dump."""
        import threading
        rec = FlightRecorder(size=64, node="r")
        rec.set_root(str(tmp_path))
        for i in range(40):
            rec.record("step", step=i)

        def hammer():
            for _ in range(25):
                assert rec.dump(reason="race") is not None

        threads = [threading.Thread(target=hammer) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        back = flight_recorder.read_dump(str(tmp_path), "r")
        assert back is not None and back["reason"] == "race"
        assert len(back["events"]) == 40

    def test_set_root_is_first_wins(self, tmp_path):
        rec = FlightRecorder(node="x")
        rec.set_root(str(tmp_path / "a"))
        rec.set_root(str(tmp_path / "b"))
        assert rec.root == str(tmp_path / "a")

    def test_crash_never_raises(self, tmp_path, monkeypatch):
        rec = FlightRecorder(node="c")
        rec.set_root(str(tmp_path))
        rec.crash(RuntimeError("boom"))
        back = flight_recorder.read_dump(str(tmp_path), "c")
        assert back["reason"] == "crash"
        assert "boom" in back["events"][-1]["error"]
        # even a failing dump must not mask the real exception
        monkeypatch.setattr(rec, "dump",
                            lambda **kw: (_ for _ in ()).throw(OSError))
        rec.crash(RuntimeError("again"))   # no raise

    def test_sigterm_chains_previous_handler(self, tmp_path):
        hits = []
        prev = signal.signal(signal.SIGTERM,
                             lambda s, f: hits.append(s))
        try:
            rec = FlightRecorder(node="sig")
            rec.set_root(str(tmp_path))
            assert rec.install_sigterm()
            os.kill(os.getpid(), signal.SIGTERM)
            back = flight_recorder.read_dump(str(tmp_path), "sig")
            assert back is not None and back["reason"] == "sigterm"
            assert hits == [signal.SIGTERM]   # previous handler ran
        finally:
            signal.signal(signal.SIGTERM, prev)

    def test_fault_listener_records_injected_points(self):
        cfg = TelemetryConfig(enabled=True, interval_steps=100)
        tel = TelemetryCollector(cfg)
        try:
            fault_injection.reset()
            fault_injection.arm("reshape", fails=1)
            with pytest.raises(fault_injection.FaultError):
                fault_injection.fire("reshape")
            fault_injection.fire("reshape")    # healed, clean: silent
            points = [e for e in tel.flight.events()
                      if e["kind"] == "fault_point"]
            assert points == [{"t": points[0]["t"],
                               "kind": "fault_point",
                               "point": "reshape", "injected": True}]
        finally:
            fault_injection.reset()
            tel.close()


# ----------------------------------------------------------- profiler arm
class TestProfilerControl:
    def test_parse(self):
        assert ProfilerControl._parse("3:7") == (3, 7)
        assert ProfilerControl._parse(None) is None
        assert ProfilerControl._parse("7:3") is None
        assert ProfilerControl._parse("junk") is None

    def test_step_range_capture(self, tmp_path, monkeypatch):
        calls = []
        monkeypatch.setattr(jax.profiler, "start_trace",
                            lambda d: calls.append(("start", d)))
        monkeypatch.setattr(jax.profiler, "stop_trace",
                            lambda: calls.append(("stop",)))
        monkeypatch.setenv("DSTPU_PROFILE_STEPS", "2:4")
        rec = FlightRecorder(node="p")
        pc = ProfilerControl(logdir=str(tmp_path), flight=rec)
        for step in range(6):
            pc.on_step(step)
        assert [c[0] for c in calls] == ["start", "stop"]
        assert calls[0][1] == os.path.join(str(tmp_path), "xprof")
        kinds = [e["kind"] for e in rec.events()]
        assert kinds == ["profile_start", "profile_stop"]

    def test_non_numeric_port_never_fatal(self):
        """Regression (review finding): DSTPU_PROFILE_PORT=xprof must
        degrade with a warning, not crash engine construction."""
        from deepspeed_tpu.monitor.telemetry import _maybe_start_server
        assert _maybe_start_server("xprof") is False
        assert _maybe_start_server(None) is False

    def test_trigger_file_arms_next_steps(self, tmp_path, monkeypatch):
        monkeypatch.delenv("DSTPU_PROFILE_STEPS", raising=False)
        pc = ProfilerControl(logdir=str(tmp_path))
        pc.check_trigger(str(tmp_path), step=10)
        assert pc.range is None
        with open(os.path.join(str(tmp_path), "PROFILE"), "w") as f:
            f.write("3")
        pc.check_trigger(str(tmp_path), step=10)
        assert pc.range == (11, 14)
        assert not os.path.exists(os.path.join(str(tmp_path),
                                               "PROFILE"))


# ------------------------------------------------------- collector (unit)
class TestTelemetryCollector:
    def _collector(self, monitor=None, interval=2, costs=None):
        cfg = TelemetryConfig(enabled=True, interval_steps=interval,
                              cluster_agg=False)
        return TelemetryCollector(
            cfg, monitor=monitor, n_devices=2, device_kind="TPU v5 lite",
            costs_fn=(lambda: costs) if costs else None)

    def test_flush_emits_documented_tags(self):
        mon = _StubMonitor()
        costs = {"flops_per_chip": 197e12 * 0.010, "source": "hlo",
                 "collectives": 10, "exposed_comm_pct": 40.0}
        tel = self._collector(monitor=mon, costs=costs)
        try:
            for step in range(1, 5):
                tel.on_step(step, wall_s=0.020, tokens=1000)
            tel.drain()
            assert mon.events, "no telemetry events reached the monitor"
            for tag, _, _ in mon.events:
                assert tag in TAG_SCHEMA, f"undocumented tag {tag}"
            by_tag = {t: v for t, v, _ in mon.events}
            # 10ms of flops per step at 20ms step time -> 50% MFU
            assert by_tag["Train/Telemetry/mfu_pct"] == \
                pytest.approx(50.0, rel=0.01)
            assert by_tag["Train/Telemetry/exposed_comm_pct"] == 40.0
            assert by_tag["Train/Telemetry/collectives"] == 10
            # 1000 tokens / 0.02 s / 2 chips
            assert by_tag["Train/Telemetry/tokens_per_sec_chip"] == \
                pytest.approx(25000.0, rel=0.01)
        finally:
            tel.close()

    def test_goodput_accounting(self):
        tel = self._collector()
        try:
            tel._t0 = time.perf_counter() - 10.0     # 10s elapsed
            tel.note_overhead("checkpoint_save", 1.5)
            tel.note_overhead("checkpoint_restore", 0.5)
            assert tel.goodput_pct() == pytest.approx(80.0, abs=1.0)
            kinds = [e["kind"] for e in tel.flight.events()]
            assert kinds == ["checkpoint_save", "checkpoint_restore"]
        finally:
            tel.close()

    def test_on_restore_records_tier(self):
        tel = self._collector()
        try:
            tel.on_restore("hot", "global_step7", 0.25)
            ev = tel.flight.events()[-1]
            assert ev["kind"] == "restore" and ev["tier"] == "hot"
            assert tel._overhead_s["checkpoint_restore"] == 0.25
        finally:
            tel.close()

    def test_costs_failure_degrades(self):
        def bad():
            raise RuntimeError("no program yet")

        cfg = TelemetryConfig(enabled=True, interval_steps=1,
                              cluster_agg=False)
        tel = TelemetryCollector(cfg, costs_fn=bad)
        try:
            tel.on_step(1, 0.01, tokens=10)
            assert "mfu_pct" not in tel.last
            assert tel.last["step_time_ms_p50"] == pytest.approx(10.0)
        finally:
            tel.close()

    def test_reset_window_clears_samples_and_tokens(self):
        tel = self._collector(interval=100)
        try:
            tel.on_step(1, 0.5, tokens=999)
            tel.reset_window()
            assert len(tel._step_ms) == 0 and tel._tokens == 0
            tel.on_step(2, 0.01, tokens=100)
            tel._flush(2)
            # warmup tokens/times gone: 100 tokens / 0.01 s / 2 chips
            assert tel.last["tokens_per_sec_chip"] == \
                pytest.approx(5000.0, rel=0.01)
        finally:
            tel.close()

    def test_fs_cluster_events_emit_on_main_thread_flush(
            self, tmp_path, monkeypatch):
        """Regression (review finding): a pool-side fs gather must not
        call the (non-thread-safe) monitor writers — its events park
        and emit at the NEXT main-thread flush."""
        monkeypatch.setenv("DSTPU_TELEM_DIR", str(tmp_path))
        monkeypatch.setenv("DSTPU_TELEM_NODE", "h0")
        monkeypatch.setenv("DSTPU_TELEM_PEERS", "h0")
        mon = _StubMonitor()
        cfg = TelemetryConfig(enabled=True, interval_steps=2,
                              cluster_agg=True)
        tel = TelemetryCollector(cfg, monitor=mon, n_devices=1)
        try:
            assert tel.cluster is not None \
                and tel.cluster.transport == "fs"
            tel.on_step(1, 0.01)
            tel.on_step(2, 0.01)      # flush 1: round runs on the pool
            tel.drain()
            tags1 = {t for t, _, _ in mon.events}
            assert "Train/Telemetry/straggler_delta_ms" not in tags1
            assert tel.last["cluster"]["hosts"] == 1   # computed though
            tel.on_step(3, 0.01)
            tel.on_step(4, 0.01)      # flush 2: parked events emit
            tel.drain()
            tags2 = {t for t, _, _ in mon.events}
            assert "Train/Telemetry/straggler_delta_ms" in tags2
            assert "Train/Telemetry/cluster_hosts" in tags2
        finally:
            tel.close()

    def test_dead_collector_unregisters_fault_listener(self):
        """Regression (review finding): the process-global fault
        injector must not pin dead collectors (and through costs_fn,
        whole engines) — the weak hook unhooks itself."""
        import gc
        n0 = len(fault_injection.injector._listeners)
        tel = self._collector()
        hook = tel._fault_listener
        assert len(fault_injection.injector._listeners) == n0 + 1
        del tel
        gc.collect()
        hook("reshape", True)      # dead weakref -> self-unregister
        assert len(fault_injection.injector._listeners) == n0
        assert hook not in fault_injection.injector._listeners

    def test_snapshot_without_monitor(self):
        tel = self._collector(monitor=None)
        try:
            tel.on_step(2, 0.01, tokens=10)
            snap = tel.snapshot()
            assert snap["steps_in_window"] == 1
            assert 0.0 <= snap["goodput_pct_live"] <= 100.0
        finally:
            tel.close()


# ----------------------------------------------------------- serving side
class TestServingTelemetry:
    def test_ttft_tpot_accounting(self):
        st = ServingTelemetry(interval=1)
        st.on_submit(1)
        time.sleep(0.02)
        st.on_token(1)                     # first token -> TTFT
        time.sleep(0.01)
        for _ in range(4):
            st.on_token(1)                 # one dispatch, 4 tokens
        st.on_dispatch(active=1)
        p = st.percentiles()
        assert p["ttft_ms_p50"] >= 15.0
        assert p["tpot_ms_p50"] is not None
        assert p["tpot_ms_p50"] <= p["ttft_ms_p50"]
        st.on_finish(1)
        assert st.percentiles()["completed"] == 1

    def test_emits_through_monitor(self):
        mon = _StubMonitor()
        st = ServingTelemetry(monitor=mon, interval=1)
        st.on_submit(5)
        st.on_token(5)
        st.on_finish(5)
        st.maybe_emit()
        tags = {t for t, _, _ in mon.events}
        assert "Serve/Telemetry/completed" in tags
        assert "Serve/Telemetry/ttft_ms_p50" in tags
        for t in tags:
            assert t in TAG_SCHEMA

    def test_unknown_uid_ignored(self):
        st = ServingTelemetry()
        st.on_token(99)
        st.on_finish(99)
        assert st.percentiles()["completed"] == 1

    def test_shed_heavy_traffic_does_not_poison_the_windows(self):
        """Regression (ISSUE-17 satellite): under shed-heavy traffic the
        TTFT/TPOT windows must hold ONLY requests served to completion.
        Before on_reject existed, shed/expired requests lingered in
        _live/_started and the next dispatch amortized wall time across
        their stale state, and 'completed' never matched reality."""
        st = ServingTelemetry(interval=1)
        for uid in range(10):
            st.on_submit(uid)
        # two served to completion (2 dispatch-amortized tokens each)
        for uid in (0, 1):
            st.on_token(uid)
            st.on_token(uid)
        st.on_dispatch(active=2)
        ttft_after_serves = len(st._ttft_ms)
        tpot_after_serves = len(st._tpot_ms)
        for uid in (0, 1):
            st.on_finish(uid)
        # one shed AFTER producing a token (deadline-expired mid-decode)
        st.on_token(5)
        st.on_reject(5)
        # the rest shed while still queued
        for uid in (2, 3, 4, 6, 7, 8, 9):
            st.on_reject(uid)
        p = st.percentiles()
        assert p["completed"] == 2
        assert p["rejected"] == 8
        assert not st._live and not st._started   # accounting emptied
        # a dispatch after the rejects must add no poison samples: the
        # windows still hold only what the two served requests produced
        st.on_dispatch(active=0)
        assert len(st._ttft_ms) == ttft_after_serves + 1   # + uid 5's
        assert len(st._tpot_ms) == tpot_after_serves
        # double-reject and reject-after-finish are idempotent no-ops
        st.on_reject(5)
        st.on_reject(0)
        assert st.percentiles()["rejected"] == 8

    def test_rejected_key_absent_without_rejects(self):
        """Router-off byte-identity: the 'rejected' key may only appear
        once a cancel/shed actually happened — a plain engine run's
        snapshot stays identical to pre-router serving."""
        st = ServingTelemetry()
        st.on_submit(1)
        st.on_token(1)
        st.on_finish(1)
        assert "rejected" not in st.percentiles()
        st.on_submit(2)
        st.on_reject(2)
        assert st.percentiles()["rejected"] == 1

    def test_handoff_anchoring_spans_replicas(self):
        """Regression (ISSUE-20 satellite): a prefill->decode handoff
        must keep ONE latency story per request. The prefill side keeps
        its TTFT sample (the first token was produced there) and
        forgets the request WITHOUT counting a rejection; the decode
        side registers the request anchored at the ORIGINAL submit
        stamp and must never take a second TTFT sample."""
        tel_p = ServingTelemetry(interval=1)
        tel_d = ServingTelemetry(interval=1)
        tel_p.on_submit(7, klass=2)
        time.sleep(0.01)
        tel_p.on_token(7)                  # TTFT sampled on P
        stamp = tel_p.submit_stamp(7)
        assert stamp is not None
        assert tel_p.klass_of(7) == 2
        ttft_samples = len(tel_p._ttft_ms)
        tel_p.on_handoff_out(7)
        p = tel_p.percentiles()
        assert p.get("rejected", 0) == 0   # handoff is not a shed
        assert len(tel_p._ttft_ms) == ttft_samples  # sample survives
        assert 7 not in tel_p._live and 7 not in tel_p._started
        assert p["handoffs_out"] == 1
        tel_d.on_handoff_in(7, klass=2, submit_ts=stamp)
        assert tel_d.klass_of(7) == 2
        assert tel_d.submit_stamp(7) == stamp   # original anchor
        tel_d.on_token(7)
        tel_d.on_token(7)
        tel_d.on_dispatch(active=1)
        d = tel_d.percentiles()
        assert "ttft_ms_p50" not in d or d["ttft_ms_p50"] is None, \
            "decode side must not take a second TTFT sample"
        assert d["tpot_ms_p50"] is not None
        assert d["handoffs_in"] == 1
        tel_d.on_finish(7)
        assert tel_d.percentiles()["completed"] == 1

    def test_handoff_keys_absent_without_handoffs(self):
        """Disagg-off byte-identity: the handoffs_in/out keys may only
        appear once a handoff actually happened — a colocated engine's
        snapshot stays identical to pre-disaggregation serving."""
        st = ServingTelemetry()
        st.on_submit(1)
        st.on_token(1)
        st.on_finish(1)
        p = st.percentiles()
        assert "handoffs_in" not in p and "handoffs_out" not in p

    def test_dispatch_skips_queued_requests(self):
        """Regression (review finding): on_dispatch runs per engine
        step — it must visit only requests past their first token, not
        the whole admission backlog (O(queued) per step at 10k queued
        requests)."""
        st = ServingTelemetry()
        for uid in range(50):
            st.on_submit(uid)               # queued, never started
        st.on_submit("hot")
        st.on_token("hot")
        st.on_token("hot")
        assert set(st._started) == {"hot"}
        st.on_dispatch(active=1)
        assert st.percentiles()["tpot_ms_p50"] is not None
        st.on_finish("hot")
        assert not st._started              # pruned on finish
        assert len(st._live) == 50          # queue untouched


# ----------------------------------------------- engine integration + chaos
def _tiny_engine(tmp_path=None, telemetry=None, tp=1):
    from deepspeed_tpu.models.gpt2 import GPT2, GPT2_TINY
    from deepspeed_tpu.utils import groups
    from deepspeed_tpu.utils.groups import TopologyConfig
    topo = None
    if tp > 1:
        topo = groups.initialize(TopologyConfig(tensor_parallel_size=tp))
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "steps_per_print": 0,
    }
    if telemetry is not None:
        config["telemetry"] = telemetry
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2(GPT2_TINY), config=config,
        **({"topology": topo} if topo is not None else {}))
    rng = np.random.RandomState(0)
    batch = {"input_ids": rng.randint(
        0, 1024, (engine.config.train_batch_size, 128)).astype(np.int32)}
    return engine, batch


class TestEngineTelemetry:
    def test_step_analytics_flow_through_fanout(self):
        engine, batch = _tiny_engine(
            telemetry={"enabled": True, "interval_steps": 3,
                       "cluster_agg": False})
        stub = _StubMonitor()
        engine.monitor.monitors.append(stub)
        engine.monitor.enabled = True
        try:
            for _ in range(6):
                engine.train_batch(batch)
            engine.telemetry.drain()
            tags = {t for t, _, _ in stub.events}
            assert "Train/Telemetry/step_time_ms_p50" in tags
            assert "Train/Telemetry/goodput_pct" in tags
            assert "Train/Telemetry/mfu_pct" in tags
            for t in tags:
                assert t in TAG_SCHEMA, f"undocumented tag {t}"
            snap = engine.telemetry_report()
            assert snap["flops_source"] == "hlo"
            assert snap["mfu_pct"] > 0
            assert snap["tokens_per_sec_chip"] > 0
            assert "collectives" in snap
        finally:
            engine.telemetry.close()

    def test_disabled_by_default_without_monitor(self, monkeypatch):
        for v in ("DSTPU_TELEMETRY", "DSTPU_FLIGHTREC_DIR",
                  "ELASTIC_GENERATION"):
            monkeypatch.delenv(v, raising=False)
        engine, _ = _tiny_engine()
        assert engine.telemetry is None
        assert engine.telemetry_report() is None

    def test_auto_enable_is_rank_symmetric(self, monkeypatch, tmp_path):
        """Regression (review finding): 'auto' must resolve from the
        CONFIG monitor flag, not MonitorMaster.enabled (rank-0-gated) —
        the allgather cluster transport is collective, so rank-0-only
        arming would hang a multi-process pod at the first flush."""
        import jax
        for v in ("DSTPU_TELEMETRY", "DSTPU_FLIGHTREC_DIR",
                  "ELASTIC_GENERATION"):
            monkeypatch.delenv(v, raising=False)
        monkeypatch.setattr(jax, "process_index", lambda: 1)
        from deepspeed_tpu.models.gpt2 import GPT2, GPT2_TINY
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=GPT2(GPT2_TINY), config={
                "train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "bf16": {"enabled": True},
                "csv_monitor": {"enabled": True,
                                "output_path": str(tmp_path)},
                "telemetry": {"cluster_agg": False},
            })
        try:
            assert not engine.monitor.enabled     # rank 1 writes nothing
            assert engine.telemetry is not None   # but telemetry is armed
        finally:
            engine.telemetry.close()


@pytest.mark.chaos
class TestChaosFlightRecorder:
    def test_kill_mid_save_leaves_black_box(self, tmp_path):
        """The ISSUE-9 acceptance chaos: a worker killed mid-run leaves
        a flight-recorder dump whose last events include the fired
        fault point AND the tier its generation restored from."""
        ckpt = str(tmp_path / "ckpt")
        engine, batch = _tiny_engine(
            telemetry={"enabled": True, "interval_steps": 100,
                       "cluster_agg": False})
        try:
            engine.train_batch(batch)
            engine.save_checkpoint(ckpt)
            # resume: the restore (tier=durable) enters the flight ring
            engine2, batch2 = _tiny_engine(
                telemetry={"enabled": True, "interval_steps": 100,
                           "cluster_agg": False})
            try:
                engine2.load_checkpoint(ckpt)
                assert engine2.last_restore_tier == "durable"
                engine2.train_batch(batch2)
                fault_injection.reset()
                fault_injection.arm("write", fails=1, kill=True)
                with pytest.raises(fault_injection.SimulatedKill):
                    engine2.save_checkpoint(ckpt)
            finally:
                fault_injection.reset()
                engine2.telemetry.close()
            dump = flight_recorder.read_dump(
                os.path.join(ckpt, "flightrec"),
                engine2.telemetry.flight.node)
            assert dump is not None, "no flight-recorder dump written"
            assert dump["reason"] == "crash"
            kinds = [e["kind"] for e in dump["events"]]
            assert kinds[-1] == "crash"
            restores = [e for e in dump["events"]
                        if e["kind"] == "restore"]
            assert restores and restores[-1]["tier"] == "durable"
            faults = [e for e in dump["events"]
                      if e["kind"] == "fault_point"]
            assert faults and faults[-1]["point"] == "write"
            assert any(k == "step" for k in kinds)
        finally:
            engine.telemetry.close()

    def test_agent_attaches_flight_record(self, tmp_path):
        """Agent side of the black box: a failed host's dump is read on
        membership change and attached to the classification."""
        from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent
        root = str(tmp_path / "fr")
        rec = FlightRecorder(node="h1")
        rec.set_root(root)
        rec.record("fault_point", point="write", injected=True)
        rec.record("crash", error="FaultError: injected")
        rec.dump(reason="crash")
        agent = DSElasticAgent(lambda hosts: [], ["h0", "h1"],
                               flightrec_root=root)
        env = agent.worker_env("h1")
        assert env["DSTPU_FLIGHTREC_DIR"] == root
        assert env["DSTPU_FLIGHTREC_NODE"] == "h1"
        agent._handle_membership_change({"h1": "dead"})
        assert "h1" in agent.last_failure_records
        tail = agent.last_failure_records["h1"]["events"]
        assert tail[-1]["kind"] == "crash"
        assert agent.hosts == ["h0"]


class TestOffCriticalPath:
    def test_dp2_step_time_within_noise(self):
        """ISSUE-9 acceptance: per-step wall time with telemetry on is
        within noise of telemetry off (dp=2 virtual mesh). The step
        path only appends to a ring; flushes (including the one-time
        cost-analysis compile) land in warmup."""
        def run(telemetry):
            engine, batch = _tiny_engine(telemetry=telemetry, tp=4)
            # warmup past compile AND past the first flush (the lazy
            # cost capture compiles once at step==interval)
            for _ in range(6):
                engine.train_batch(batch)
            times = []
            for _ in range(12):
                t0 = time.perf_counter()
                engine.train_batch(batch)
                times.append(time.perf_counter() - t0)
            if engine.telemetry is not None:
                engine.telemetry.drain()
                assert engine.telemetry.last, "telemetry never flushed"
                engine.telemetry.close()
            return float(np.median(times))

        t_off = run(telemetry={"enabled": False})
        t_on = run(telemetry={"enabled": True, "interval_steps": 5,
                              "cluster_agg": False})
        assert t_on <= t_off * 1.5 + 0.05, (
            f"telemetry on the critical path: median step "
            f"{t_on * 1e3:.2f}ms (on) vs {t_off * 1e3:.2f}ms (off)")
