"""Dropless MoE at pod scale: grouped-GEMM Pallas kernel + hierarchical
ICI->DCN expert all_to_all.

Tier-1 coverage for the production MoE path: grouped-kernel parity vs
``lax.ragged_dot`` (uneven/empty groups, bf16 grads, the fused SwiGLU
chain), the warm/cold autotune HLO-identity contract for the
``moe_grouped_mm`` op, the hierarchical two-stage exchange (engages only
with a data_outer axis; int8 clamp on the DCN leg only; loss parity on
the virtual mesh), the padding audit (pad rows can never skew
group_sizes or the combine), and the EP x TP / EP x ring compositions.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.autotuning import kernel_dispatch
from deepspeed_tpu.moe.sharded_moe import (moe_swiglu_ragged_ep,
                                           resolve_grouped_params,
                                           resolve_hierarchical_a2a)
from deepspeed_tpu.ops.pallas.grouped_matmul import (TUNE_DEFAULTS,
                                                     grouped_matmul,
                                                     grouped_swiglu)
from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.groups import TopologyConfig


@pytest.fixture(autouse=True)
def _pristine_dispatch(tmp_path, monkeypatch):
    """Private winner-cache path + reset process-global dispatch state
    (the grouped-backend resolution consults it under "auto")."""
    monkeypatch.setenv("DSTPU_AUTOTUNE_CACHE",
                       str(tmp_path / "kernel_autotune.json"))
    monkeypatch.delenv("DSTPU_AUTOTUNE", raising=False)
    kernel_dispatch.reset()
    yield
    kernel_dispatch.reset()


def _swiglu_ref(x, w1, w3, w2, gs):
    g = jax.lax.ragged_dot(x, w1, gs)
    u = jax.lax.ragged_dot(x, w3, gs)
    return jax.lax.ragged_dot(jax.nn.silu(g) * u, w2, gs)


class TestGroupedKernelParity:
    """ops/pallas/grouped_matmul.py vs lax.ragged_dot (interpreter mode
    on CPU — the driver's kernel_parity.py re-proves on real Mosaic)."""

    def _data(self, dtype, S=192, K=128, N=256, E=4, seed=0):
        ks = jax.random.split(jax.random.key(seed), 2)
        x = jax.random.normal(ks[0], (S, K), dtype) * 0.3
        w = jax.random.normal(ks[1], (E, K, N), dtype) * 0.1
        return x, w

    @pytest.mark.parametrize("sizes", [
        [50, 0, 120, 22],        # uneven + an empty group
        [192, 0, 0, 0],          # everything on one expert
        [0, 0, 0, 0],            # all groups empty (zero output)
        [1, 63, 100, 28],
    ])
    def test_forward_matches_ragged_dot(self, sizes):
        x, w = self._data(jnp.float32)
        gs = jnp.asarray(sizes, jnp.int32)
        got = jax.jit(lambda x, w: grouped_matmul(x, w, gs,
                                                  block_m=64))(x, w)
        ref = jax.lax.ragged_dot(x, w, gs)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_rows_beyond_groups_are_zero(self):
        """The ragged_dot tail contract the EP transport relies on:
        rows past sum(group_sizes) come out exactly zero."""
        x, w = self._data(jnp.float32)
        gs = jnp.asarray([40, 30, 0, 10], jnp.int32)
        got = np.asarray(grouped_matmul(x, w, gs, block_m=64))
        assert np.all(got[80:] == 0.0)
        assert np.abs(got[:80]).max() > 0

    def test_bf16_grads_match_ragged_dot(self):
        x, w = self._data(jnp.bfloat16)
        gs = jnp.asarray([37, 51, 3, 101], jnp.int32)

        def lk(x, w):
            return jnp.sum(grouped_matmul(x, w, gs, block_m=64)
                           .astype(jnp.float32) ** 2)

        def lr(x, w):
            return jnp.sum(jax.lax.ragged_dot(x, w, gs)
                           .astype(jnp.float32) ** 2)

        ga = jax.grad(lk, (0, 1))(x, w)
        gr = jax.grad(lr, (0, 1))(x, w)
        for a, b, n in zip(ga, gr, ("dx", "dw")):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                rtol=5e-2, atol=5e-2, err_msg=n)

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_fused_swiglu_chain(self, dtype):
        """The fused w1/w3 -> silu*mul -> w2 launch: forward and all
        four cotangents against the three-ragged_dot reference."""
        S, K, F, E = 160, 128, 256, 4
        ks = jax.random.split(jax.random.key(1), 4)
        x = jax.random.normal(ks[0], (S, K), dtype) * 0.3
        w1 = jax.random.normal(ks[1], (E, K, F), dtype) * 0.1
        w3 = jax.random.normal(ks[2], (E, K, F), dtype) * 0.1
        w2 = jax.random.normal(ks[3], (E, F, K), dtype) * 0.1
        gs = jnp.asarray([60, 0, 89, 11], jnp.int32)
        tol = dict(rtol=1e-4, atol=1e-4) if dtype == jnp.float32 \
            else dict(rtol=5e-2, atol=5e-2)
        got = grouped_swiglu(x, w1, w3, w2, gs, block_m=64)
        np.testing.assert_allclose(
            np.asarray(got, np.float32),
            np.asarray(_swiglu_ref(x, w1, w3, w2, gs), np.float32), **tol)

        ga = jax.grad(lambda *a: jnp.sum(
            grouped_swiglu(*a, gs, block_m=64).astype(jnp.float32) ** 2),
            (0, 1, 2, 3))(x, w1, w3, w2)
        gr = jax.grad(lambda *a: jnp.sum(
            _swiglu_ref(*a, gs).astype(jnp.float32) ** 2),
            (0, 1, 2, 3))(x, w1, w3, w2)
        for a, b, n in zip(ga, gr, ("dx", "dw1", "dw3", "dw2")):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                err_msg=n, **tol)

    def test_unaligned_dims_fall_back(self):
        """Dims that cannot form tile-aligned blocks take the ragged_dot
        fallback (identical semantics, no crash) — the tiny-model path."""
        x = jax.random.normal(jax.random.key(0), (12, 16), jnp.float32)
        w = jax.random.normal(jax.random.key(1), (2, 16, 24), jnp.float32)
        gs = jnp.asarray([5, 7], jnp.int32)
        np.testing.assert_allclose(
            np.asarray(grouped_matmul(x, w, gs)),
            np.asarray(jax.lax.ragged_dot(x, w, gs)), rtol=1e-6)


class TestGroupedDispatch:
    """The 'moe_grouped_mm' knob/winner-cache contract."""

    def test_knob_resolution(self):
        assert resolve_grouped_params(False, 256, 4, 128, 256,
                                      jnp.float32)["backend"] == "ragged"
        p = resolve_grouped_params(True, 256, 4, 128, 256, jnp.float32)
        assert p["backend"] == "kernel"
        # "auto" on a cold cache = the ragged defaults (current behavior)
        kernel_dispatch.configure(mode="cache_only")
        assert resolve_grouped_params("auto", 256, 4, 128, 256,
                                      jnp.float32) == TUNE_DEFAULTS

    def test_warm_cache_steers_auto(self):
        """A cached kernel winner flips the "auto" resolution — proven
        at the jaxpr level (the kernel program contains a pallas call,
        the ragged program contains ragged_dot)."""
        from deepspeed_tpu.autotuning import KernelCache
        from deepspeed_tpu.ops.pallas._common import moe_grouped_bucket
        path = os.environ["DSTPU_AUTOTUNE_CACHE"]
        S, E, M, F = 256, 4, 128, 256
        bucket = moe_grouped_bucket(S, E, M, F)
        c = KernelCache()
        c.put(kernel_dispatch.device_kind(), "moe_grouped_mm", bucket,
              "float32", {"backend": "kernel", "block_m": 64,
                          "block_n": 128, "block_k": 128})
        c.save(path)
        kernel_dispatch.configure(mode="cache_only")
        p = resolve_grouped_params("auto", S, E, M, F, jnp.float32)
        assert p["backend"] == "kernel" and p["block_m"] == 64

    def test_cold_cache_hlo_identical_to_ragged(self):
        """moe_layer_ragged with grouped_kernel="auto" on a COLD cache
        lowers to the byte-identical program of grouped_kernel=False —
        the established cold-cache contract."""
        from deepspeed_tpu.moe.sharded_moe import moe_layer_ragged
        kernel_dispatch.configure(mode="cache_only")
        rs = np.random.RandomState(0)
        x = jnp.asarray(rs.randn(64, 128), jnp.float32)
        gate_w = jnp.asarray(rs.randn(128, 4) * 0.1, jnp.float32)
        wi = jnp.asarray(rs.randn(4, 128, 256) * 0.1, jnp.float32)
        bi = jnp.zeros((4, 256), jnp.float32)
        wo = jnp.asarray(rs.randn(4, 256, 128) * 0.1, jnp.float32)
        bo = jnp.zeros((4, 128), jnp.float32)

        def lower(knob):
            return jax.jit(
                lambda *a: moe_layer_ragged(*a, k=2,
                                            grouped_kernel=knob)
            ).lower(x, gate_w, wi, bi, wo, bo).as_text()

        assert lower("auto") == lower(False)
        # and the kernel knob produces a genuinely different program
        assert lower(True) != lower(False)


def _swiglu_params(M=16, F=32, E=8, seed=0):
    rs = np.random.RandomState(seed)
    return (jnp.asarray(rs.randn(M, E) * 0.1, jnp.float32),
            jnp.asarray(rs.randn(E, M, F) * 0.1, jnp.float32),
            jnp.asarray(rs.randn(E, M, F) * 0.1, jnp.float32),
            jnp.asarray(rs.randn(E, F, M) * 0.1, jnp.float32))


def _swiglu_dense(x, gate_w, w1, w3, w2, k=2):
    logits = x.astype(jnp.float32) @ gate_w
    probs = jax.nn.softmax(logits, axis=-1)
    weights, experts = jax.lax.top_k(probs, k)
    weights = weights / jnp.sum(weights, axis=-1, keepdims=True)
    y = jnp.zeros_like(x)
    for e in range(gate_w.shape[-1]):
        o = (jax.nn.silu(x @ w1[e]) * (x @ w3[e])) @ w2[e]
        w = jnp.sum(jnp.where(experts == e, weights, 0.0), axis=-1)
        y = y + o * w[:, None]
    return y


class TestHierarchicalA2A:
    """The two-stage ICI->DCN expert exchange (acceptance: engages only
    when the mesh has a data_outer axis; int8 clamp on the DCN leg
    only; loss parity on the virtual mesh)."""

    def _outer_mesh(self, tensor=1):
        groups.reset()
        # dp with zero_shard_size -> data_outer=2 on the 8-device world
        return groups.initialize(TopologyConfig(
            data_parallel_size=4 // tensor, zero_shard_size=2 // tensor,
            expert_parallel_size=2, tensor_parallel_size=tensor))

    def test_resolution_gating(self):
        assert resolve_hierarchical_a2a("auto", 2, 8, 2) is True
        assert resolve_hierarchical_a2a("auto", 1, 8, 2) is False
        assert resolve_hierarchical_a2a("auto", 2, 6, 2) is False
        assert resolve_hierarchical_a2a(False, 2, 8, 2) is False
        assert resolve_hierarchical_a2a(True, 1, 8, 2) is False
        with pytest.raises(ValueError, match="divisible"):
            resolve_hierarchical_a2a(True, 2, 6, 2)

    @pytest.mark.parametrize("odd_tokens", [False, True])
    def test_loss_parity_at_data_outer(self, odd_tokens):
        """y at data_outer=2 x expert=2 (experts over the combined grid,
        two-stage exchange) == the dense single-shard reference."""
        gate_w, w1, w3, w2 = _swiglu_params()
        rs = np.random.RandomState(1)
        S = 15 if odd_tokens else 16
        x = jnp.asarray(rs.randn(S, 16) * 0.3, jnp.float32)
        ref = _swiglu_dense(x, gate_w, w1, w3, w2)
        topo = self._outer_mesh()
        with jax.set_mesh(topo.mesh):
            y = jax.jit(lambda *a: moe_swiglu_ragged_ep(*a, k=2))(
                x, gate_w, w1, w3, w2)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_engages_only_with_data_outer_axis(self):
        """Acceptance: the staged exchange (an all_to_all over
        'data_outer') appears in the traced program iff the mesh has a
        data_outer axis > 1."""
        gate_w, w1, w3, w2 = _swiglu_params()
        x = jnp.zeros((16, 16), jnp.float32)
        f = lambda *a: moe_swiglu_ragged_ep(*a, k=2)   # noqa: E731
        topo = self._outer_mesh()
        with jax.set_mesh(topo.mesh):
            jaxpr_hier = str(jax.make_jaxpr(f)(x, gate_w, w1, w3, w2))
        groups.reset()
        flat = groups.initialize(TopologyConfig(expert_parallel_size=4))
        with jax.set_mesh(flat.mesh):
            jaxpr_flat = str(jax.make_jaxpr(f)(x, gate_w, w1, w3, w2))
        # the DCN hop is an all_to_all whose axis_name is data_outer —
        # present iff the staged path engaged (the mesh-shape dict in
        # the jaxpr always NAMES the axis, so probe the collective)
        probe = "axis_name=data_outer"
        assert probe in jaxpr_hier
        assert "all_to_all" in jaxpr_flat
        assert probe not in jaxpr_flat

    def test_int8_clamp_on_dcn_leg_only(self):
        """dcn_quantize perturbs the hierarchical path (bounded int8
        round-trip error on the DCN legs) but is a NO-OP on a flat mesh
        — there is no DCN leg to clamp (bitwise-identical output)."""
        gate_w, w1, w3, w2 = _swiglu_params()
        rs = np.random.RandomState(2)
        x = jnp.asarray(rs.randn(16, 16) * 0.3, jnp.float32)
        ref = _swiglu_dense(x, gate_w, w1, w3, w2)
        topo = self._outer_mesh()
        with jax.set_mesh(topo.mesh):
            yq = jax.jit(lambda *a: moe_swiglu_ragged_ep(
                *a, k=2, dcn_quantize=True))(x, gate_w, w1, w3, w2)
        err = np.abs(np.asarray(yq) - np.asarray(ref)).max()
        assert 0 < err < 0.05, err     # clamped, not broken
        groups.reset()
        flat = groups.initialize(TopologyConfig(expert_parallel_size=4))
        with jax.set_mesh(flat.mesh):
            ya = jax.jit(lambda *a: moe_swiglu_ragged_ep(
                *a, k=2, dcn_quantize=True))(x, gate_w, w1, w3, w2)
            yb = jax.jit(lambda *a: moe_swiglu_ragged_ep(
                *a, k=2, dcn_quantize=False))(x, gate_w, w1, w3, w2)
        np.testing.assert_array_equal(np.asarray(ya), np.asarray(yb))

    def test_hier_with_tp_and_kernel_backend(self):
        """data_outer x expert x tensor with the grouped kernel forced:
        the full composition still matches the dense reference (tiny
        dims -> the kernel wrapper falls back per-call where blocks
        cannot form; the composition contract is what's under test)."""
        gate_w, w1, w3, w2 = _swiglu_params()
        rs = np.random.RandomState(3)
        x = jnp.asarray(rs.randn(16, 16) * 0.3, jnp.float32)
        ref = _swiglu_dense(x, gate_w, w1, w3, w2)
        topo = self._outer_mesh(tensor=2)
        with jax.set_mesh(topo.mesh):
            y = jax.jit(lambda *a: moe_swiglu_ragged_ep(
                *a, k=2, grouped_kernel=True))(x, gate_w, w1, w3, w2)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)


class TestPaddingAudit:
    """Pad rows added for the shard split must never skew group_sizes
    or the combine (their gate weights are masked to zero and they ride
    with the invalid expert id)."""

    @pytest.mark.parametrize("hier", [False, True])
    def test_counts_exclude_pad_rows(self, hier):
        gate_w, w1, w3, w2 = _swiglu_params()
        rs = np.random.RandomState(4)
        S, k = 13, 2                   # 13 % 4 != 0 -> 3 pad rows
        x = jnp.asarray(rs.randn(S, 16) * 0.3, jnp.float32)
        groups.reset()
        topo = groups.initialize(
            TopologyConfig(data_parallel_size=4, zero_shard_size=2,
                           expert_parallel_size=2) if hier
            else TopologyConfig(expert_parallel_size=4))
        with jax.set_mesh(topo.mesh):
            y, counts = jax.jit(lambda *a: moe_swiglu_ragged_ep(
                *a, k=k, return_counts=True))(x, gate_w, w1, w3, w2)
        # the audit observable: every real token dispatches exactly k
        # times, pad rows never enter a group
        assert int(np.asarray(counts).sum()) == S * k
        ref = _swiglu_dense(x, gate_w, w1, w3, w2)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)


class TestComposition:
    """EP x TP and EP x ring are supported scenarios."""

    def test_ep_tp_with_kernel_backend(self):
        """EP x TP through the grouped kernel at kernel-aligned dims
        (M=128, F=256): interpret-mode Pallas inside the full-manual
        shard_map region matches the dense reference."""
        gate_w, w1, w3, w2 = _swiglu_params(M=128, F=256, E=4)
        rs = np.random.RandomState(5)
        x = jnp.asarray(rs.randn(24, 128) * 0.3, jnp.float32)
        ref = _swiglu_dense(x, gate_w, w1, w3, w2)
        groups.reset()
        topo = groups.initialize(TopologyConfig(
            expert_parallel_size=2, tensor_parallel_size=2))
        with jax.set_mesh(topo.mesh):
            y = jax.jit(lambda *a: moe_swiglu_ragged_ep(
                *a, k=2, grouped_kernel=True))(x, gate_w, w1, w3, w2)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_ep_ring_model_matches_unsharded(self):
        """EP x ring (long-context MoE): GPT2MoE with zigzag ring
        attention on an expert=2 x seq=2 mesh reproduces the unsharded
        model's logits."""
        from deepspeed_tpu.models import GPT2MoE, GPT2MoEConfig
        kw = dict(n_layer=2, n_head=4, d_model=32, max_seq_len=32,
                  vocab_size=128, remat=False, dtype="float32",
                  num_experts=4, moe_top_k=2, moe_backend="ragged")
        dense = GPT2MoE(GPT2MoEConfig(**kw))
        ring = GPT2MoE(GPT2MoEConfig(attention_backend="ring", **kw))
        params = dense.init(jax.random.key(0))
        # batch divisible by the batch axes (data x expert = 4 on the
        # 8-device expert=2 x seq=2 mesh)
        ids = jax.random.randint(jax.random.key(1), (4, 32), 0, 128,
                                 dtype=jnp.int32)
        ref = dense.apply(params, ids)
        groups.reset()
        topo = groups.initialize(TopologyConfig(
            expert_parallel_size=2, seq_parallel_size=2))
        with jax.set_mesh(topo.mesh):
            out = jax.jit(
                lambda p, i: ring.apply(p, i, seq_sharded=True))(
                params, ids)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=5e-4, atol=5e-5)

    def test_engine_reports_in_scan_a2a(self):
        """engine.verify_comm_overlap on an EP mesh reports the expert
        all_to_all INSIDE the scan body (in_loop_by_op) — the dispatch
        overlaps layer compute instead of serializing after the scan."""
        import deepspeed_tpu
        from deepspeed_tpu.models import GPT2MoE, GPT2MoEConfig
        groups.reset()
        topo = groups.initialize(TopologyConfig(expert_parallel_size=2))
        cfg = GPT2MoEConfig(n_layer=2, n_head=2, d_model=32,
                            max_seq_len=16, vocab_size=128, remat=True,
                            dtype="float32", num_experts=4, moe_top_k=2,
                            moe_backend="ragged")
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=GPT2MoE(cfg), topology=topo,
            config={"train_micro_batch_size_per_gpu": 1,
                    "gradient_accumulation_steps": 1,
                    "steps_per_print": 0,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 2}})
        # engine installed the moe config block on the model
        assert engine.model._moe_cfg.grouped_kernel == "auto"
        rng = np.random.RandomState(0)
        batch = {"input_ids": rng.randint(
            0, cfg.vocab_size,
            (engine.config.train_batch_size, cfg.max_seq_len))
            .astype(np.int32)}
        report = engine.verify_comm_overlap(batch)
        assert report["in_loop_by_op"].get("all-to-all", 0) >= 1, \
            report["in_loop_by_op"]
