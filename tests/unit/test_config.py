import pytest

from deepspeed_tpu.runtime.config import DeepSpeedConfig, DeepSpeedConfigError


def test_batch_triad_all_given():
    cfg = DeepSpeedConfig(
        {"train_batch_size": 32, "train_micro_batch_size_per_gpu": 2,
         "gradient_accumulation_steps": 2}, dp_world_size=8)
    assert cfg.train_batch_size == 32
    assert cfg.gradient_accumulation_steps == 2


def test_batch_triad_mismatch_raises():
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig(
            {"train_batch_size": 33, "train_micro_batch_size_per_gpu": 2,
             "gradient_accumulation_steps": 2}, dp_world_size=8)


def test_batch_triad_derive_gas():
    cfg = DeepSpeedConfig(
        {"train_batch_size": 64, "train_micro_batch_size_per_gpu": 2},
        dp_world_size=8)
    assert cfg.gradient_accumulation_steps == 4


def test_batch_triad_derive_micro():
    cfg = DeepSpeedConfig(
        {"train_batch_size": 64, "gradient_accumulation_steps": 4},
        dp_world_size=8)
    assert cfg.train_micro_batch_size_per_gpu == 2


def test_batch_triad_from_micro_only():
    cfg = DeepSpeedConfig({"train_micro_batch_size_per_gpu": 4}, dp_world_size=8)
    assert cfg.train_batch_size == 32
    assert cfg.gradient_accumulation_steps == 1


def test_batch_triad_nothing_raises():
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({}, dp_world_size=8)


def test_precision_exclusive():
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_batch_size": 8,
                         "fp16": {"enabled": True},
                         "bf16": {"enabled": True}}, dp_world_size=8)


def test_zero_stage_validation():
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_batch_size": 8,
                         "zero_optimization": {"stage": 5}}, dp_world_size=8)


def test_defaults_and_blocks():
    cfg = DeepSpeedConfig({
        "train_batch_size": 16,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2, "overlap_comm": False},
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "scheduler": {"type": "WarmupLR",
                      "params": {"warmup_num_steps": 10}},
        "gradient_clipping": 1.0,
    }, dp_world_size=8)
    assert cfg.zero.stage == 2
    assert not cfg.zero.overlap_comm
    assert cfg.optimizer.type == "AdamW"
    assert cfg.scheduler.params["warmup_num_steps"] == 10
    assert cfg.gradient_clipping == 1.0
    import jax.numpy as jnp
    assert cfg.precision_dtype == jnp.bfloat16


def test_comm_overlap_defaults():
    cfg = DeepSpeedConfig({"train_batch_size": 8}, dp_world_size=8)
    co = cfg.comm_overlap
    assert co.enabled == "auto"
    assert co.bucket_mb == 32
    assert co.prefetch is True
    assert co.hierarchical == "auto"
    assert co.dcn_quantize is False
    # auto resolution: program annotations only when dp > 1 / a real
    # data_outer split exists
    assert not co.resolve_enabled(1)
    assert co.resolve_enabled(8)
    assert not co.resolve_hierarchical(1)
    assert co.resolve_hierarchical(2)


def test_comm_overlap_block_parses():
    cfg = DeepSpeedConfig({
        "train_batch_size": 8,
        "comm_overlap": {"enabled": True, "bucket_mb": 8,
                         "prefetch": False, "hierarchical": False,
                         "dcn_quantize": True},
    }, dp_world_size=8)
    co = cfg.comm_overlap
    assert co.enabled is True and co.resolve_enabled(1)
    assert co.bucket_mb == 8
    assert co.prefetch is False
    assert not co.resolve_hierarchical(4)
    assert co.dcn_quantize is True


def test_comm_overlap_validation():
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_batch_size": 8,
                         "comm_overlap": {"enabled": "yes"}},
                        dp_world_size=8)
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_batch_size": 8,
                         "comm_overlap": {"hierarchical": "always"}},
                        dp_world_size=8)
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_batch_size": 8,
                         "comm_overlap": {"bucket_mb": -1}},
                        dp_world_size=8)


def test_sequence_block_defaults_and_parses():
    cfg = DeepSpeedConfig({"train_batch_size": 8}, dp_world_size=8)
    sq = cfg.sequence
    assert sq.layout == "zigzag"
    assert sq.block_kernel == "auto"
    assert sq.double_buffer is True
    cfg = DeepSpeedConfig({
        "train_batch_size": 8,
        "sequence": {"layout": "contiguous", "block_kernel": False,
                     "double_buffer": False},
    }, dp_world_size=8)
    sq = cfg.sequence
    assert sq.layout == "contiguous"
    assert sq.block_kernel is False
    assert sq.double_buffer is False


def test_sequence_block_validation():
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_batch_size": 8,
                         "sequence": {"layout": "striped"}},
                        dp_world_size=8)
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_batch_size": 8,
                         "sequence": {"block_kernel": "maybe"}},
                        dp_world_size=8)


def test_moe_block_defaults_and_parses():
    cfg = DeepSpeedConfig({"train_batch_size": 8}, dp_world_size=8)
    mo = cfg.moe
    assert mo.grouped_kernel == "auto"
    assert mo.hierarchical_a2a == "auto"
    assert mo.dcn_quantize is False
    cfg = DeepSpeedConfig({
        "train_batch_size": 8,
        "moe": {"grouped_kernel": True, "hierarchical_a2a": False,
                "dcn_quantize": True},
    }, dp_world_size=8)
    mo = cfg.moe
    assert mo.grouped_kernel is True
    assert mo.hierarchical_a2a is False
    assert mo.dcn_quantize is True


def test_moe_block_validation():
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_batch_size": 8,
                         "moe": {"grouped_kernel": "fast"}},
                        dp_world_size=8)
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_batch_size": 8,
                         "moe": {"hierarchical_a2a": "always"}},
                        dp_world_size=8)
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_batch_size": 8,
                         "moe": {"dcn_quantize": "yes"}},
                        dp_world_size=8)


def test_quantize_block_defaults_and_parses():
    cfg = DeepSpeedConfig({"train_batch_size": 8}, dp_world_size=8)
    qz = cfg.quantize
    # None = defer to the per-subsystem knobs (comm_overlap.dcn_quantize
    # / moe.dcn_quantize); the compute levers default hard-off so a
    # config without the block is byte-identical to one with defaults
    assert qz.grad_dcn is None
    assert qz.moe_dcn is None
    assert qz.int8_matmul is False
    assert qz.moe_int8_matmul is False
    cfg = DeepSpeedConfig({
        "train_batch_size": 8,
        "quantize": {"grad_dcn": True, "moe_dcn": False,
                     "int8_matmul": True, "moe_int8_matmul": True},
    }, dp_world_size=8)
    qz = cfg.quantize
    assert qz.grad_dcn is True
    assert qz.moe_dcn is False
    assert qz.int8_matmul is True
    assert qz.moe_int8_matmul is True


def test_quantize_block_auto_spellings_roundtrip():
    raw = {
        "train_batch_size": 8,
        "quantize": {"grad_dcn": "auto", "moe_dcn": "auto",
                     "int8_matmul": "auto", "moe_int8_matmul": "auto"},
    }
    cfg = DeepSpeedConfig(raw, dp_world_size=8)
    qz = cfg.quantize
    assert qz.grad_dcn == "auto"
    assert qz.moe_dcn == "auto"
    assert qz.int8_matmul == "auto"
    assert qz.moe_int8_matmul == "auto"
    # same dict parses twice to the same block (input never mutated)
    cfg2 = DeepSpeedConfig(raw, dp_world_size=8)
    assert cfg2.quantize.int8_matmul == "auto"
    assert cfg2.quantize.grad_dcn == "auto"


def test_quantize_block_validation():
    for field, bad in [
        ("grad_dcn", "yes"),
        ("moe_dcn", "sometimes"),
        ("int8_matmul", "fast"),
        ("int8_matmul", None),
        ("moe_int8_matmul", "yes"),
    ]:
        with pytest.raises(DeepSpeedConfigError):
            DeepSpeedConfig({"train_batch_size": 8,
                             "quantize": {field: bad}},
                            dp_world_size=8)


def test_autotune_defaults():
    cfg = DeepSpeedConfig({"train_batch_size": 8}, dp_world_size=8)
    at = cfg.autotune
    assert at.mode == ""                 # inherit DSTPU_AUTOTUNE env
    assert at.cache_path == ""           # env / ~/.cache default
    assert at.chain_lengths == (8, 24)
    assert at.reps == 3


def test_autotune_block_parses():
    cfg = DeepSpeedConfig({
        "train_batch_size": 8,
        "autotune": {"mode": "on_first_use", "cache_path": "/tmp/x.json",
                     "chain_lengths": [4, 12], "reps": 2},
    }, dp_world_size=8)
    at = cfg.autotune
    assert at.mode == "on_first_use"
    assert at.cache_path == "/tmp/x.json"
    assert at.chain_lengths == (4, 12)   # normalized to a tuple
    assert at.reps == 2


def test_autotune_validation():
    for bad in ({"mode": "always"},
                {"chain_lengths": [8]},
                {"chain_lengths": [24, 8]},
                {"chain_lengths": ["a", "b"]},
                {"reps": 0}):
        with pytest.raises(DeepSpeedConfigError):
            DeepSpeedConfig({"train_batch_size": 8, "autotune": bad},
                            dp_world_size=8)


def test_telemetry_defaults():
    cfg = DeepSpeedConfig({"train_batch_size": 8}, dp_world_size=8)
    t = cfg.telemetry
    assert t.enabled == "auto"
    assert t.interval_steps == 20
    assert t.cluster_agg == "auto"
    assert t.flight_recorder_size == 256
    assert t.profile_port == 0
    assert t.flightrec_dir == ""


def test_telemetry_block_parses():
    cfg = DeepSpeedConfig({
        "train_batch_size": 8,
        "telemetry": {"enabled": True, "interval_steps": 5,
                      "cluster_agg": False, "flight_recorder_size": 64,
                      "profile_port": 9012,
                      "flightrec_dir": "/tmp/fr"},
    }, dp_world_size=8)
    t = cfg.telemetry
    assert t.enabled is True
    assert t.interval_steps == 5
    assert t.cluster_agg is False
    assert t.flight_recorder_size == 64
    assert t.profile_port == 9012
    assert t.flightrec_dir == "/tmp/fr"


def test_telemetry_validation():
    for bad in ({"enabled": "yes"},
                {"interval_steps": 0},
                {"cluster_agg": "maybe"},
                {"flight_recorder_size": 4},
                {"profile_port": -1}):
        with pytest.raises(DeepSpeedConfigError):
            DeepSpeedConfig({"train_batch_size": 8, "telemetry": bad},
                            dp_world_size=8)


def test_telemetry_resolve_enabled(monkeypatch):
    cfg = DeepSpeedConfig({"train_batch_size": 8}, dp_world_size=8)
    t = cfg.telemetry
    for v in ("DSTPU_TELEMETRY", "DSTPU_FLIGHTREC_DIR",
              "ELASTIC_GENERATION"):
        monkeypatch.delenv(v, raising=False)
    assert t.resolve_enabled(monitor_enabled=False) is False
    assert t.resolve_enabled(monitor_enabled=True) is True
    monkeypatch.setenv("ELASTIC_GENERATION", "0")
    assert t.resolve_enabled(monitor_enabled=False) is True
    monkeypatch.delenv("ELASTIC_GENERATION")
    monkeypatch.setenv("DSTPU_FLIGHTREC_DIR", "/tmp/fr")
    assert t.resolve_enabled(monitor_enabled=False) is True


# ----------------------------- inference-side serving config (v2 engine)


def test_serving_paged_kernel_defaults():
    from deepspeed_tpu.inference.v2 import RaggedInferenceEngineConfig
    cfg = RaggedInferenceEngineConfig()
    assert cfg.paged_kernel == "auto"
    assert cfg.paged_block_c == "auto"
    assert cfg.autotune_mode == ""
    assert cfg.autotune_cache == ""


def test_serving_paged_kernel_validation():
    from deepspeed_tpu.inference.v2 import RaggedInferenceEngineConfig
    for bad in ({"paged_kernel": "yes"},
                {"paged_block_c": 0},
                {"paged_block_c": "big"},
                {"autotune_mode": "always"},
                {"splitfuse_tokens": -1}):
        with pytest.raises(ValueError):
            RaggedInferenceEngineConfig(**bad)


def test_serving_config_dict_roundtrip():
    """The engine accepts plain dicts; the dataclass round-trips through
    asdict with the new kernel/autotune fields preserved."""
    from dataclasses import asdict
    from deepspeed_tpu.inference.v2 import RaggedInferenceEngineConfig
    d = {"paged_kernel": True, "paged_block_c": 64,
         "autotune_mode": "cache_only", "autotune_cache": "/tmp/c.json",
         "splitfuse_tokens": 256, "kv_block_size": 64}
    cfg = RaggedInferenceEngineConfig(**d)
    back = asdict(cfg)
    for k, v in d.items():
        assert back[k] == v
    # and the dumped dict reconstructs the identical config
    assert RaggedInferenceEngineConfig(**back) == cfg


def test_checkpoint_hot_tier_defaults():
    cfg = DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1})
    ce = cfg.checkpoint_engine
    assert ce.hot_tier == "auto"
    assert ce.hot_replicas == 1
    assert ce.hot_root == ""
    assert ce.hot_keep_last == 2
    # 'auto' without the launcher-exported ring env: off — even
    # multi-process (the fs transport into node-local tmpfs can't serve
    # a host-loss restore unless the ring/dcn env was wired)
    import os
    for k in ("DSTPU_HOT_PEERS", "DSTPU_HOT_TIER_ROOT",
              "DSTPU_HOT_TRANSPORT"):
        assert k not in os.environ
    assert ce.resolve_hot_tier(1) is False
    assert ce.resolve_hot_tier(4) is False


def test_checkpoint_hot_tier_block_parses(monkeypatch):
    cfg = DeepSpeedConfig(
        {"train_micro_batch_size_per_gpu": 1,
         "checkpoint_engine": {"type": "async", "hot_tier": True,
                               "hot_replicas": 2,
                               "hot_root": "/dev/shm/x",
                               "hot_keep_last": 3}})
    ce = cfg.checkpoint_engine
    assert (ce.hot_tier, ce.hot_replicas, ce.hot_root,
            ce.hot_keep_last) == (True, 2, "/dev/shm/x", 3)
    assert ce.resolve_hot_tier(1) is True
    # env hint flips 'auto' on even single-process
    monkeypatch.setenv("DSTPU_HOT_PEERS", "a,b")
    cfg2 = DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1})
    assert cfg2.checkpoint_engine.resolve_hot_tier(1) is True


def test_checkpoint_hot_tier_validation():
    for bad in ({"hot_tier": "yes"}, {"hot_replicas": -1},
                {"hot_keep_last": 0}):
        with pytest.raises(DeepSpeedConfigError):
            DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1,
                             "checkpoint_engine": bad})


def test_checkpoint_push_backlog_and_drain_knobs(monkeypatch):
    cfg = DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1})
    ce = cfg.checkpoint_engine
    assert ce.hot_max_inflight_pushes == 4
    assert ce.preempt_drain == "auto"
    # 'auto' arms the drain iff something supervises the worker
    for k in ("ELASTIC_GENERATION", "DSTPU_PREEMPT_DRAIN"):
        monkeypatch.delenv(k, raising=False)
    assert ce.resolve_preempt_drain() is False
    monkeypatch.setenv("ELASTIC_GENERATION", "0")
    assert ce.resolve_preempt_drain() is True
    monkeypatch.delenv("ELASTIC_GENERATION")
    monkeypatch.setenv("DSTPU_PREEMPT_DRAIN", "1")
    assert ce.resolve_preempt_drain() is True
    # explicit true/false beats the env either way
    monkeypatch.delenv("DSTPU_PREEMPT_DRAIN")
    on = DeepSpeedConfig(
        {"train_micro_batch_size_per_gpu": 1,
         "checkpoint_engine": {"preempt_drain": True,
                               "hot_max_inflight_pushes": 1}})
    assert on.checkpoint_engine.resolve_preempt_drain() is True
    assert on.checkpoint_engine.hot_max_inflight_pushes == 1
    monkeypatch.setenv("ELASTIC_GENERATION", "0")
    off = DeepSpeedConfig(
        {"train_micro_batch_size_per_gpu": 1,
         "checkpoint_engine": {"preempt_drain": False}})
    assert off.checkpoint_engine.resolve_preempt_drain() is False


def test_checkpoint_push_backlog_and_drain_validation():
    for bad in ({"hot_max_inflight_pushes": 0},
                {"hot_max_inflight_pushes": True},
                {"hot_max_inflight_pushes": "many"},
                {"preempt_drain": "on"}):
        with pytest.raises(DeepSpeedConfigError):
            DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1,
                             "checkpoint_engine": bad})


def test_pipeline_block_defaults():
    cfg = DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1})
    p = cfg.pipeline
    assert (p.stages, p.micro_batches, p.schedule) == (1, 0, "auto")
    assert p.offload_activations == "auto"
    assert p.offload_moments == "auto"
    assert p.offload_double_buffer is True
    # 'auto' schedule defers to the model knob; explicit block wins
    assert p.resolve_schedule("1f1b") == "1f1b"
    assert p.resolve_schedule(None) == "gpipe"


def test_pipeline_block_parses_and_roundtrips():
    raw = {"train_micro_batch_size_per_gpu": 1,
           "pipeline": {"stages": 4, "micro_batches": 8,
                        "schedule": "zb",
                        "offload_activations": True,
                        "offload_moments": False,
                        "offload_double_buffer": False}}
    cfg = DeepSpeedConfig(raw)
    p = cfg.pipeline
    assert (p.stages, p.micro_batches, p.schedule) == (4, 8, "zb")
    assert p.offload_activations is True
    assert p.offload_double_buffer is False
    assert p.resolve_schedule("gpipe") == "zb"   # explicit wins
    # dict round trip preserves the block
    again = DeepSpeedConfig(cfg.to_dict())
    assert again.pipeline.schedule == "zb"
    assert again.pipeline.micro_batches == 8


def test_pipeline_block_validation():
    for bad in ({"schedule": "zb2"}, {"offload_activations": "yes"},
                {"offload_moments": 2}, {"micro_batches": -1},
                {"stages": 0}):
        with pytest.raises(DeepSpeedConfigError):
            DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1,
                             "pipeline": bad})


def test_pipeline_offload_auto_resolution():
    cfg = DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1})
    p = cfg.pipeline
    # the three 'auto' gates: host kind available, pipe axis present,
    # HBM-fit heuristic says the state does NOT fit
    big, hbm = 40 << 30, 16 << 30
    assert p.resolve_offload_activations(
        True, pipe_world=2, est_state_bytes=big, hbm_bytes=hbm) is True
    assert p.resolve_offload_activations(
        True, pipe_world=1, est_state_bytes=big, hbm_bytes=hbm) is False
    assert p.resolve_offload_activations(
        False, pipe_world=2, est_state_bytes=big, hbm_bytes=hbm) is False
    assert p.resolve_offload_activations(
        True, pipe_world=2, est_state_bytes=1 << 30,
        hbm_bytes=hbm) is False
    # unknown sizes never turn offload on blind
    assert p.resolve_offload_activations(
        True, pipe_world=2) is False
    # explicit true wins regardless (host_stage degrades to identity
    # on single-memory-space backends)
    forced = DeepSpeedConfig(
        {"train_micro_batch_size_per_gpu": 1,
         "pipeline": {"offload_activations": True}}).pipeline
    assert forced.resolve_offload_activations(False) is True
    # moments: 'auto' stays off; explicit true needs the backend kind
    assert p.resolve_offload_moments(True) is False
    forced_m = DeepSpeedConfig(
        {"train_micro_batch_size_per_gpu": 1,
         "pipeline": {"offload_moments": True}}).pipeline
    assert forced_m.resolve_offload_moments(True) is True
    assert forced_m.resolve_offload_moments(False) is False


def test_pipeline_hbm_fits():
    from deepspeed_tpu.runtime.config import PipelineConfig
    assert PipelineConfig.hbm_fits(None, 16 << 30)
    assert PipelineConfig.hbm_fits(1 << 30, None)
    assert PipelineConfig.hbm_fits(10 << 30, 16 << 30)
    assert not PipelineConfig.hbm_fits(15 << 30, 16 << 30)  # 0.8 margin


# ------------------------------------------- PR12 auto-knob surfaces

def test_new_auto_knob_defaults():
    """The knobs PR12 opened to 'auto' keep their numeric/bool defaults
    (cold-cache byte-identity depends on it) except the ones whose
    default IS 'auto' by design."""
    cfg = DeepSpeedConfig({"train_batch_size": 8}, dp_world_size=8)
    assert cfg.comm_overlap.scan_unroll == "auto"
    assert cfg.sequence.rotate_chunks == "auto"
    assert cfg.checkpoint_engine.hot_replicas == 1
    assert cfg.moe.dcn_quantize is False
    assert cfg.parallelism == ""


def test_new_auto_knobs_parse_and_roundtrip():
    raw = {
        "train_batch_size": 8,
        "parallelism": "auto",
        "comm_overlap": {"bucket_mb": "auto", "dcn_quantize": "auto",
                         "scan_unroll": 4},
        "sequence": {"rotate_chunks": 2},
        "moe": {"dcn_quantize": "auto"},
        "checkpoint_engine": {"hot_replicas": "auto"},
    }
    cfg = DeepSpeedConfig(raw, dp_world_size=8)
    assert cfg.parallelism == "auto"
    assert cfg.comm_overlap.bucket_mb == "auto"
    assert cfg.comm_overlap.dcn_quantize == "auto"
    assert cfg.comm_overlap.scan_unroll == 4
    assert cfg.sequence.rotate_chunks == 2
    assert cfg.moe.dcn_quantize == "auto"
    assert cfg.checkpoint_engine.hot_replicas == "auto"
    # the same dict parses twice to the same block values (the config
    # never mutates its input)
    cfg2 = DeepSpeedConfig(raw, dp_world_size=8)
    assert cfg2.comm_overlap.bucket_mb == "auto"
    assert cfg2.sequence.rotate_chunks == 2


def test_new_auto_knob_validation():
    for block, field, bad in [
        ("comm_overlap", "bucket_mb", "sometimes"),
        ("comm_overlap", "scan_unroll", 0),
        ("comm_overlap", "scan_unroll", True),
        ("comm_overlap", "dcn_quantize", "yes"),
        ("sequence", "rotate_chunks", 0),
        ("sequence", "rotate_chunks", "maybe"),
        ("moe", "dcn_quantize", "yes"),
        ("checkpoint_engine", "hot_replicas", "many"),
        ("checkpoint_engine", "hot_replicas", -1),
    ]:
        with pytest.raises(DeepSpeedConfigError):
            DeepSpeedConfig({"train_batch_size": 8, block: {field: bad}},
                            dp_world_size=8)


def test_parallelism_top_level_validation():
    for ok in ("", "auto"):
        cfg = DeepSpeedConfig({"train_batch_size": 8,
                               "parallelism": ok}, dp_world_size=8)
        assert cfg.parallelism == ok
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_batch_size": 8, "parallelism": "manual"},
                        dp_world_size=8)
