"""Fast DSElasticAgent coverage (satellite of ISSUE 2; grown by ISSUE 7):
restart-budget exhaustion, shrink below min_hosts, inadmissible-world
rejection, the heartbeat/hang detector, surviving-topology computation,
failure classification with per-class backoff, and hot-tier pointing —
all against stub processes so the suite is deterministic and runs inside
tier-1 (the subprocess-based end-to-end resume tests stay in
test_elastic_agent.py's slow set)."""

import os
import signal
import threading
import time

import pytest

from deepspeed_tpu.elasticity.elastic_agent import (
    CORRUPT_CKPT_EXIT_CODE, PREEMPTED_EXIT_CODE, DSElasticAgent,
    WorldFailure)
from deepspeed_tpu.utils import fault_injection


class StubProc:
    """Popen-shaped test double. rc=None means 'runs forever' until
    kill()/terminate()."""

    def __init__(self, rc=0, exit_after_polls=1):
        self._rc = rc
        self._polls_left = exit_after_polls

    def poll(self):
        if self._rc is None:
            return None
        if self._polls_left > 0:
            self._polls_left -= 1
            return None
        return self._rc

    def kill(self):
        self._rc = -9
        self._polls_left = 0

    def terminate(self):
        self._rc = -15
        self._polls_left = 0

    def wait(self, timeout=None):
        return self._rc


def _launcher(rc_for):
    """rc_for(host, gen_hosts) -> rc (None = hang forever)."""
    def launch(hosts):
        return [(h, StubProc(rc=rc_for(h, hosts))) for h in hosts]
    return launch


class TestRestartBudget:
    def test_budget_exhaustion_raises(self):
        # the first host of every generation dies -> one restart per
        # generation until the budget runs out
        agent = DSElasticAgent(
            _launcher(lambda h, hosts: 1 if h == hosts[0] else 0),
            ["a", "b", "c", "d", "e"], poll_s=0.001, max_restarts=2)
        with pytest.raises(WorldFailure, match="budget"):
            agent.run()
        assert agent.restart_count == 3          # the one over budget

    def test_budget_counts_across_generations(self):
        events = []
        died = {"a": False}

        def rc_for(h, hosts):
            if h == "a" and not died["a"]:
                died["a"] = True
                return 1
            return 0

        agent = DSElasticAgent(
            _launcher(rc_for), ["a", "b", "c"], poll_s=0.001,
            max_restarts=5,
            on_restart=lambda gen, hosts: events.append((gen, hosts)))
        final = agent.run()
        assert final == ["b", "c"]
        assert events == [(1, ["b", "c"])]


class TestShrinkLimits:
    def test_shrink_below_min_hosts_raises(self):
        agent = DSElasticAgent(
            _launcher(lambda h, hosts: 1 if h == "b" else 0),
            ["a", "b"], poll_s=0.001, min_hosts=2)
        with pytest.raises(WorldFailure, match="min_hosts"):
            agent.run()

    def test_initial_world_below_min_hosts_rejected_before_launch(self):
        launched = []

        def launch(hosts):
            launched.append(hosts)
            return []

        agent = DSElasticAgent(launch, ["a"], min_hosts=3, poll_s=0.001)
        with pytest.raises(WorldFailure, match="min_hosts"):
            agent.run()
        assert launched == []                    # never launched


class TestAdmissibility:
    DS_CONFIG = {"elasticity": {
        "enabled": True, "max_train_batch_size": 64,
        "micro_batch_sizes": [4], "min_gpus": 1, "max_gpus": 16,
        "version": 0.2, "num_gpus_per_node": 2}}

    def test_inadmissible_shrunken_world_rejected(self):
        # 2 hosts x 3 chips = 6 admissible; 1 host x 3 = 3 chips is not
        # a multiple of num_gpus_per_node=2 -> WorldFailure on shrink
        agent = DSElasticAgent(
            _launcher(lambda h, hosts: 1 if h == "b" else 0),
            ["a", "b"], ds_config=self.DS_CONFIG, chips_per_host=3,
            poll_s=0.001)
        with pytest.raises(WorldFailure, match="admissible"):
            agent.run()

    def test_admissible_shrink_restarts(self):
        died = {"b": False}

        def rc_for(h, hosts):
            if h == "b" and not died["b"]:
                died["b"] = True
                return 1
            return 0

        # 2 hosts x 4 = 8 admissible, and the shrunken 1 host x 4 = 4
        # is still in the valid set -> restart instead of abort
        agent = DSElasticAgent(
            _launcher(rc_for), ["a", "b"],
            ds_config=self.DS_CONFIG, chips_per_host=4, poll_s=0.001)
        assert agent.run() == ["a"]
        assert agent.restart_count == 1


class TestHeartbeatLiveness:
    def test_hung_worker_is_killed_and_world_restarts(self, tmp_path):
        """A worker that neither exits nor beats is treated exactly like
        a dead one: killed, dropped, world restarted."""
        restarts = []

        def rc_for(h, hosts):
            if h == "b" and len(hosts) == 2:
                return None                      # hangs in generation 0
            return 0

        agent = DSElasticAgent(
            _launcher(rc_for), ["a", "b"], poll_s=0.01,
            heartbeat_timeout_s=0.15, heartbeat_dir=str(tmp_path),
            on_restart=lambda gen, hosts: restarts.append((gen, hosts)))
        t0 = time.time()
        final = agent.run()
        assert final == ["a"]
        assert agent.restart_count == 1
        assert restarts == [(1, ["a"])]
        assert time.time() - t0 < 10             # detector, not a hang

    def test_beating_worker_is_not_killed(self, tmp_path):
        """A slow-but-alive worker (fresh heartbeat) survives a timeout
        window several times shorter than its runtime."""
        agent = DSElasticAgent(
            lambda hosts: [], ["w1"], heartbeat_timeout_s=0.2,
            heartbeat_dir=str(tmp_path))
        hb = agent.heartbeat_path("w1")
        launched_at = time.time() - 10           # launched long ago
        with open(hb, "w"):
            pass                                 # fresh beat
        assert agent._hung("w1", launched_at) is False
        # stale beat -> hung
        old = time.time() - 5
        os.utime(hb, (old, old))
        assert agent._hung("w1", launched_at) is True
        # no beat at all: measured from launch time
        os.remove(hb)
        assert agent._hung("w1", time.time()) is False
        assert agent._hung("w1", launched_at) is True

    def test_clear_heartbeats_between_generations(self, tmp_path):
        agent = DSElasticAgent(
            lambda hosts: [], ["h/0", "h/1"], heartbeat_timeout_s=1.0,
            heartbeat_dir=str(tmp_path))
        for h in ("h/0", "h/1"):
            with open(agent.heartbeat_path(h), "w"):
                pass
        assert len(os.listdir(str(tmp_path))) == 2
        agent._clear_heartbeats(["h/0", "h/1"])
        assert os.listdir(str(tmp_path)) == []

    def test_disabled_by_default(self):
        agent = DSElasticAgent(lambda hosts: [], ["a"])
        assert agent._hung("a", 0.0) is False    # even 'launched' at epoch


class TestHeartbeatRemoteHosts:
    """ISSUE 7 satellite: the /tmp default heartbeat_dir silently makes
    every healthy ssh-launched remote worker look hung. The agent now
    refuses that combination up front instead of killing a healthy
    world."""

    def test_default_tmp_dir_with_remote_hosts_fails_fast(self):
        with pytest.raises(WorldFailure, match="shared"):
            DSElasticAgent(lambda hosts: [], ["tpu-worker-0",
                                              "tpu-worker-1"],
                           heartbeat_timeout_s=10.0)

    def test_explicit_dir_with_remote_hosts_is_trusted(self, tmp_path):
        # warns about the shared-FS requirement but constructs
        agent = DSElasticAgent(lambda hosts: [], ["tpu-worker-0"],
                               heartbeat_timeout_s=10.0,
                               heartbeat_dir=str(tmp_path))
        assert agent.heartbeat_dir == str(tmp_path)

    def test_local_hosts_keep_the_tmp_default(self):
        import socket
        for h in ("localhost", "127.0.0.1", socket.gethostname()):
            agent = DSElasticAgent(lambda hosts: [], [h],
                                   heartbeat_timeout_s=10.0)
            assert "/tmp" in agent.heartbeat_dir

    def test_no_hang_detection_means_no_check(self):
        agent = DSElasticAgent(lambda hosts: [], ["tpu-worker-0"])
        assert agent.heartbeat_timeout_s is None


class TestSurvivingTopology:
    def test_topology_not_just_world_size(self):
        agent = DSElasticAgent(lambda hosts: [], ["a", "b", "c", "d"],
                               chips_per_host=4, tensor_parallel=2,
                               expert_parallel=2)
        topo = agent.compute_topology(["a", "b", "c"])
        assert topo == {"world": 12, "dp": 3, "do": 1, "tp": 2, "ep": 2,
                        "pipe": 1, "seq": 1, "hosts": ["a", "b", "c"]}

    def test_fixed_factors_gate_admissibility(self):
        # tp*ep = 8 does not divide a 1-host x 4-chip survivor world
        agent = DSElasticAgent(lambda hosts: [], ["a", "b"],
                               chips_per_host=4, tensor_parallel=8)
        with pytest.raises(WorldFailure, match="tp\\*ep"):
            agent.compute_topology(["a"])

    def test_shrink_to_inadmissible_topology_aborts_run(self):
        # dp shrinks 2 -> ... but tp=4 with 2 chips/host: one surviving
        # host gives world 2, not divisible by 4 -> WorldFailure
        agent = DSElasticAgent(
            _launcher(lambda h, hosts: 1 if h == "b" else 0),
            ["a", "b"], chips_per_host=2, tensor_parallel=4,
            poll_s=0.001)
        with pytest.raises(WorldFailure, match="admissible topology"):
            agent.run()

    def test_two_arg_launcher_receives_topology(self):
        seen = []

        def launch(hosts, topology):
            seen.append(topology)
            return [(h, StubProc(rc=0)) for h in hosts]

        agent = DSElasticAgent(launch, ["a", "b"], chips_per_host=2,
                               poll_s=0.001)
        agent.run()
        assert seen and seen[0]["world"] == 4 and seen[0]["dp"] == 4

    def test_worker_env_exports_ring(self, tmp_path):
        agent = DSElasticAgent(lambda hosts: [], ["a", "b", "c"],
                               hot_root=str(tmp_path),
                               heartbeat_timeout_s=5.0,
                               heartbeat_dir=str(tmp_path / "hb"))
        env = agent.worker_env("b")
        assert env["DSTPU_HOT_TIER_ROOT"] == str(tmp_path)
        assert env["DSTPU_HOT_NODE"] == "b"
        assert env["DSTPU_HOT_PEERS"] == "a,b,c"
        assert env["DSTPU_HEARTBEAT_FILE"] == agent.heartbeat_path("b")


class TestFailureClassification:
    def test_dead_host_is_dropped_and_classified(self):
        died = {"b": False}

        def rc_for(h, hosts):
            if h == "b" and not died["b"]:
                died["b"] = True
                return 1
            return 0

        agent = DSElasticAgent(_launcher(rc_for), ["a", "b"],
                               poll_s=0.001)
        assert agent.run() == ["a"]
        assert agent.last_failures == {"b": "dead"}

    def test_corrupt_ckpt_exit_keeps_the_host(self):
        """A corrupt-checkpoint exit means the HOST is healthy: the
        world relaunches unshrunk after the corrupt-class backoff."""
        tries = {"n": 0}

        def rc_for(h, hosts):
            if h == "a" and tries["n"] == 0:
                tries["n"] += 1
                return CORRUPT_CKPT_EXIT_CODE
            return 0

        agent = DSElasticAgent(
            _launcher(rc_for), ["a", "b"], poll_s=0.001,
            restart_backoff_s={"corrupt_ckpt": 0.05})
        t0 = time.time()
        final = agent.run()
        assert final == ["a", "b"]               # world NOT shrunk
        assert agent.restart_count == 1
        assert agent.last_failures == {"a": "corrupt_ckpt"}
        assert time.time() - t0 >= 0.05          # backoff applied

    def test_per_class_backoff_zero_for_dead(self):
        died = {"b": False}

        def rc_for(h, hosts):
            if h == "b" and not died["b"]:
                died["b"] = True
                return 1
            return 0

        agent = DSElasticAgent(
            _launcher(rc_for), ["a", "b"], poll_s=0.001,
            restart_backoff_s={"dead": 0.0, "corrupt_ckpt": 30.0})
        t0 = time.time()
        agent.run()
        assert time.time() - t0 < 5.0            # no corrupt backoff

    def test_hung_worker_classified_hung(self, tmp_path):
        def rc_for(h, hosts):
            if h == "b" and len(hosts) == 2:
                return None                      # hangs, never beats
            return 0

        agent = DSElasticAgent(
            _launcher(rc_for), ["a", "b"], poll_s=0.01,
            heartbeat_timeout_s=0.15, heartbeat_dir=str(tmp_path))
        assert agent.run() == ["a"]
        assert agent.last_failures == {"b": "hung"}


class TestHotTierPointing:
    def test_dead_host_store_purged_and_host_loss_fires(self, tmp_path):
        """On membership change the agent drops the dead host's
        hot-tier store (its RAM is gone) — survivors' replicas are the
        restore source — and the host_loss fault point fires."""
        from deepspeed_tpu.runtime.checkpoint_engine import hot_tier
        root = str(tmp_path)
        stores = {p: hot_tier.HotTierStore(root=root, node=p,
                                           peers=["a", "b"], replicas=1)
                  for p in ("a", "b")}
        stores["b"].push("global_step1", {"w#0.0": __import__(
            "numpy").zeros((2,), "float32")},
            {"index": {"w": {"shape": [2], "dtype": "float32",
                             "chunks": [{"key": "w#0.0",
                                         "start": [0]}]}},
             "__tree_meta__": {}, "user_extra": {"global_step": 1,
                                                 "nprocs": 1}},
            shard_name="shard-0.npz")
        died = {"b": False}

        def rc_for(h, hosts):
            if h == "b" and not died["b"]:
                died["b"] = True
                return 1
            return 0

        fault_injection.reset()
        agent = DSElasticAgent(_launcher(rc_for), ["a", "b"],
                               poll_s=0.001, hot_root=root)
        assert agent.run() == ["a"]
        assert not os.path.isdir(os.path.join(root, "b"))   # purged
        # the replica b pushed to a survives and is restorable
        tag, _, _ = stores["a"].load_best()
        assert tag == "global_step1"
        assert fault_injection.injector.fired("host_loss") == 1
        fault_injection.reset()

    def test_armed_host_loss_aborts_recovery(self):
        """Chaos: host_loss armed with kill models the agent itself
        dying mid-recovery — the error must propagate (a supervisor
        above owns that restart), never be swallowed."""
        died = {"b": False}

        def rc_for(h, hosts):
            if h == "b" and not died["b"]:
                died["b"] = True
                return 1
            return 0

        fault_injection.reset()
        fault_injection.arm("host_loss", kill=True)
        agent = DSElasticAgent(_launcher(rc_for), ["a", "b"],
                               poll_s=0.001)
        try:
            with pytest.raises(fault_injection.SimulatedKill):
                agent.run()
        finally:
            fault_injection.reset()


SLICES = {"a": "0", "b": "0", "c": "1", "d": "1"}


class TestSliceAwareness:
    """ISSUE 15 tentpole (b): the agent learns slice membership,
    computes data_outer over SURVIVING slices, classifies a whole-slice
    failure as dead_slice, and drops a partially-lost slice whole."""

    def test_topology_do_counts_surviving_slices(self):
        agent = DSElasticAgent(lambda hosts: [], ["a", "b", "c", "d"],
                               slices=SLICES)
        assert agent.compute_topology(["a", "b", "c", "d"])["do"] == 2
        # slice 1 gone: do shrinks, slice 0 keeps its intra-slice dp
        topo = agent.compute_topology(["a", "b"])
        assert topo["do"] == 1 and topo["dp"] == 2

    def test_ragged_surviving_slices_rejected(self):
        agent = DSElasticAgent(lambda hosts: [], ["a", "b", "c", "d"],
                               slices=SLICES)
        with pytest.raises(WorldFailure, match="ragged"):
            agent.compute_topology(["a", "b", "c"])

    def test_worker_env_exports_slice_membership(self, tmp_path):
        agent = DSElasticAgent(lambda hosts: [], ["a", "b", "c", "d"],
                               slices=SLICES, hot_root=str(tmp_path))
        env = agent.worker_env("c")
        assert env["DSTPU_HOT_SLICE"] == "1"
        assert env["DSTPU_HOT_SLICES"] == "0,0,1,1"

    def test_without_slices_no_slice_env(self, tmp_path):
        agent = DSElasticAgent(lambda hosts: [], ["a", "b"],
                               hot_root=str(tmp_path))
        env = agent.worker_env("a")
        assert "DSTPU_HOT_SLICE" not in env
        assert "DSTPU_HOT_SLICES" not in env

    def test_whole_slice_loss_classified_dead_slice(self, tmp_path):
        """Every host of slice 1 dies together: ONE slice_loss fires,
        all members classify dead_slice, do shrinks 2 -> 1, and the
        dead slice's hot-tier stores are purged."""
        root = str(tmp_path)
        for h in ("c", "d"):
            os.makedirs(os.path.join(root, h))

        def rc_for(h, hosts):
            return 1 if h in ("c", "d") and len(hosts) == 4 else 0

        fault_injection.reset()
        agent = DSElasticAgent(_launcher(rc_for),
                               ["a", "b", "c", "d"], slices=SLICES,
                               poll_s=0.001, hot_root=root)
        try:
            assert agent.run() == ["a", "b"]
            assert agent.last_failures == {"c": "dead_slice",
                                           "d": "dead_slice"}
            assert fault_injection.injector.fired("slice_loss") == 1
            assert agent.topology["do"] == 1
            for h in ("c", "d"):
                assert not os.path.isdir(os.path.join(root, h))
        finally:
            fault_injection.reset()

    def test_partial_slice_loss_drops_the_whole_slice(self):
        """Only c of slice 1 dies: the stranded healthy d is dropped
        too (a data_outer mesh needs equal slice populations) — but the
        failure stays an ordinary host death, NOT a dead_slice, and
        slice_loss does not fire."""
        def rc_for(h, hosts):
            return 1 if h == "c" and len(hosts) == 4 else 0

        fault_injection.reset()
        agent = DSElasticAgent(_launcher(rc_for),
                               ["a", "b", "c", "d"], slices=SLICES,
                               poll_s=0.001)
        try:
            assert agent.run() == ["a", "b"]
            assert agent.last_failures == {"c": "dead"}
            assert fault_injection.injector.fired("slice_loss") == 0
            assert agent.topology["do"] == 1
        finally:
            fault_injection.reset()


class TestPreemption:
    """ISSUE 15 tentpole (c): a PREEMPTED_EXIT_CODE exit means the
    worker drained cleanly after SIGTERM — the host is healthy, the
    world relaunches unshrunk with zero backoff."""

    def test_preempted_exit_keeps_host_no_backoff(self):
        tries = {"n": 0}

        def rc_for(h, hosts):
            if h == "a" and tries["n"] == 0:
                tries["n"] += 1
                return PREEMPTED_EXIT_CODE
            return 0

        agent = DSElasticAgent(
            _launcher(rc_for), ["a", "b"], poll_s=0.001,
            restart_backoff_s={"corrupt_ckpt": 30.0})
        t0 = time.time()
        final = agent.run()
        assert final == ["a", "b"]               # world NOT shrunk
        assert agent.restart_count == 1
        assert agent.last_failures == {"a": "preempted"}
        assert time.time() - t0 < 5.0            # zero-backoff class

    def test_sigterm_forwarded_to_live_workers(self):
        """The agent's SIGTERM handler flags the preemption notice and
        terminates every live worker — invoked directly (real signal
        delivery in-process is racy under pytest)."""
        agent = DSElasticAgent(lambda hosts: [], ["a"])
        prev = signal.getsignal(signal.SIGTERM)
        try:
            assert agent.install_sigterm_forwarding() is True
            p = StubProc(rc=None)
            agent._live_procs = {"a": p}
            handler = signal.getsignal(signal.SIGTERM)
            handler(signal.SIGTERM, None)
            assert agent._preempt_notice is True
            assert p.poll() == -15               # terminated, not -9
        finally:
            signal.signal(signal.SIGTERM, prev)

    def test_forwarding_refused_off_main_thread(self):
        agent = DSElasticAgent(lambda hosts: [], ["a"])
        prev = signal.getsignal(signal.SIGTERM)
        out = []
        t = threading.Thread(
            target=lambda: out.append(agent.install_sigterm_forwarding()))
        t.start()
        t.join()
        assert out == [False]
        assert signal.getsignal(signal.SIGTERM) is prev
