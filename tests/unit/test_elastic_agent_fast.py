"""Fast DSElasticAgent coverage (satellite of ISSUE 2): restart-budget
exhaustion, shrink below min_hosts, inadmissible-world rejection, and
the new heartbeat/hang detector — all against stub processes so the
suite is deterministic and runs inside tier-1 (the subprocess-based
end-to-end resume test stays in test_elastic_agent.py's slow set)."""

import os
import time

import pytest

from deepspeed_tpu.elasticity.elastic_agent import (DSElasticAgent,
                                                    WorldFailure)


class StubProc:
    """Popen-shaped test double. rc=None means 'runs forever' until
    kill()/terminate()."""

    def __init__(self, rc=0, exit_after_polls=1):
        self._rc = rc
        self._polls_left = exit_after_polls

    def poll(self):
        if self._rc is None:
            return None
        if self._polls_left > 0:
            self._polls_left -= 1
            return None
        return self._rc

    def kill(self):
        self._rc = -9
        self._polls_left = 0

    def terminate(self):
        self._rc = -15
        self._polls_left = 0

    def wait(self, timeout=None):
        return self._rc


def _launcher(rc_for):
    """rc_for(host, gen_hosts) -> rc (None = hang forever)."""
    def launch(hosts):
        return [(h, StubProc(rc=rc_for(h, hosts))) for h in hosts]
    return launch


class TestRestartBudget:
    def test_budget_exhaustion_raises(self):
        # the first host of every generation dies -> one restart per
        # generation until the budget runs out
        agent = DSElasticAgent(
            _launcher(lambda h, hosts: 1 if h == hosts[0] else 0),
            ["a", "b", "c", "d", "e"], poll_s=0.001, max_restarts=2)
        with pytest.raises(WorldFailure, match="budget"):
            agent.run()
        assert agent.restart_count == 3          # the one over budget

    def test_budget_counts_across_generations(self):
        events = []
        died = {"a": False}

        def rc_for(h, hosts):
            if h == "a" and not died["a"]:
                died["a"] = True
                return 1
            return 0

        agent = DSElasticAgent(
            _launcher(rc_for), ["a", "b", "c"], poll_s=0.001,
            max_restarts=5,
            on_restart=lambda gen, hosts: events.append((gen, hosts)))
        final = agent.run()
        assert final == ["b", "c"]
        assert events == [(1, ["b", "c"])]


class TestShrinkLimits:
    def test_shrink_below_min_hosts_raises(self):
        agent = DSElasticAgent(
            _launcher(lambda h, hosts: 1 if h == "b" else 0),
            ["a", "b"], poll_s=0.001, min_hosts=2)
        with pytest.raises(WorldFailure, match="min_hosts"):
            agent.run()

    def test_initial_world_below_min_hosts_rejected_before_launch(self):
        launched = []

        def launch(hosts):
            launched.append(hosts)
            return []

        agent = DSElasticAgent(launch, ["a"], min_hosts=3, poll_s=0.001)
        with pytest.raises(WorldFailure, match="min_hosts"):
            agent.run()
        assert launched == []                    # never launched


class TestAdmissibility:
    DS_CONFIG = {"elasticity": {
        "enabled": True, "max_train_batch_size": 64,
        "micro_batch_sizes": [4], "min_gpus": 1, "max_gpus": 16,
        "version": 0.2, "num_gpus_per_node": 2}}

    def test_inadmissible_shrunken_world_rejected(self):
        # 2 hosts x 3 chips = 6 admissible; 1 host x 3 = 3 chips is not
        # a multiple of num_gpus_per_node=2 -> WorldFailure on shrink
        agent = DSElasticAgent(
            _launcher(lambda h, hosts: 1 if h == "b" else 0),
            ["a", "b"], ds_config=self.DS_CONFIG, chips_per_host=3,
            poll_s=0.001)
        with pytest.raises(WorldFailure, match="admissible"):
            agent.run()

    def test_admissible_shrink_restarts(self):
        died = {"b": False}

        def rc_for(h, hosts):
            if h == "b" and not died["b"]:
                died["b"] = True
                return 1
            return 0

        # 2 hosts x 4 = 8 admissible, and the shrunken 1 host x 4 = 4
        # is still in the valid set -> restart instead of abort
        agent = DSElasticAgent(
            _launcher(rc_for), ["a", "b"],
            ds_config=self.DS_CONFIG, chips_per_host=4, poll_s=0.001)
        assert agent.run() == ["a"]
        assert agent.restart_count == 1


class TestHeartbeatLiveness:
    def test_hung_worker_is_killed_and_world_restarts(self, tmp_path):
        """A worker that neither exits nor beats is treated exactly like
        a dead one: killed, dropped, world restarted."""
        restarts = []

        def rc_for(h, hosts):
            if h == "b" and len(hosts) == 2:
                return None                      # hangs in generation 0
            return 0

        agent = DSElasticAgent(
            _launcher(rc_for), ["a", "b"], poll_s=0.01,
            heartbeat_timeout_s=0.15, heartbeat_dir=str(tmp_path),
            on_restart=lambda gen, hosts: restarts.append((gen, hosts)))
        t0 = time.time()
        final = agent.run()
        assert final == ["a"]
        assert agent.restart_count == 1
        assert restarts == [(1, ["a"])]
        assert time.time() - t0 < 10             # detector, not a hang

    def test_beating_worker_is_not_killed(self, tmp_path):
        """A slow-but-alive worker (fresh heartbeat) survives a timeout
        window several times shorter than its runtime."""
        agent = DSElasticAgent(
            lambda hosts: [], ["w1"], heartbeat_timeout_s=0.2,
            heartbeat_dir=str(tmp_path))
        hb = agent.heartbeat_path("w1")
        launched_at = time.time() - 10           # launched long ago
        with open(hb, "w"):
            pass                                 # fresh beat
        assert agent._hung("w1", launched_at) is False
        # stale beat -> hung
        old = time.time() - 5
        os.utime(hb, (old, old))
        assert agent._hung("w1", launched_at) is True
        # no beat at all: measured from launch time
        os.remove(hb)
        assert agent._hung("w1", time.time()) is False
        assert agent._hung("w1", launched_at) is True

    def test_clear_heartbeats_between_generations(self, tmp_path):
        agent = DSElasticAgent(
            lambda hosts: [], ["h/0", "h/1"], heartbeat_timeout_s=1.0,
            heartbeat_dir=str(tmp_path))
        for h in ("h/0", "h/1"):
            with open(agent.heartbeat_path(h), "w"):
                pass
        assert len(os.listdir(str(tmp_path))) == 2
        agent._clear_heartbeats(["h/0", "h/1"])
        assert os.listdir(str(tmp_path)) == []

    def test_disabled_by_default(self):
        agent = DSElasticAgent(lambda hosts: [], ["a"])
        assert agent._hung("a", 0.0) is False    # even 'launched' at epoch
