"""Sequence/context parallelism: Ulysses all-to-all + ring attention.

The reference ships Ulysses untested (SURVEY §4: sequence_parallelism/ test
dir is empty); here both paths are parity-tested against dense attention on
the virtual mesh.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.sequence import (ring_attention_sharded,
                                    ulysses_attention)
from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.groups import TopologyConfig

# compile-heavy: excluded from the fast core set (pytest -m 'not slow')
pytestmark = pytest.mark.slow



def _dense_ref(q, k, v, causal=True):
    T = q.shape[1]
    s = jnp.einsum("bthd,bshd->bhts", q, k,
                   preferred_element_type=jnp.float32)
    s = s / math.sqrt(q.shape[-1])
    if causal:
        mask = jnp.tril(jnp.ones((T, T), jnp.bool_))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhts,bshd->bthd", p, v)


def _qkv(B=2, T=32, H=4, D=8, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (B, T, H, D)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


def _seq_mesh(sp=4):
    groups.reset()
    return groups.initialize(TopologyConfig(seq_parallel_size=sp))


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_dense(causal):
    q, k, v = _qkv()
    ref = _dense_ref(q, k, v, causal)
    topo = _seq_mesh(4)
    spec = NamedSharding(topo.mesh, P(("data", "expert"), "seq", None, None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    with jax.set_mesh(topo.mesh):
        out = jax.jit(lambda a, b, c: ring_attention_sharded(
            a, b, c, topo.mesh, causal=causal))(qs, ks, vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)


def test_ring_attention_grads_match_dense():
    q, k, v = _qkv(T=16)
    topo = _seq_mesh(4)

    def loss_ring(q, k, v):
        return jnp.sum(jnp.square(
            ring_attention_sharded(q, k, v, topo.mesh)))

    def loss_dense(q, k, v):
        return jnp.sum(jnp.square(_dense_ref(q, k, v)))

    with jax.set_mesh(topo.mesh):
        g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                   rtol=3e-4, atol=3e-5)


def test_ulysses_matches_dense():
    q, k, v = _qkv(H=8)  # heads divisible by sp=4
    ref = _dense_ref(q, k, v, causal=True)
    topo = _seq_mesh(4)
    with jax.set_mesh(topo.mesh):
        out = jax.jit(lambda a, b, c: ulysses_attention(
            a, b, c, topo.mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)


def test_ulysses_grads_match_dense():
    """Grad parity for the Ulysses path: autodiff differentiates through
    the all-to-all pair (no hand-written backward), so gradients must
    match dense causal attention, not just the forward."""
    q, k, v = _qkv(H=8, T=16)
    topo = _seq_mesh(4)

    def loss_u(q, k, v):
        return jnp.sum(jnp.square(ulysses_attention(q, k, v, topo.mesh)))

    def loss_d(q, k, v):
        return jnp.sum(jnp.square(_dense_ref(q, k, v)))

    with jax.set_mesh(topo.mesh):
        g_u = jax.jit(jax.grad(loss_u, argnums=(0, 1, 2)))(q, k, v)
    g_d = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for gu, gd in zip(g_u, g_d):
        np.testing.assert_allclose(np.asarray(gu), np.asarray(gd),
                                   rtol=3e-4, atol=3e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_tp_composition_matches_dense(causal):
    """Ring-CP x TP: heads sharded over 'tensor' while the sequence
    rings over 'seq' (the head_axis composition gpt2 uses). Forward AND
    grads vs dense."""
    groups.reset()
    topo = groups.initialize(TopologyConfig(seq_parallel_size=2,
                                            tensor_parallel_size=2))
    q, k, v = _qkv(B=4, T=16, H=4)
    ref = _dense_ref(q, k, v, causal)

    def ring(a, b, c):
        return ring_attention_sharded(a, b, c, topo.mesh, causal=causal,
                                      head_axis="tensor")

    with jax.set_mesh(topo.mesh):
        out = jax.jit(ring)(q, k, v)
        g_r = jax.jit(jax.grad(
            lambda a, b, c: jnp.sum(jnp.square(ring(a, b, c))),
            argnums=(0, 1, 2)))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)
    g_d = jax.grad(
        lambda a, b, c: jnp.sum(jnp.square(_dense_ref(a, b, c, causal))),
        argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_r, g_d):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                   rtol=3e-4, atol=3e-5)


def test_ring_contiguous_layout_matches_dense():
    """The contiguous (compute-then-mask) fallback layout stays exact."""
    q, k, v = _qkv()
    ref = _dense_ref(q, k, v, True)
    topo = _seq_mesh(4)
    with jax.set_mesh(topo.mesh):
        out = jax.jit(lambda a, b, c: ring_attention_sharded(
            a, b, c, topo.mesh, layout="contiguous"))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)


def test_gpt2_ring_backend_matches_dense_model():
    from deepspeed_tpu.models import GPT2, GPT2Config
    kw = dict(n_layer=2, n_head=4, d_model=32, max_seq_len=32,
              vocab_size=128, remat=False, dtype="float32")
    dense = GPT2(GPT2Config(**kw))
    ring = GPT2(GPT2Config(attention_backend="ring", **kw))
    params = dense.init(jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (2, 32), 0, 128,
                             dtype=jnp.int32)
    ref = dense.apply(params, ids)

    topo = _seq_mesh(4)
    with jax.set_mesh(topo.mesh):
        out = jax.jit(lambda p, i: ring.apply(p, i, seq_sharded=True))(
            params, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=5e-4, atol=5e-5)


def test_engine_verify_comm_overlap_reports_ring_rotation():
    """engine.verify_comm_overlap on a seq-sharded ring engine reports
    the KV collective-permute INSIDE the scan body (in_loop_by_op) —
    the acceptance signal that the rotation overlaps ring compute."""
    import deepspeed_tpu
    from deepspeed_tpu.models import GPT2, GPT2Config
    topo = _seq_mesh(4)
    cfg = GPT2Config(n_layer=2, n_head=2, d_model=32, max_seq_len=32,
                     vocab_size=128, remat=True, dtype="float32",
                     attention_backend="ring")
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2(cfg), topology=topo,
        config={"train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": 1, "steps_per_print": 0,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 2}})
    # engine installed the config's sequence block on the model
    assert engine.model._sequence_cfg.layout == "zigzag"
    rng = np.random.RandomState(0)
    batch = {"input_ids": rng.randint(
        0, cfg.vocab_size,
        (engine.config.train_batch_size, cfg.max_seq_len)).astype(np.int32)}
    report = engine.verify_comm_overlap(batch)
    assert report["in_loop_by_op"].get("collective-permute", 0) >= 1, \
        report["in_loop_by_op"]


def test_engine_trains_with_ring_attention():
    import deepspeed_tpu
    from deepspeed_tpu.models import GPT2, GPT2Config
    topo = _seq_mesh(2)
    cfg = GPT2Config(n_layer=2, n_head=2, d_model=32, max_seq_len=32,
                     vocab_size=128, remat=True, dtype="float32",
                     attention_backend="ring")
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2(cfg), topology=topo,
        config={"train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": 1, "steps_per_print": 0,
                "optimizer": {"type": "Adam", "params": {"lr": 3e-3}},
                "zero_optimization": {"stage": 2}})
    rng = np.random.RandomState(0)
    batch = {"input_ids": rng.randint(
        0, cfg.vocab_size,
        (engine.config.train_batch_size, cfg.max_seq_len)).astype(np.int32)}
    losses = [float(engine.train_batch(batch)) for _ in range(8)]
    assert losses[-1] < losses[0] * 0.9, losses
