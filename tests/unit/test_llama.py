"""Llama family tests: training, TP parity, GQA cached/paged decode parity
(reference inference llama2/mistral model_implementations coverage)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import deepspeed_tpu
from deepspeed_tpu.inference.v2 import InferenceEngineV2
from deepspeed_tpu.models import Llama, LlamaConfig
from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.groups import TopologyConfig

# compile-heavy: excluded from the fast core set (pytest -m 'not slow')
pytestmark = pytest.mark.slow



CFG = LlamaConfig(n_layer=2, n_head=4, n_kv_heads=2, d_model=64,
                  max_seq_len=128, vocab_size=256, remat=False,
                  dtype="float32")


class TestLlamaTraining:
    def test_loss_falls_zero2(self):
        groups.reset()
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=Llama(CFG),
            config={"train_micro_batch_size_per_gpu": 1,
                    "optimizer": {"type": "AdamW", "params": {"lr": 2e-3}},
                    "zero_optimization": {"stage": 2},
                    "steps_per_print": 0})
        data = (np.arange(engine.config.train_batch_size * 48)
                .reshape(-1, 48) % 256).astype(np.int32)
        losses = [float(engine.train_batch({"input_ids": data}))
                  for _ in range(6)]
        assert losses[-1] < losses[0]

    def test_tp_matches_dp_loss(self):
        data = (np.arange(8 * 32).reshape(8, 32) * 3 % 256).astype(np.int32)

        def run(tp):
            groups.reset()
            topo = groups.initialize(
                TopologyConfig(tensor_parallel_size=tp))
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=Llama(CFG), topology=topo, seed=0,
                config={"train_micro_batch_size_per_gpu": 8 // (8 // tp)
                        if tp > 1 else 1,
                        "train_batch_size": 8,
                        "optimizer": {"type": "AdamW",
                                      "params": {"lr": 1e-3}},
                        "steps_per_print": 0})
            return [float(engine.train_batch({"input_ids": data}))
                    for _ in range(3)]

        np.testing.assert_allclose(run(1), run(4), rtol=2e-4, atol=2e-4)

    def test_gqa_param_shapes(self):
        model = Llama(CFG)
        params = model.init(jax.random.key(0))
        kvd = CFG.n_kv_heads * CFG.d_head
        assert params["blocks"]["wk"].shape == (2, 64, kvd)
        assert params["blocks"]["wq"].shape == (2, 64, 64)
        n = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
        assert n == CFG.num_params()


class TestLlamaDecode:
    def test_cached_matches_full(self):
        model = Llama(CFG)
        params = model.init(jax.random.key(0))
        T = 12
        ids = jax.random.randint(jax.random.key(1), (2, T), 0, 256)
        full = model.apply(params, ids)
        cache = model.init_cache(2, 32, dtype="float32")
        valid = jnp.broadcast_to(jnp.arange(32)[None, :] < T, (2, 32))
        pos = jnp.tile(jnp.arange(T)[None, :], (2, 1)).astype(jnp.int32)
        logits, _ = model.apply_cached(params, ids, pos, cache, 0, valid)
        np.testing.assert_allclose(np.asarray(logits), np.asarray(full),
                                   rtol=3e-4, atol=3e-4)

    def test_v1_generate_greedy(self):
        model = Llama(CFG)
        params = model.init(jax.random.key(0))
        groups.reset()
        eng = deepspeed_tpu.init_inference(
            model, params=params,
            config={"dtype": "float32", "prompt_bucket": 16})
        prompt = np.arange(7)[None, :] % 256
        out = eng.generate(prompt, max_new_tokens=5, temperature=0.0)
        # manual greedy
        ids = prompt.astype(np.int32)
        for i in range(5):
            nxt = int(np.argmax(np.asarray(
                model.apply(params, jnp.asarray(ids)))[0, -1]))
            assert nxt == out[0, i]
            ids = np.concatenate([ids, [[nxt]]], axis=1)

    def test_v2_paged_matches_v1(self):
        model = Llama(CFG)
        params = model.init(jax.random.key(0))
        groups.reset()
        v1 = deepspeed_tpu.init_inference(
            model, params=params,
            config={"dtype": "float32", "prompt_bucket": 16})
        prompts = [np.arange(5) % 256, (np.arange(9) * 2) % 256]
        ref = v1.generate(prompts, max_new_tokens=6, temperature=0.0)
        groups.reset()
        v2 = InferenceEngineV2(model, params=params,
                               config={"dtype": "float32",
                                       "kv_block_size": 8,
                                       "prompt_bucket": 16,
                                       "max_batch_size": 2})
        outs = v2.generate_all(prompts, max_new_tokens=6)
        for i, o in enumerate(outs):
            np.testing.assert_array_equal(o, ref[i])

    def test_tp_generate_matches_single(self):
        model = Llama(CFG)
        params = model.init(jax.random.key(0))
        groups.reset()
        topo = groups.initialize(TopologyConfig(tensor_parallel_size=2))
        tp = deepspeed_tpu.init_inference(
            model, params=params, topology=topo,
            config={"dtype": "float32", "prompt_bucket": 8,
                    "tensor_parallel": {"tp_size": 2}})
        prompt = (np.arange(6) * 5)[None, :] % 256
        out_tp = tp.generate(prompt, max_new_tokens=5, temperature=0.0)
        groups.reset()
        single = deepspeed_tpu.init_inference(
            model, params=params,
            config={"dtype": "float32", "prompt_bucket": 8})
        out_1 = single.generate(prompt, max_new_tokens=5, temperature=0.0)
        np.testing.assert_array_equal(out_tp, out_1)


class TestMixtral:
    """Mixtral-class MoE serving model (reference
    inference/v2/model_implementations/mixtral): Llama attention +
    dropless grouped-GEMM SwiGLU experts."""

    def _model(self):
        from deepspeed_tpu.models import Mixtral, MIXTRAL_TINY
        from dataclasses import replace
        return Mixtral(replace(MIXTRAL_TINY, dtype="float32"))

    def test_param_count(self):
        m = self._model()
        params = m.init(jax.random.key(0))
        n = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
        assert n == m.config.num_params()

    def test_forward_and_experts_used(self):
        m = self._model()
        params = m.init(jax.random.key(0))
        ids = np.random.RandomState(0).randint(
            0, m.config.vocab_size, (2, 32)).astype(np.int32)
        logits = m.apply(params, ids)
        assert logits.shape == (2, 32, m.config.vocab_size)
        assert np.isfinite(np.asarray(logits)).all()

    def test_paged_serving_matches_contiguous_decode(self):
        """v2 paged decode == contiguous-cache decode, token for token
        (greedy) — the Mixtral serving path end to end."""
        from deepspeed_tpu.inference.v2.engine_v2 import (
            InferenceEngineV2, RaggedInferenceEngineConfig)
        m = self._model()
        params = m.init(jax.random.key(0))
        rng = np.random.RandomState(1)
        prompt = rng.randint(0, m.config.vocab_size, (17,)).astype(np.int32)

        groups.reset()
        eng = InferenceEngineV2(
            m, RaggedInferenceEngineConfig(
                dtype="float32", max_batch_size=2, kv_block_size=16,
                prompt_bucket=32, decode_steps_per_dispatch=4),
            params=params)
        uid = eng.put(prompt, max_new_tokens=10, eos_token_id=-1)
        while eng.has_work:
            eng.step()
        got = np.asarray(eng.get(uid))

        # reference: contiguous-cache greedy decode
        cache = m.init_cache(1, 64, dtype=jnp.float32)
        T = len(prompt)
        valid = np.zeros((1, 64), bool)
        valid[0, :T] = True
        logits, cache = m.apply_cached(
            params, prompt[None, :], np.arange(T)[None, :], cache,
            0, jnp.asarray(valid), last_token_only=True)
        toks = []
        tok = int(np.argmax(np.asarray(logits)[0, -1]))
        for i in range(10):
            toks.append(tok)
            valid[0, T + i] = True
            logits, cache = m.apply_cached(
                params, np.asarray([[tok]], np.int32),
                np.asarray([[T + i]], np.int32), cache, T + i,
                jnp.asarray(valid))
            tok = int(np.argmax(np.asarray(logits)[0, -1]))
        np.testing.assert_array_equal(got, np.asarray(toks, np.int32))



class TestQwen:
    """Qwen family (reference inference/v2/model_implementations/
    qwen_v2): Llama + attention-projection bias."""

    def _model(self):
        from deepspeed_tpu.models import Qwen
        from deepspeed_tpu.models.qwen import QWEN_TINY
        from dataclasses import replace
        return Qwen(replace(QWEN_TINY, dtype="float32"))

    def test_param_count_includes_bias(self):
        m = self._model()
        params = m.init(jax.random.key(0))
        n = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
        assert n == m.config.num_params()
        assert "bq" in params["blocks"]          # the family knob is real

    def test_paged_serving_end_to_end(self):
        """v2 paged decode == contiguous-cache decode token for token
        (greedy) — the Qwen serving path end to end."""
        from deepspeed_tpu.inference.v2.engine_v2 import (
            InferenceEngineV2, RaggedInferenceEngineConfig)
        from deepspeed_tpu.inference.engine import InferenceEngine
        m = self._model()
        groups.reset()
        rng = np.random.RandomState(3)
        prompts = [rng.randint(0, 500, (n,)).astype(np.int32)
                   for n in (7, 12)]
        v2 = InferenceEngineV2(
            m, RaggedInferenceEngineConfig(max_batch_size=2,
                                           kv_block_size=16,
                                           prompt_bucket=16))
        uids = [v2.put(p, max_new_tokens=6, eos_token_id=-1)
                for p in prompts]
        while v2.has_work:
            v2.step()
        got = {u: np.asarray(v2.get(u)) for u in uids}
        groups.reset()
        ref = InferenceEngine(m, config={"dtype": "float32",
                                         "prompt_bucket": 16})
        for u, p in zip(uids, prompts):
            want = np.asarray(ref.generate(p[None], max_new_tokens=6,
                                           temperature=0.0))[0]
            np.testing.assert_array_equal(got[u][len(p):],
                                          want[len(p):])


class TestPhi:
    """Phi family (reference inference/v2/model_implementations/phi):
    parallel attention/MLP block, partial rotary, LayerNorm with bias,
    plain-gelu MLP."""

    def _model(self):
        from deepspeed_tpu.models import Phi
        from deepspeed_tpu.models.phi import PHI_TINY
        from dataclasses import replace
        return Phi(replace(PHI_TINY, dtype="float32"))

    def test_param_count_includes_ln_biases(self):
        m = self._model()
        params = m.init(jax.random.key(0))
        n = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
        assert n == m.config.num_params()
        assert "b1" in params["blocks"] and "norm_f_b" in params

    def test_partial_rotary_leaves_tail_dims(self):
        """rotary_pct < 1: trailing head dims pass through unrotated."""
        import jax.numpy as jnp
        m = self._model()
        x = jnp.asarray(np.random.RandomState(0).randn(1, 4, 4, 32),
                        jnp.float32)
        pos = jnp.arange(4)[None, :]
        y = m._rope(x, pos)
        rot = max(2, int(32 * m.config.rotary_pct)) // 2 * 2
        np.testing.assert_array_equal(np.asarray(y[..., rot:]),
                                      np.asarray(x[..., rot:]))
        assert not np.allclose(np.asarray(y[..., 1:rot]),
                               np.asarray(x[..., 1:rot]))

    def test_paged_serving_end_to_end(self):
        from deepspeed_tpu.inference.v2.engine_v2 import (
            InferenceEngineV2, RaggedInferenceEngineConfig)
        from deepspeed_tpu.inference.engine import InferenceEngine
        m = self._model()
        groups.reset()
        rng = np.random.RandomState(4)
        prompts = [rng.randint(0, 500, (n,)).astype(np.int32)
                   for n in (5, 11)]
        v2 = InferenceEngineV2(
            m, RaggedInferenceEngineConfig(max_batch_size=2,
                                           kv_block_size=16,
                                           prompt_bucket=16))
        uids = [v2.put(p, max_new_tokens=6, eos_token_id=-1)
                for p in prompts]
        while v2.has_work:
            v2.step()
        got = {u: np.asarray(v2.get(u)) for u in uids}
        groups.reset()
        ref = InferenceEngine(m, config={"dtype": "float32",
                                         "prompt_bucket": 16})
        for u, p in zip(uids, prompts):
            want = np.asarray(ref.generate(p[None], max_new_tokens=6,
                                           temperature=0.0))[0]
            np.testing.assert_array_equal(got[u][len(p):],
                                          want[len(p):])



class TestFalcon:
    """Falcon family (reference inference/v2/model_implementations/
    falcon): parallel block, LayerNorm, multi-query attention
    (n_kv_heads=1 — one shared KV head, the paged cache stores a single
    head per layer)."""

    def _model(self):
        from deepspeed_tpu.models import Falcon
        from deepspeed_tpu.models.falcon import FALCON_TINY
        from dataclasses import replace
        return Falcon(replace(FALCON_TINY, dtype="float32"))

    def test_param_count_and_mqa_cache(self):
        m = self._model()
        params = m.init(jax.random.key(0))
        n = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
        assert n == m.config.num_params()
        cache = m.init_paged_cache(num_blocks=4, block_size=16)
        assert cache["k"][0].shape[1] == 1        # single KV head

    def test_paged_serving_end_to_end(self):
        from deepspeed_tpu.inference.v2.engine_v2 import (
            InferenceEngineV2, RaggedInferenceEngineConfig)
        from deepspeed_tpu.inference.engine import InferenceEngine
        m = self._model()
        groups.reset()
        rng = np.random.RandomState(5)
        prompts = [rng.randint(0, 500, (n,)).astype(np.int32)
                   for n in (6, 13)]
        v2 = InferenceEngineV2(
            m, RaggedInferenceEngineConfig(max_batch_size=2,
                                           kv_block_size=16,
                                           prompt_bucket=16))
        uids = [v2.put(p, max_new_tokens=6, eos_token_id=-1)
                for p in prompts]
        while v2.has_work:
            v2.step()
        got = {u: np.asarray(v2.get(u)) for u in uids}
        groups.reset()
        ref = InferenceEngine(m, config={"dtype": "float32",
                                         "prompt_bucket": 16})
        for u, p in zip(uids, prompts):
            want = np.asarray(ref.generate(p[None], max_new_tokens=6,
                                           temperature=0.0))[0]
            np.testing.assert_array_equal(got[u][len(p):],
                                          want[len(p):])



class TestOPT:
    """OPT family (reference inference/v2/model_implementations/opt):
    GPT-2 machinery + ReLU feed-forward."""

    def _model(self):
        from deepspeed_tpu.models import OPT
        from deepspeed_tpu.models.opt import OPT_TINY
        from dataclasses import replace
        return OPT(replace(OPT_TINY, dtype="float32"))

    def test_relu_is_live(self):
        import jax.numpy as jnp
        m = self._model()
        params = m.init(jax.random.key(0))
        ids = np.random.RandomState(0).randint(0, 500, (1, 16)).astype(np.int32)
        logits = m.apply(params, ids)
        assert np.isfinite(np.asarray(logits)).all()
        # flipping the activation changes the function (knob is real)
        from dataclasses import replace as _r
        from deepspeed_tpu.models import GPT2
        g = GPT2(_r(m.config, activation="gelu"))
        assert not np.allclose(np.asarray(logits),
                               np.asarray(g.apply(params, ids)))

    def test_paged_serving_end_to_end(self):
        from deepspeed_tpu.inference.v2.engine_v2 import (
            InferenceEngineV2, RaggedInferenceEngineConfig)
        from deepspeed_tpu.inference.engine import InferenceEngine
        m = self._model()
        groups.reset()
        rng = np.random.RandomState(6)
        prompts = [rng.randint(0, 500, (n,)).astype(np.int32)
                   for n in (9, 14)]
        v2 = InferenceEngineV2(
            m, RaggedInferenceEngineConfig(max_batch_size=2,
                                           kv_block_size=16,
                                           prompt_bucket=16))
        uids = [v2.put(p, max_new_tokens=6, eos_token_id=-1)
                for p in prompts]
        while v2.has_work:
            v2.step()
        got = {u: np.asarray(v2.get(u)) for u in uids}
        groups.reset()
        ref = InferenceEngine(m, config={"dtype": "float32",
                                         "prompt_bucket": 16})
        for u, p in zip(uids, prompts):
            want = np.asarray(ref.generate(p[None], max_new_tokens=6,
                                           temperature=0.0))[0]
            np.testing.assert_array_equal(got[u][len(p):],
                                          want[len(p):])


class TestMistralWindow:
    """Mistral family: sliding-window attention (reference
    inference/v2/model_implementations/mistral). The window must bind
    identically in training (dense/flash), v1 cached decode, and v2
    paged decode."""

    def _model(self, window=16):
        from dataclasses import replace
        from deepspeed_tpu.models.llama import LLAMA_TINY
        return Llama(replace(LLAMA_TINY, dtype="float32",
                             sliding_window=window))

    def test_window_changes_logits(self):
        m_win = self._model(8)
        m_full = self._model(0)
        params = m_win.init(jax.random.key(0))
        ids = jnp.asarray(np.arange(48)[None, :] % 500, jnp.int32)
        lw = m_win.apply(params, ids)
        lf = m_full.apply(params, ids)
        # positions < window see identical context; later ones differ
        np.testing.assert_allclose(np.asarray(lw[:, :8]),
                                   np.asarray(lf[:, :8]), atol=1e-5)
        assert not np.allclose(np.asarray(lw[:, -1]),
                               np.asarray(lf[:, -1]), atol=1e-3)

    def test_paged_decode_honors_window(self):
        """v2 paged serving == v1 cached decode with the window on."""
        from deepspeed_tpu.inference.v2.engine_v2 import (
            InferenceEngineV2, RaggedInferenceEngineConfig)
        from deepspeed_tpu.inference.engine import InferenceEngine
        m = self._model(8)
        groups.reset()
        rng = np.random.RandomState(7)
        prompts = [rng.randint(0, 500, (n,)).astype(np.int32)
                   for n in (20, 13)]
        v2 = InferenceEngineV2(
            m, RaggedInferenceEngineConfig(max_batch_size=2,
                                           kv_block_size=16,
                                           prompt_bucket=32))
        uids = [v2.put(p, max_new_tokens=8, eos_token_id=-1)
                for p in prompts]
        while v2.has_work:
            v2.step()
        got = {u: np.asarray(v2.get(u)) for u in uids}
        groups.reset()
        ref = InferenceEngine(m, config={"dtype": "float32",
                                         "prompt_bucket": 32})
        for u, p in zip(uids, prompts):
            want = np.asarray(ref.generate(p[None], max_new_tokens=8,
                                           temperature=0.0))[0]
            np.testing.assert_array_equal(got[u][len(p):],
                                          want[len(p):])

    def test_trains_loss_falls(self):
        groups.reset()
        m = self._model(8)
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=m, config={"train_micro_batch_size_per_gpu": 2,
                             "steps_per_print": 0,
                             "optimizer": {"type": "AdamW",
                                           "params": {"lr": 1e-3}},
                             "zero_optimization": {"stage": 2}})
        rng = np.random.RandomState(0)
        bsz = engine.config.train_batch_size
        batch = {"input_ids": rng.randint(0, 500, (bsz, 64))
                 .astype(np.int32)}
        losses = [float(engine.train_batch(batch)) for _ in range(3)]
        assert losses[-1] < losses[0]


class TestBloom:
    """Bloom family: ALiBi + embedding LN + biases everywhere
    (reference module_inject/containers/bloom.py)."""

    def _model(self):
        from dataclasses import replace
        from deepspeed_tpu.models import Bloom
        from deepspeed_tpu.models.bloom import BLOOM_TINY
        return Bloom(replace(BLOOM_TINY, dtype="float32"))

    def test_param_count_and_knobs(self):
        m = self._model()
        params = m.init(jax.random.key(0))
        n = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
        assert n == m.config.num_params()
        assert "embed_ln_s" in params
        assert "bo" in params["blocks"]

    def test_alibi_changes_logits(self):
        from dataclasses import replace
        m = self._model()
        params = m.init(jax.random.key(0))
        ids = jnp.asarray(np.arange(32)[None, :] % 500, jnp.int32)
        la = m.apply(params, ids)
        m_no = Llama(replace(m.config, alibi=False))
        ln = m_no.apply(params, ids)
        assert not np.allclose(np.asarray(la), np.asarray(ln), atol=1e-3)

    def test_paged_serving_end_to_end(self):
        from deepspeed_tpu.inference.v2.engine_v2 import (
            InferenceEngineV2, RaggedInferenceEngineConfig)
        from deepspeed_tpu.inference.engine import InferenceEngine
        m = self._model()
        groups.reset()
        rng = np.random.RandomState(8)
        prompts = [rng.randint(0, 500, (n,)).astype(np.int32)
                   for n in (9, 15)]
        v2 = InferenceEngineV2(
            m, RaggedInferenceEngineConfig(max_batch_size=2,
                                           kv_block_size=16,
                                           prompt_bucket=16))
        uids = [v2.put(p, max_new_tokens=6, eos_token_id=-1)
                for p in prompts]
        while v2.has_work:
            v2.step()
        got = {u: np.asarray(v2.get(u)) for u in uids}
        groups.reset()
        ref = InferenceEngine(m, config={"dtype": "float32",
                                         "prompt_bucket": 16})
        for u, p in zip(uids, prompts):
            want = np.asarray(ref.generate(p[None], max_new_tokens=6,
                                           temperature=0.0))[0]
            np.testing.assert_array_equal(got[u][len(p):],
                                          want[len(p):])

    def test_trains_loss_falls(self):
        groups.reset()
        m = self._model()
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=m, config={"train_micro_batch_size_per_gpu": 2,
                             "steps_per_print": 0,
                             "optimizer": {"type": "AdamW",
                                           "params": {"lr": 1e-3}},
                             "zero_optimization": {"stage": 2}})
        rng = np.random.RandomState(0)
        bsz = engine.config.train_batch_size
        batch = {"input_ids": rng.randint(0, 500, (bsz, 64))
                 .astype(np.int32)}
        losses = [float(engine.train_batch(batch)) for _ in range(3)]
        assert losses[-1] < losses[0]


class TestSplitFuseLlama:
    """The SplitFuse chunk program on the llama machinery (window +
    GQA + rope must all hold through chunked prefill)."""

    def test_windowed_chunked_matches_bucketed(self):
        from dataclasses import replace
        from deepspeed_tpu.models.llama import LLAMA_TINY
        from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2
        m = Llama(replace(LLAMA_TINY, dtype="float32", sliding_window=24))
        params = m.init(jax.random.key(0))
        rng = np.random.RandomState(5)
        prompts = [rng.randint(0, 500, (n,)).astype(np.int32)
                   for n in (7, 33, 49)]
        groups.reset()
        legacy = InferenceEngineV2(
            m, params=params,
            config={"dtype": "float32", "kv_block_size": 16,
                    "prompt_bucket": 16, "max_batch_size": 4})
        want = legacy.generate_all(prompts, max_new_tokens=6)
        groups.reset()
        sf = InferenceEngineV2(
            m, params=params,
            config={"dtype": "float32", "kv_block_size": 16,
                    "prompt_bucket": 16, "max_batch_size": 4,
                    "splitfuse_tokens": 16})
        got = sf.generate_all(prompts, max_new_tokens=6)
        for w, g in zip(want, got):
            np.testing.assert_array_equal(g, w)
