"""Engine end-to-end: ZeRO stage parity (the reference's core correctness
test — tests/unit/runtime/zero/test_zero.py compares stages against DDP)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import GPT2, GPT2Config
from deepspeed_tpu.utils import groups

# compile-heavy: excluded from the fast core set (pytest -m 'not slow')
pytestmark = pytest.mark.slow



CFG = GPT2Config(n_layer=2, n_head=2, d_model=64, max_seq_len=32,
                 vocab_size=256, remat=False, dtype="float32")


def _config(stage=0, micro=2, gas=1, dp=8, **over):
    cfg = {
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": gas,
        "steps_per_print": 0,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "gradient_clipping": 1.0,
        "zero_optimization": {"stage": stage},
    }
    cfg.update(over)
    return cfg


def _batches(n, bsz, seed=0):
    rng = np.random.RandomState(seed)
    return [{"input_ids": rng.randint(0, CFG.vocab_size,
                                      (bsz, CFG.max_seq_len)).astype(np.int32)}
            for _ in range(n)]


def _train(stage, steps=4, gas=1, **over):
    """Repeatedly fit one fixed batch (random tokens are otherwise
    irreducible); parity tests compare trajectories, decrease tests rely on
    memorization."""
    groups.reset()
    model = GPT2(CFG)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config=_config(stage=stage, gas=gas, **over))
    batch = _batches(1, engine.config.train_batch_size)[0]
    losses = [float(engine.train_batch(batch)) for _ in range(steps)]
    return losses, engine


def test_zero0_trains():
    losses, eng = _train(stage=0, steps=5)
    assert losses[-1] < losses[0]
    assert eng.global_step == 5


@pytest.mark.parametrize("stage", [1, 2, 3])
def test_zero_stage_loss_parity(stage):
    """Stages must produce identical losses to stage 0 (same math, different
    memory layout). XLA is deterministic on CPU => tight tolerance."""
    base, _ = _train(stage=0, steps=4)
    got, eng = _train(stage=stage, steps=4)
    np.testing.assert_allclose(got, base, rtol=1e-5, atol=1e-6)
    # check the partitioning actually happened: master leaves sharded over dp
    from jax.sharding import PartitionSpec as P
    specs = jax.tree.leaves(
        eng.plan.master_specs, is_leaf=lambda x: isinstance(x, P))
    assert any(any(e is not None for e in s) for s in specs), \
        f"stage {stage} master specs all replicated"
    if stage >= 3:
        pspecs = jax.tree.leaves(
            eng.plan.param_specs, is_leaf=lambda x: isinstance(x, P))
        assert any(any(e is not None for e in s) for s in pspecs)


def test_grad_accumulation_equivalence():
    """gas=2 with half micro-batch == gas=1 (same global batch)."""
    base, _ = _train(stage=0, steps=3, gas=1, train_micro_batch_size_per_gpu=2)
    got, _ = _train(stage=0, steps=3, gas=2, train_micro_batch_size_per_gpu=1)
    np.testing.assert_allclose(got, base, rtol=1e-5, atol=1e-6)


def test_staged_fwd_bwd_step_matches_train_batch():
    groups.reset()
    model = GPT2(CFG)
    e1, _, _, _ = deepspeed_tpu.initialize(model=model,
                                           config=_config(stage=2, gas=2))
    groups.reset()
    e2, _, _, _ = deepspeed_tpu.initialize(model=model,
                                           config=_config(stage=2, gas=2))
    batches = _batches(2, e1.config.train_batch_size)
    l_fused = [float(e1.train_batch(b)) for b in batches]

    l_staged = []
    gas = e2.config.gradient_accumulation_steps
    per_micro = e2.config.train_batch_size // gas
    for b in batches:
        micro_losses = []
        for i in range(gas):
            micro = {k: v[i * per_micro:(i + 1) * per_micro]
                     for k, v in b.items()}
            loss = e2(micro)
            e2.backward(loss)
            e2.step()
            micro_losses.append(float(loss))
        l_staged.append(float(np.mean(micro_losses)))
    assert e2.global_step == 2
    # the fused path's per-step loss is the mean over its gas micro
    # losses — the staged path must reproduce it step for step
    np.testing.assert_allclose(l_staged, l_fused, rtol=2e-5, atol=1e-6)
    # same state evolution => same final eval loss
    probe = _batches(1, 8, seed=99)[0]
    np.testing.assert_allclose(float(e1.eval_loss(probe)),
                               float(e2.eval_loss(probe)),
                               rtol=1e-5, atol=1e-6)


def test_tp_with_zero2():
    """dp=4 x tp=2 must match pure-dp=8 given the same global batch (16)."""
    base, _ = _train(stage=0, steps=3, micro=2)
    got, eng = _train(stage=2, steps=3, micro=4,
                      tensor_parallel={"size": 2})
    assert eng.config.train_batch_size == 16
    assert eng.topology.get_model_parallel_world_size() == 2
    np.testing.assert_allclose(got, base, rtol=1e-4, atol=1e-5)


def test_bf16_trains():
    groups.reset()
    model = GPT2(GPT2Config(**{**CFG.__dict__, "dtype": "bfloat16"}))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config=_config(stage=2, bf16={"enabled": True}))
    # one FIXED batch, like every other decrease test here (_train's
    # memorization rationale): with a fresh random batch per step the
    # per-batch loss is sampling noise (~±0.02) that swamps the genuine
    # 6-step improvement at lr=1e-3 — the old margin failed on exactly
    # that, not on bf16 numerics
    batch = _batches(1, engine.config.train_batch_size)[0]
    losses = [float(engine.train_batch(batch)) for _ in range(6)]
    assert losses[-1] < losses[0]
    # master kept in fp32
    assert engine.state["master"]["wte"].dtype == jnp.float32
    assert engine.state["params"]["wte"].dtype == jnp.bfloat16
