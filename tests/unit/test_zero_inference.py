"""ZeRO-Inference: weight-only int8 serving (reference README.md:30 —
'20x faster inference via weight quantization'; inference/quantization/).

Weights live in HBM as int8 + per-channel scales; serving paths
dequantize one layer at a time in-program. Tests pin the quantization
math, the ~2x capacity win, and end-to-end serving quality on both
engines."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.int8_weights import (Int8Weight, dequant_tree,
                                            has_quantized, quantize_leaf,
                                            quantize_tree)
from deepspeed_tpu.models import GPT2, GPT2Config, Llama
from deepspeed_tpu.models.llama import LLAMA_TINY
from deepspeed_tpu.utils import groups

# compile-heavy: excluded from the fast core set (pytest -m 'not slow')
pytestmark = pytest.mark.slow


CFG = GPT2Config(n_layer=2, n_head=4, d_model=128, max_seq_len=128,
                 vocab_size=512, remat=False, dtype="float32")


class TestInt8Weights:
    def test_roundtrip_error_bound(self):
        rng = np.random.RandomState(0)
        w = rng.randn(64, 96).astype(np.float32)
        q = quantize_leaf(w)
        back = np.asarray(q.dequant(jnp.float32))
        # symmetric per-channel: |err| <= scale/2 per column
        scale = np.max(np.abs(w), axis=0, keepdims=True) / 127.0
        assert np.all(np.abs(back - w) <= scale / 2 + 1e-7)

    def test_quantize_tree_selects_block_weights_only(self):
        model = GPT2(CFG)
        params = jax.tree.map(np.asarray, model.init(jax.random.key(0)))
        qt = quantize_tree(params, min_size=1024)
        assert has_quantized(qt)
        # embeddings / norms stay float
        assert isinstance(qt["wte"], np.ndarray)
        assert isinstance(qt["lnf_scale"], np.ndarray)
        assert isinstance(qt["blocks"]["wqkv"], Int8Weight)
        assert isinstance(qt["blocks"]["ln1_scale"], np.ndarray)

    def test_capacity_halved(self):
        model = GPT2(CFG)
        params = jax.tree.map(np.asarray, model.init(jax.random.key(0)))
        qt = quantize_tree(params, min_size=1024)

        def nbytes(t):
            return sum(np.asarray(x).nbytes for x in jax.tree.leaves(
                t, is_leaf=lambda y: isinstance(y, np.ndarray)))

        blocks_f32 = nbytes(params["blocks"])
        blocks_q = nbytes(qt["blocks"])
        # fp32 -> int8 + small scales: > 3.5x smaller (vs bf16: ~2x)
        assert blocks_q < blocks_f32 / 3.5

    def test_dequant_tree_identity_on_plain(self):
        t = {"a": jnp.ones((4,)), "b": [jnp.zeros((2,))]}
        out = dequant_tree(t, jnp.float32)
        np.testing.assert_array_equal(out["a"], t["a"])


class TestQuantizedServing:
    def _logit_close(self, a, b):
        # int8 weight error shifts logits slightly; demand the ranking
        # is preserved where it matters (top-1 agreement) and values
        # close in absolute terms
        a, b = np.asarray(a, np.float32), np.asarray(b, np.float32)
        agree = (a.argmax(-1) == b.argmax(-1)).mean()
        assert agree >= 0.9, f"top-1 agreement {agree}"

    def test_v1_generate_int8(self):
        from deepspeed_tpu.inference.engine import InferenceEngine
        model = GPT2(CFG)
        params = model.init(jax.random.key(0))
        rng = np.random.RandomState(0)
        prompt = rng.randint(0, 512, (1, 12)).astype(np.int32)
        groups.reset()
        ref = InferenceEngine(model, config={"dtype": "float32"},
                              params=params)
        want = np.asarray(ref.generate(prompt, max_new_tokens=8,
                                       temperature=0.0))
        groups.reset()
        q = InferenceEngine(model, config={"dtype": "float32",
                                           "quantize_weights": True},
                            params=params)
        got = np.asarray(q.generate(prompt, max_new_tokens=8,
                                    temperature=0.0))
        # logits parity (quantization-tolerant)
        self._logit_close(q.forward(prompt), ref.forward(prompt))
        assert got.shape == want.shape

    def test_v2_paged_int8_end_to_end(self):
        from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2
        model = Llama(LLAMA_TINY.__class__(**{
            **LLAMA_TINY.__dict__, "dtype": "float32"}))
        params = model.init(jax.random.key(0))
        rng = np.random.RandomState(1)
        prompts = [rng.randint(0, 500, (n,)).astype(np.int32)
                   for n in (6, 11)]
        groups.reset()
        ref = InferenceEngineV2(model, params=params,
                                config={"dtype": "float32",
                                        "kv_block_size": 16,
                                        "prompt_bucket": 16,
                                        "max_batch_size": 2})
        want = ref.generate_all(prompts, max_new_tokens=6)
        groups.reset()
        q = InferenceEngineV2(model, params=params,
                              config={"dtype": "float32",
                                      "kv_block_size": 16,
                                      "prompt_bucket": 16,
                                      "max_batch_size": 2,
                                      "quantize_weights": True})
        assert has_quantized(q.params)
        got = q.generate_all(prompts, max_new_tokens=6)
        for w, g in zip(want, got):
            assert g.shape == w.shape
            # greedy decode over quantized weights stays on-distribution:
            # most tokens agree with the bf16 reference on a tiny model
            assert (np.asarray(g) == np.asarray(w)).mean() >= 0.5

    def test_v2_int8_with_splitfuse(self):
        from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2
        model = GPT2(CFG)
        params = model.init(jax.random.key(0))
        rng = np.random.RandomState(2)
        prompts = [rng.randint(0, 512, (20,)).astype(np.int32)]
        groups.reset()
        q = InferenceEngineV2(model, params=params,
                              config={"dtype": "float32",
                                      "kv_block_size": 16,
                                      "prompt_bucket": 16,
                                      "max_batch_size": 2,
                                      "splitfuse_tokens": 16,
                                      "quantize_weights": True})
        out = q.generate_all(prompts, max_new_tokens=5)
        assert out[0].shape == (5,)
