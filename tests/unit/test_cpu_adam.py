"""Host C++ Adam tests (reference tests/unit/ops/adam/test_cpu_adam.py:
numerics vs a reference implementation)."""

import numpy as np
import pytest

from deepspeed_tpu.ops.native.cpu_adam import DeepSpeedCPUAdam


def ref_adamw(params, grads, m, v, steps, lr=1e-3, b1=0.9, b2=0.999,
              eps=1e-8, wd=0.0, adamw=True, bias_correction=True):
    p = params.astype(np.float64).copy()
    m = m.astype(np.float64).copy()
    v = v.astype(np.float64).copy()
    for t in range(1, steps + 1):
        g = grads[t - 1].astype(np.float64)
        if wd and not adamw:
            g = g + wd * p
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        if bias_correction:
            step_size = lr / (1 - b1 ** t)
            denom = np.sqrt(v) / np.sqrt(1 - b2 ** t) + eps
        else:
            step_size = lr
            denom = np.sqrt(v) + eps
        upd = step_size * (m / denom)
        if wd and adamw:
            # torch.optim.AdamW: decoupled decay scales by PLAIN lr,
            # never by the bias-correction factor
            upd = upd + lr * wd * p
        p -= upd
    return p


class TestCPUAdam:
    @pytest.mark.parametrize("wd,adamw", [(0.0, True), (0.01, True),
                                          (0.01, False)])
    def test_matches_reference(self, wd, adamw):
        n = 10_000
        rs = np.random.RandomState(0)
        p0 = rs.randn(n).astype(np.float32)
        grads = [rs.randn(n).astype(np.float32) for _ in range(5)]
        opt = DeepSpeedCPUAdam(lr=1e-2, weight_decay=wd, adamw_mode=adamw,
                               num_threads=4)
        st = opt.create_state(n)
        p = p0.copy()
        for g in grads:
            opt.step(p, g, st)
        ref = ref_adamw(p0, grads, np.zeros(n), np.zeros(n), 5, lr=1e-2,
                        wd=wd, adamw=adamw)
        np.testing.assert_allclose(p, ref, rtol=2e-4, atol=2e-5)
        opt.close()

    def test_bf16_grads(self):
        import ml_dtypes
        n = 4096
        rs = np.random.RandomState(1)
        p = rs.randn(n).astype(np.float32)
        g32 = rs.randn(n).astype(np.float32)
        gbf = g32.astype(ml_dtypes.bfloat16)
        opt = DeepSpeedCPUAdam(lr=1e-2, num_threads=2)
        st = opt.create_state(n)
        p_bf = p.copy()
        opt.step(p_bf, gbf, st)
        opt2 = DeepSpeedCPUAdam(lr=1e-2, num_threads=2)
        st2 = opt2.create_state(n)
        p_f = p.copy()
        opt2.step(p_f, gbf.astype(np.float32), st2)
        np.testing.assert_allclose(p_bf, p_f, rtol=1e-5, atol=1e-6)
        opt.close()
        opt2.close()

    def test_set_lr_and_multitensor_step(self):
        """Multiple tensors in one logical step share the step counter."""
        opt = DeepSpeedCPUAdam(lr=1e-2, num_threads=2)
        a = np.ones(100, np.float32)
        b = np.ones(50, np.float32)
        sa, sb = opt.create_state(100), opt.create_state(50)
        ga = np.full(100, 0.5, np.float32)
        gb = np.full(50, 0.5, np.float32)
        opt.step(a, ga, sa, increment_step=True)
        opt.step(b, gb, sb, increment_step=False)  # same step
        # identical inputs -> identical update
        np.testing.assert_allclose(a[:50], b, rtol=1e-6)
        opt.set_lr(5e-3)
        assert opt.lr == 5e-3
        opt.close()

    def test_offload_roundtrip_with_swapper(self, tmp_path):
        """The ZeRO-Offload shape: state lives on disk between steps."""
        from deepspeed_tpu.runtime.swap_tensor import OptimizerStateSwapper
        opt = DeepSpeedCPUAdam(lr=1e-2, num_threads=2)
        n = 1000
        p = np.random.RandomState(2).randn(n).astype(np.float32)
        st = opt.create_state(n)
        osw = OptimizerStateSwapper(str(tmp_path / "off"))
        for i in range(3):
            st = osw.swap_in_tree("st") if i else st
            g = np.random.RandomState(10 + i).randn(n).astype(np.float32)
            opt.step(p, g, st)
            osw.swap_out_tree("st", st, blocking=True)
        assert np.isfinite(p).all()
        osw.close()
        opt.close()

    def test_adamw_decay_matches_torch(self):
        """Decoupled decay must equal torch.optim.AdamW exactly."""
        import torch
        n = 512
        rs = np.random.RandomState(3)
        p0 = rs.randn(n).astype(np.float32)
        grads = [rs.randn(n).astype(np.float32) for _ in range(4)]
        tp = torch.nn.Parameter(torch.tensor(p0))
        topt = torch.optim.AdamW([tp], lr=1e-2, weight_decay=0.05,
                                 betas=(0.9, 0.999), eps=1e-8)
        for g in grads:
            tp.grad = torch.tensor(g)
            topt.step()
        opt = DeepSpeedCPUAdam(lr=1e-2, weight_decay=0.05, num_threads=2)
        st = opt.create_state(n)
        p = p0.copy()
        for g in grads:
            opt.step(p, g, st)
        np.testing.assert_allclose(p, tp.detach().numpy(), rtol=2e-4,
                                   atol=2e-5)
        opt.close()
