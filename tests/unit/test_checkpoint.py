"""Checkpoint tests, mirroring the reference's tests/unit/checkpoint/ focus:
save/load roundtrip, cross-stage resharding (their DistributedFixture
pattern), async engines, and the native C++ writer."""

import os

import numpy as np
import jax
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import GPT2, GPT2Config
from deepspeed_tpu.utils import groups
from deepspeed_tpu.runtime.checkpoint_engine.engines import (
    SyncCheckpointEngine, AsyncCheckpointEngine, NativeCheckpointEngine,
    NoneCheckpointEngine)
from deepspeed_tpu.runtime.checkpoint_engine import serialization as ser

# compile-heavy: excluded from the fast core set (pytest -m 'not slow')
pytestmark = pytest.mark.slow


CFG = GPT2Config(n_layer=2, n_head=2, d_model=64, max_seq_len=32,
                 vocab_size=256, remat=False, dtype="float32")


def _engine(stage=2, ckpt_type="sync"):
    groups.reset()
    model = GPT2(CFG)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 2,
        "steps_per_print": 0,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": stage},
        "checkpoint_engine": {"type": ckpt_type},
    })
    return engine


def _batch(seed=0, bsz=16):
    rng = np.random.RandomState(seed)
    return {"input_ids": rng.randint(0, CFG.vocab_size,
                                     (bsz, CFG.max_seq_len)).astype(np.int32)}


def test_serialization_roundtrip(tmp_path):
    tree = {"a": np.arange(6, dtype=np.float32).reshape(2, 3),
            "b": {"c": np.ones((4,), np.int32)}}
    p = str(tmp_path / "x.npz")
    ser.save_file(p, tree, extra_meta={"step": 7})
    flat, header = ser.load_file(p)
    out = ser.unflatten_into(tree, flat, header["meta"])
    np.testing.assert_array_equal(out["a"], tree["a"])
    np.testing.assert_array_equal(out["b"]["c"], tree["b"]["c"])
    assert header["extra"]["step"] == 7


def test_save_load_roundtrip(tmp_path):
    e1 = _engine(stage=2)
    b = _batch()
    for _ in range(3):
        e1.train_batch(b)
    tag = e1.save_checkpoint(str(tmp_path), client_state={"note": "hi"})
    loss_before = float(e1.eval_loss(_batch(seed=5)))

    e2 = _engine(stage=2)
    path, client = e2.load_checkpoint(str(tmp_path))
    assert path is not None and client["note"] == "hi"
    assert e2.global_step == 3
    loss_after = float(e2.eval_loss(_batch(seed=5)))
    np.testing.assert_allclose(loss_after, loss_before, rtol=1e-6)
    # training continues identically
    l1 = float(e1.train_batch(b))
    l2 = float(e2.train_batch(b))
    np.testing.assert_allclose(l1, l2, rtol=1e-5)


@pytest.mark.parametrize("save_stage,load_stage", [(2, 0), (0, 3), (3, 1)])
def test_cross_stage_reshard(tmp_path, save_stage, load_stage):
    """A checkpoint saved at one ZeRO stage loads at another (the
    reference's universal-checkpoint capability, natively)."""
    e1 = _engine(stage=save_stage)
    for _ in range(2):
        e1.train_batch(_batch())
    e1.save_checkpoint(str(tmp_path))
    ref = float(e1.eval_loss(_batch(seed=9)))

    e2 = _engine(stage=load_stage)
    e2.load_checkpoint(str(tmp_path))
    got = float(e2.eval_loss(_batch(seed=9)))
    np.testing.assert_allclose(got, ref, rtol=1e-6)


def test_missing_checkpoint_returns_none(tmp_path):
    e = _engine()
    path, client = e.load_checkpoint(str(tmp_path))
    assert path is None


def test_async_engine_roundtrip(tmp_path):
    e1 = _engine(stage=1, ckpt_type="async")
    e1.train_batch(_batch())
    e1.save_checkpoint(str(tmp_path))
    e1.checkpoint_engine.wait()
    e2 = _engine(stage=1, ckpt_type="async")
    path, _ = e2.load_checkpoint(str(tmp_path))
    assert path is not None
    e1.save_checkpoint_terminate()


def test_none_engine_writes_nothing(tmp_path):
    eng = NoneCheckpointEngine()
    eng.save(({"x": np.ones(3)}, {}), str(tmp_path / "no" / "x.npz"))
    assert not os.path.exists(str(tmp_path / "no"))


def test_native_writer_direct(tmp_path):
    """C++ writer pool writes bytes correctly (chunked pwrite)."""
    pytest.importorskip("ctypes")
    from deepspeed_tpu.ops.native.ckpt_writer import Writer
    try:
        w = Writer(threads=4)
    except Exception as e:
        pytest.skip(f"native build unavailable: {e}")
    data = np.random.bytes(1 << 20)
    p = str(tmp_path / "blob.bin")
    w.write(p, data)
    with open(p, "rb") as f:
        assert f.read() == data
    w.close()


def test_native_engine_roundtrip(tmp_path):
    e1 = _engine(stage=2, ckpt_type="native")
    e1.train_batch(_batch())
    e1.save_checkpoint(str(tmp_path))
    e1.checkpoint_engine.wait()
    e2 = _engine(stage=2, ckpt_type="native")
    path, _ = e2.load_checkpoint(str(tmp_path))
    assert path is not None
    ref = float(e1.eval_loss(_batch(seed=3)))
    got = float(e2.eval_loss(_batch(seed=3)))
    np.testing.assert_allclose(got, ref, rtol=1e-6)


def test_sharded_layout_partial_chunks(tmp_path):
    """The new per-host layout (reference engine.py:3545 per-rank ZeRO
    partition files): shard files hold only addressable chunks with a
    reassembly index — a ZeRO-partitioned leaf must appear as MULTIPLE
    partial chunks, not one gathered tensor."""
    import os
    e = _engine(stage=2)
    e.train_batch(_batch())
    tag = e.save_checkpoint(str(tmp_path))
    files = os.listdir(str(tmp_path / tag))
    assert files == ["shard-0.npz"], files   # one process -> one shard file
    flat, header = ser.load_file(str(tmp_path / tag / "shard-0.npz"))
    index = header["extra"]["index"]
    # master leaves are dp-sharded under ZeRO-2: chunked, offsets > 0 exist
    key = "master/blocks/wqkv"
    assert len(index[key]["chunks"]) == 8    # 8-device virtual mesh
    starts = sorted(tuple(c["start"]) for c in index[key]["chunks"])
    assert starts[0] != starts[-1]
    # chunk data are partial slices of the global shape
    ck = index[key]["chunks"][0]["key"]
    assert list(flat[ck].shape) != index[key]["shape"]
    # reassembly reproduces the global logical tensor bit-for-bit
    global_flat, h2 = ser.load_sharded(str(tmp_path / tag))
    assert list(global_flat[key].shape) == index[key]["shape"]
    got = np.sort(np.asarray(
        jax.device_get(e.state["master"]["blocks"]["wqkv"])).ravel())
    np.testing.assert_array_equal(
        np.sort(global_flat[key].ravel()), got)


def test_load_sharded_rejects_missing_shards(tmp_path):
    """Gaps in the np.empty reassembly buffer must raise, not resume
    training from uninitialized memory: a checkpoint whose shard files
    don't cover every leaf (torn save, partial copy, wrong nprocs) is
    rejected at load."""
    import os
    import pytest
    e = _engine(stage=2)
    e.train_batch(_batch())
    tag = e.save_checkpoint(str(tmp_path))
    shard = tmp_path / tag / "shard-0.npz"
    # simulate a second writer whose shard never landed: bump the
    # recorded world size without adding its file
    flat, header = ser.load_file(str(shard))
    header["extra"]["user_extra"]["nprocs"] = 2
    np_arrays = {k.replace("/", "%2F"): v for k, v in flat.items()}
    import json as _json
    np_arrays["__meta__"] = np.frombuffer(
        _json.dumps(header).encode(), dtype=np.uint8)
    with open(str(shard), "wb") as f:
        np.savez(f, **np_arrays)
    with pytest.raises(ValueError, match="nprocs"):
        ser.load_sharded(str(tmp_path / tag))
    # and a chunk-coverage gap (shard file deleted outright, single-proc
    # header) must also raise rather than return np.empty garbage
    header["extra"]["user_extra"]["nprocs"] = 1
    some_chunk = next(k for k in list(np_arrays)
                      if k != "__meta__" and "#" in k)
    del np_arrays[some_chunk]
    np_arrays["__meta__"] = np.frombuffer(
        _json.dumps(header).encode(), dtype=np.uint8)
    with open(str(shard), "wb") as f:
        np.savez(f, **np_arrays)
    with pytest.raises(ValueError, match="chunk|covered"):
        ser.load_sharded(str(tmp_path / tag))


def test_retention_and_corrupt_fallback(tmp_path):
    """End-to-end robustness at the DeepSpeedEngine level: keep_last
    retention GC, and load_checkpoint falling back to the previous
    durable generation when the newest shard is corrupt."""
    groups.reset()
    engine, _, _, _ = deepspeed_tpu.initialize(model=GPT2(CFG), config={
        "train_micro_batch_size_per_gpu": 2,
        "steps_per_print": 0,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 0},
        "checkpoint_engine": {"type": "sync", "keep_last": 2},
    })
    b = _batch()
    ref2 = None
    for i in range(3):
        engine.train_batch(b)
        engine.save_checkpoint(str(tmp_path))
        if i == 1:     # eval state as of the 2nd durable generation
            ref2 = float(engine.eval_loss(_batch(seed=5)))
    tags = sorted(d for d in os.listdir(str(tmp_path))
                  if (tmp_path / d).is_dir())
    assert tags == ["global_step2", "global_step3"]   # keep_last=2
    assert engine.checkpoint_engine.counters["gc_removed"] == 1

    # corrupt the newest generation AFTER it was published
    shard = tmp_path / "global_step3" / "shard-0.npz"
    with open(shard, "r+b") as f:
        f.truncate(os.path.getsize(shard) // 2)

    e2 = _engine(stage=0)
    path, _ = e2.load_checkpoint(str(tmp_path))
    assert path is not None and path.endswith("global_step2")
    assert e2.global_step == 2                        # prior generation
    assert e2.checkpoint_engine.counters["load_fallbacks"] >= 1
    # ...and it is exactly the step-2 training state, not garbage
    np.testing.assert_allclose(float(e2.eval_loss(_batch(seed=5))),
                               ref2, rtol=1e-6)


def test_transient_write_failure_recovers(tmp_path):
    """Acceptance: a save that fails transiently succeeds via retry
    without the training step erroring, and counters record it."""
    from deepspeed_tpu.utils import fault_injection
    e = _engine(stage=1, ckpt_type="async")
    e.train_batch(_batch())
    fault_injection.arm("write", fails=1)
    try:
        e.save_checkpoint(str(tmp_path))
        e.checkpoint_engine.wait()
    finally:
        fault_injection.reset()
    assert e.checkpoint_engine.counters["retries"] >= 1
    assert e.checkpoint_engine.counters["save_errors"] == 0
    e2 = _engine(stage=1)
    path, _ = e2.load_checkpoint(str(tmp_path))
    assert path is not None
    np.testing.assert_allclose(float(e2.eval_loss(_batch(seed=3))),
                               float(e.eval_loss(_batch(seed=3))),
                               rtol=1e-6)
    e.save_checkpoint_terminate()


def test_legacy_monolithic_layout_still_loads(tmp_path):
    """Checkpoints written by the old single-writer layout load through
    the same path."""
    import os
    e = _engine(stage=0)
    for _ in range(2):
        e.train_batch(_batch())
    # write a legacy-format checkpoint by hand
    tree = jax.device_get(e._ckpt_tree())
    tagdir = tmp_path / "legacy_tag"
    os.makedirs(tagdir)
    ser.save_file(str(tagdir / "state.npz"), tree, extra_meta={
        "global_step": 2, "micro_steps": 2, "zero_stage": 0,
        "lr_scheduler": None, "client_state": {"old": True}})
    with open(tmp_path / "latest", "w") as f:
        f.write("legacy_tag")
    e2 = _engine(stage=2)
    path, client = e2.load_checkpoint(str(tmp_path))
    assert path is not None and client["old"] is True
    np.testing.assert_allclose(
        float(e2.eval_loss(_batch(seed=5))),
        float(e.eval_loss(_batch(seed=5))), rtol=1e-6)


def test_d2h_fault_fails_save_before_any_write(tmp_path):
    """Chaos (d2h point): a failure during device->host staging aborts
    the save BEFORE any byte lands — no tag dir, no 'latest', and the
    previous durable generation still loads."""
    from deepspeed_tpu.utils import fault_injection
    from deepspeed_tpu.runtime.checkpoint_engine import manager
    e = _engine(stage=1)
    e.train_batch(_batch())
    e.save_checkpoint(str(tmp_path))
    e.train_batch(_batch())
    fault_injection.arm("d2h", fails=1)
    try:
        with pytest.raises(fault_injection.FaultError):
            e.save_checkpoint(str(tmp_path))
    finally:
        fault_injection.reset()
    assert manager.read_latest(str(tmp_path)) == "global_step1"
    assert not os.path.isdir(str(tmp_path / "global_step2"))
    e2 = _engine(stage=1)
    path, _ = e2.load_checkpoint(str(tmp_path))
    assert path is not None and e2.global_step == 1


def test_engine_hot_tier_roundtrip_and_purge(tmp_path):
    """Engine-level hot tier: saves replicate into the store, a resume
    with the durable dir GONE restores from the tier, and counters
    record zero durable reads."""
    import shutil
    hot_root = str(tmp_path / "hot")
    ckpt = str(tmp_path / "ckpt")

    def eng():
        groups.reset()
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=GPT2(CFG), config={
                "train_micro_batch_size_per_gpu": 2,
                "steps_per_print": 0,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 2},
                "checkpoint_engine": {"type": "async", "hot_tier": True,
                                      "hot_root": hot_root}})
        return engine

    e1 = eng()
    for _ in range(2):
        e1.train_batch(_batch())
    e1.save_checkpoint(ckpt)
    e1.checkpoint_engine.wait()
    e1.hot_store.wait()
    assert e1.checkpoint_engine.counters["hot_pushes"] == 1
    ref = float(e1.eval_loss(_batch(seed=5)))
    shutil.rmtree(ckpt)                       # storage gone entirely

    e2 = eng()
    path, _ = e2.load_checkpoint(ckpt)
    assert path is not None and e2.last_restore_tier == "hot"
    assert e2.checkpoint_engine.counters["hot_restores"] == 1
    assert e2.checkpoint_engine.counters["durable_restores"] == 0
    np.testing.assert_allclose(float(e2.eval_loss(_batch(seed=5))),
                               ref, rtol=1e-6)
    e1.save_checkpoint_terminate()


def test_all_corrupt_exits_corrupt_code_under_elastic_agent(
        tmp_path, monkeypatch):
    """Under an elastic agent (ELASTIC_GENERATION exported), a
    checkpoint with generations but NO loadable one exits with
    CORRUPT_CKPT_EXIT_CODE so the agent classifies corrupt_ckpt (host
    kept, backoff) instead of dead (host dropped); unsupervised, the
    CheckpointCorruptionError still raises."""
    from deepspeed_tpu.elasticity.elastic_agent import (
        CORRUPT_CKPT_EXIT_CODE)
    e = _engine(stage=0)
    e.train_batch(_batch())
    tag = e.save_checkpoint(str(tmp_path))
    shard = tmp_path / tag / "shard-0.npz"
    with open(shard, "r+b") as f:
        f.truncate(10)                       # every generation torn
    e2 = _engine(stage=0)
    with pytest.raises(ser.CheckpointCorruptionError):
        e2.load_checkpoint(str(tmp_path))    # unsupervised: raises
    monkeypatch.setenv("ELASTIC_GENERATION", "1")
    e3 = _engine(stage=0)
    with pytest.raises(SystemExit) as ei:
        e3.load_checkpoint(str(tmp_path))
    assert ei.value.code == CORRUPT_CKPT_EXIT_CODE
