"""Low-precision levers (tier-1): weight-only int8/int4 round trips
(channelwise scales, the two-per-byte int4 packing, the odd-dim
fallback), the dynamic W8A8 matmuls (dense + ragged grouped) with their
straight-through gradients, cold-cache byte-identity for BOTH engines
(a training step with the quantize block present-with-defaults or all
"auto" lowers the exact program the block's absence does; a v2 serving
engine with weight_quant="auto" lowers byte-identical decode/chunk
programs to weight_quant=False), and the W8A16 logit-drift bound on a
fixed tiny checkpoint (greedy token identity is NOT required — the
contract is bounded drift, gated numerics)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.autotuning import kernel_dispatch
from deepspeed_tpu.inference.v2 import InferenceEngineV2
from deepspeed_tpu.models import GPT2, GPT2Config
from deepspeed_tpu.ops.int8_weights import (Int4Weight, Int8Weight,
                                            quantize_leaf)
from deepspeed_tpu.ops.pallas.quantization import (
    dequantize_channelwise, grouped_int8_matmul, int8_matmul, pack_int4,
    quantize_channelwise, unpack_int4)
from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.groups import TopologyConfig


@pytest.fixture(autouse=True)
def _pristine_dispatch(tmp_path, monkeypatch):
    """Private empty winner cache + reset process-global dispatch state
    (cold-cache identity below depends on an actually-cold cache)."""
    monkeypatch.setenv("DSTPU_AUTOTUNE_CACHE",
                       str(tmp_path / "kernel_autotune.json"))
    monkeypatch.delenv("DSTPU_AUTOTUNE", raising=False)
    kernel_dispatch.reset()
    yield
    kernel_dispatch.reset()


# ---------------------------------------------------------------------------
# round trips: channel scales, int4 packing, host-side quantize_leaf
# ---------------------------------------------------------------------------

class TestRoundTrip:
    def test_channelwise_int8_roundtrip_bound(self):
        rng = np.random.RandomState(0)
        w = rng.randn(64, 48).astype(np.float32)
        q, s = quantize_channelwise(jnp.asarray(w), bits=8)
        assert q.dtype == jnp.int8 and s.shape == (1, 48)
        back = np.asarray(dequantize_channelwise(q, s, jnp.float32))
        # symmetric absmax/127: error <= scale/2 per element
        assert np.all(np.abs(back - w) <= np.asarray(s)[0] / 2 + 1e-7)

    def test_channelwise_int4_uses_code_range_7(self):
        w = jnp.asarray(np.random.RandomState(1).randn(32, 8),
                        jnp.float32)
        q, s = quantize_channelwise(w, bits=4)
        qn = np.asarray(q)
        assert qn.min() >= -7 and qn.max() <= 7
        # the absmax element quantizes to exactly +-7
        assert np.max(np.abs(qn), axis=0).min() == 7

    def test_zero_column_gets_unit_scale(self):
        w = jnp.zeros((16, 4), jnp.float32)
        q, s = quantize_channelwise(w, bits=8)
        assert np.all(np.asarray(s) == 1.0)
        assert np.all(np.asarray(q) == 0)

    def test_int4_pack_unpack_is_bitwise(self):
        rng = np.random.RandomState(2)
        q = rng.randint(-7, 8, (2, 64, 24)).astype(np.int8)
        p = pack_int4(jnp.asarray(q))
        assert p.shape == (2, 32, 24) and p.dtype == jnp.int8
        assert np.array_equal(np.asarray(unpack_int4(p)), q)

    def test_int4_pack_layout_pins_the_nibble_order(self):
        # byte[r, c] = (q[2r+1, c] << 4) | (q[2r, c] & 0xF) — the layout
        # the fused kernel epilogues decode; a silent swap would pass a
        # pack/unpack round trip but break every shipped checkpoint
        q = jnp.asarray([[3], [-2]], jnp.int8)
        byte = int(np.asarray(pack_int4(q))[0, 0])
        assert byte == np.int8((-2 << 4) | (3 & 0xF))

    def test_int4_pack_rejects_odd_contracted_dim(self):
        with pytest.raises(ValueError, match="even"):
            pack_int4(jnp.zeros((5, 4), jnp.int8))

    def test_quantize_leaf_int8_roundtrip(self):
        rng = np.random.RandomState(3)
        w = rng.randn(64, 32).astype(np.float32)
        qw = quantize_leaf(w, bits=8)
        assert isinstance(qw, Int8Weight)
        back = np.asarray(qw.dequant(jnp.float32))
        assert np.all(np.abs(back - w) <= qw.scale[0] / 2 + 1e-7)

    def test_quantize_leaf_int4_packs_and_roundtrips(self):
        rng = np.random.RandomState(4)
        w = rng.randn(64, 32).astype(np.float32)
        qw = quantize_leaf(w, bits=4)
        assert isinstance(qw, Int4Weight)
        assert qw.q.shape == (32, 32)          # two codes per byte
        back = np.asarray(qw.dequant(jnp.float32))
        assert np.all(np.abs(back - w) <= qw.scale[0] / 2 + 1e-7)

    def test_quantize_leaf_int4_odd_dim_falls_back_to_int8(self):
        w = np.random.RandomState(5).randn(65, 32).astype(np.float32)
        assert isinstance(quantize_leaf(w, bits=4), Int8Weight)


# ---------------------------------------------------------------------------
# dynamic W8A8 matmuls (dense + ragged) and their straight-through grads
# ---------------------------------------------------------------------------

class TestInt8Matmul:
    def test_dense_close_to_fp(self):
        rng = np.random.RandomState(6)
        x = jnp.asarray(rng.randn(4, 96, 64) * 0.5, jnp.float32)
        w = jnp.asarray(rng.randn(64, 48) / 8.0, jnp.float32)
        got = np.asarray(int8_matmul(x, w))
        ref = np.asarray(jnp.einsum("btk,km->btm", x, w))
        np.testing.assert_allclose(got, ref, rtol=5e-2, atol=5e-2)

    def test_dense_grads_are_straight_through_fp(self):
        rng = np.random.RandomState(7)
        x = jnp.asarray(rng.randn(32, 64) * 0.5, jnp.float32)
        w = jnp.asarray(rng.randn(64, 48) / 8.0, jnp.float32)
        gx, gw = jax.grad(
            lambda a, b: jnp.sum(int8_matmul(a, b) ** 2), (0, 1))(x, w)
        rx, rw = jax.grad(
            lambda a, b: jnp.sum((a @ b) ** 2), (0, 1))(x, w)
        # backward is exact fp of the QUANTIZED forward's cotangent —
        # close to the fp/fp grads within the forward's own error
        np.testing.assert_allclose(np.asarray(gx), np.asarray(rx),
                                   rtol=1e-1, atol=1e-1)
        np.testing.assert_allclose(np.asarray(gw), np.asarray(rw),
                                   rtol=1e-1, atol=1e-1)

    def test_grouped_close_to_ragged_dot(self):
        rng = np.random.RandomState(8)
        S, E, K, N = 128, 4, 32, 24
        x = jnp.asarray(rng.randn(S, K) * 0.5, jnp.float32)
        w = jnp.asarray(rng.randn(E, K, N) / 8.0, jnp.float32)
        sizes = jnp.asarray(np.bincount(np.arange(S) * 7919 % E,
                                        minlength=E), jnp.int32)
        got = np.asarray(grouped_int8_matmul(x, w, sizes))
        ref = np.asarray(jax.lax.ragged_dot(x, w, sizes))
        np.testing.assert_allclose(got, ref, rtol=5e-2, atol=5e-2)

    def test_grouped_grads_finite_and_close(self):
        rng = np.random.RandomState(9)
        S, E, K, N = 64, 4, 16, 8
        x = jnp.asarray(rng.randn(S, K) * 0.5, jnp.float32)
        w = jnp.asarray(rng.randn(E, K, N) / 4.0, jnp.float32)
        sizes = jnp.asarray([16, 16, 16, 16], jnp.int32)
        gx, gw = jax.grad(
            lambda a, b: jnp.sum(grouped_int8_matmul(a, b, sizes) ** 2),
            (0, 1))(x, w)
        rx, rw = jax.grad(
            lambda a, b: jnp.sum(jax.lax.ragged_dot(a, b, sizes) ** 2),
            (0, 1))(x, w)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(rx),
                                   rtol=1e-1, atol=1e-1)
        np.testing.assert_allclose(np.asarray(gw), np.asarray(rw),
                                   rtol=1e-1, atol=1e-1)


# ---------------------------------------------------------------------------
# cold-cache byte-identity: the training step
# ---------------------------------------------------------------------------

_TCFG = GPT2Config(n_layer=2, n_head=2, d_model=64, max_seq_len=32,
                   vocab_size=256, remat=False, dtype="float32")


def _train_engine(extra):
    groups.reset()
    topo = groups.initialize(TopologyConfig(data_parallel_size=2),
                             devices=jax.devices()[:2], force=True)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2(_TCFG), topology=topo, config={
            "train_batch_size": 4, "steps_per_print": 0,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 2},
            **extra,
        })
    return engine


def _train_text(engine):
    rng = np.random.RandomState(0)
    batch = {"input_ids": rng.randint(
        0, _TCFG.vocab_size, (4, _TCFG.max_seq_len)).astype(np.int32)}
    batch = jax.tree.map(engine._add_gas_dim, batch)
    batch = engine._shard_batch(batch, with_gas_dim=True)
    with jax.set_mesh(engine.mesh):
        return engine._train_step_jit.lower(
            engine.state, batch, engine._current_lr(), None).as_text()


def test_quantize_block_cold_cache_is_byte_identical():
    """The block's presence with defaults, and with every knob at
    "auto" on a cold winner cache, lowers the EXACT training program
    its absence does — adopting the quantize block costs nothing until
    a knob is committed."""
    base = _train_text(_train_engine({}))
    assert base == _train_text(_train_engine({"quantize": {}}))
    assert base == _train_text(_train_engine({"quantize": {
        "grad_dcn": "auto", "moe_dcn": "auto",
        "int8_matmul": "auto", "moe_int8_matmul": "auto"}}))


def test_int8_matmul_forced_on_changes_the_program():
    """The identity test above is non-vacuous: forcing the lever
    actually lands int8 compute in the lowered step."""
    txt = _train_text(_train_engine({"quantize": {"int8_matmul": True}}))
    assert txt != _train_text(_train_engine({}))
    assert "s8" in txt or "i8" in txt


# ---------------------------------------------------------------------------
# cold-cache byte-identity: the v2 serving engine (weight_quant)
# ---------------------------------------------------------------------------

# d_model must clear quantize_tree's min_size floor (1 << 16 elements)
# or weight_quant engines silently serve fp and every test here goes
# vacuous: at d_model=128/n_layer=2 the stacked wqkv/wup/wdown leaves
# quantize, wo (32k elements) stays fp — a real mixed pool
_SCFG = GPT2Config(n_layer=2, n_head=4, d_model=128, max_seq_len=128,
                   vocab_size=256, remat=False, dtype="float32")
_SBASE = {"dtype": "float32", "kv_block_size": 8, "prompt_bucket": 16,
          "max_batch_size": 2, "splitfuse_tokens": 16,
          "decode_steps_per_dispatch": 2}
_SPARAMS = None


def _sparams():
    global _SPARAMS
    if _SPARAMS is None:
        _SPARAMS = GPT2(_SCFG).init(jax.random.key(0))
    return _SPARAMS


def _serve_engine(**kw):
    groups.reset()
    # fresh tree containers per build: quantized pool construction
    # consumes its input dict host-side (consume=True frees fp leaves)
    params = jax.tree.map(lambda x: x, _sparams())
    return InferenceEngineV2(GPT2(_SCFG), params=params,
                             config=dict(_SBASE, **kw))


def _serve_texts(eng):
    B = eng.config.max_batch_size
    MB = eng.max_blocks_per_seq
    i32, f32 = np.int32, np.float32
    z = np.zeros
    rng = jax.random.key(0)
    with jax.set_mesh(eng.mesh):
        dec = eng._get_decode().lower(
            eng.params, eng.cache, z((B,), i32), z((B,), i32),
            z((B, MB), i32), rng, z((B,), f32), z((B,), i32),
            True).as_text()
        C = eng.config.splitfuse_tokens
        chk = eng._get_chunk_only().lower(
            eng.params, eng.cache, z((1, C), i32), z((C,), i32),
            z((C,), i32), i32(0), i32(0), z((MB,), i32), f32(0),
            i32(0), rng, True).as_text()
    return dec, chk


def test_weight_quant_auto_cold_is_byte_identical_to_off():
    """weight_quant="auto" (the shipped default) resolves OFF on a cold
    winner cache: fp params in the pool and decode/chunk programs
    byte-identical to weight_quant=False."""
    auto = _serve_engine(weight_quant="auto")
    assert not any(isinstance(x, (Int8Weight, Int4Weight))
                   for x in jax.tree.leaves(
                       auto.params,
                       is_leaf=lambda x: isinstance(
                           x, (Int8Weight, Int4Weight))))
    # and the forced engine DOES build a quantized pool (non-vacuous)
    q8 = _serve_engine(weight_quant="int8")
    assert any(isinstance(x, Int8Weight)
               for x in jax.tree.leaves(
                   q8.params,
                   is_leaf=lambda x: isinstance(x, Int8Weight)))
    t_auto = _serve_texts(auto)
    assert t_auto == _serve_texts(_serve_engine(weight_quant=False))


def test_weight_quant_int8_shrinks_the_pool():
    off = _serve_engine(weight_quant=False)
    q8 = _serve_engine(weight_quant="int8")
    nb = lambda e: sum(np.prod(x.shape) * x.dtype.itemsize  # noqa: E731
                       for x in jax.tree.leaves(e.params))
    assert nb(q8) < 0.55 * nb(off)      # fp32 pool -> ~int8 + scales


def test_weight_quant_junk_rejected():
    with pytest.raises(ValueError, match="weight_quant"):
        _serve_engine(weight_quant="int3")


# ---------------------------------------------------------------------------
# W8A16 logit drift on a fixed tiny checkpoint
# ---------------------------------------------------------------------------

def _prefill_logits(eng, ids, length):
    """Prefill ONE fixed prompt through the engine's own model + cache
    layout and return the next-token logits (1, V)."""
    BS = eng.config.kv_block_size
    T = ids.shape[1]
    tb = np.repeat(np.arange(1, T // BS + 1), BS).astype(np.int32)
    to = np.tile(np.arange(BS), T // BS).astype(np.int32)

    def f(params, cache):
        eng._install_trace_state()
        logits, _ = eng.model.apply_paged_prefill(
            params, jnp.asarray(ids), cache, jnp.asarray(tb),
            jnp.asarray(to), jnp.int32(length))
        return logits

    with jax.set_mesh(eng.mesh):
        return np.asarray(jax.jit(f)(eng.params, eng.cache),
                          np.float32)


def test_w8a16_logit_drift_is_bounded():
    """The acceptance gate for weight-only int8 serving: on a FIXED
    tiny checkpoint (seeded init), W8A16 next-token logits stay within
    a small drift envelope of the fp engine — greedy token identity is
    NOT required, bounded drift is."""
    rng = np.random.RandomState(0)
    L = 24
    ids = np.zeros((1, 32), np.int32)
    ids[0, :L] = rng.randint(0, _SCFG.vocab_size, L)
    ref = _prefill_logits(_serve_engine(weight_quant=False), ids, L)
    got = _prefill_logits(_serve_engine(weight_quant="int8"), ids, L)
    assert np.all(np.isfinite(got))
    scale = np.abs(ref).max()
    drift = np.abs(got - ref).max()
    assert drift < 0.05 * scale, (
        f"W8A16 drifted {drift:.4f} vs fp logit scale {scale:.4f}")
