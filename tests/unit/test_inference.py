"""Inference engine tests: cached decode == full forward, TP generate,
sampling, EOS handling. Reference coverage model:
tests/unit/inference/test_inference.py (HF-model matrix) scaled down to the
in-repo zoo."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import GPT2, GPT2Config
from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.groups import TopologyConfig

# compile-heavy: excluded from the fast core set (pytest -m 'not slow')
pytestmark = pytest.mark.slow



CFG = GPT2Config(n_layer=2, n_head=4, d_model=64, max_seq_len=128,
                 vocab_size=256, remat=False, dtype="float32")


def _model_params():
    model = GPT2(CFG)
    params = model.init(jax.random.key(0))
    return model, params


class TestCachedDecode:
    def test_prefill_matches_full_forward(self):
        model, params = _model_params()
        ids = jax.random.randint(jax.random.key(1), (2, 16), 0,
                                 CFG.vocab_size)
        full = model.apply(params, ids)
        cache = model.init_cache(2, 32, dtype="float32")
        Tmax = 32
        valid = (jnp.arange(Tmax)[None, :] < 16) * jnp.ones((2, 1),
                                                            jnp.bool_)
        pos = jnp.tile(jnp.arange(16)[None, :], (2, 1)).astype(jnp.int32)
        logits, cache = model.apply_cached(params, ids, pos, cache, 0, valid)
        np.testing.assert_allclose(np.asarray(logits), np.asarray(full),
                                   rtol=2e-4, atol=2e-4)

    def test_incremental_decode_matches_full(self):
        """Prefill T tokens then decode one more == full forward on T+1."""
        model, params = _model_params()
        T = 12
        ids = jax.random.randint(jax.random.key(2), (1, T + 1), 0,
                                 CFG.vocab_size)
        full = model.apply(params, ids)

        Tmax = 32
        cache = model.init_cache(1, Tmax, dtype="float32")
        valid = (jnp.arange(Tmax)[None, :] < T)
        pos = jnp.arange(T)[None, :].astype(jnp.int32)
        _, cache = model.apply_cached(params, ids[:, :T], pos, cache, 0,
                                      valid)
        valid = (jnp.arange(Tmax)[None, :] < T + 1)
        logits, _ = model.apply_cached(
            params, ids[:, T:T + 1],
            jnp.full((1, 1), T, jnp.int32), cache, T, valid)
        np.testing.assert_allclose(np.asarray(logits[:, 0]),
                                   np.asarray(full[:, -1]),
                                   rtol=2e-4, atol=2e-4)

    def test_left_padding_is_ignored(self):
        """A left-padded prompt decodes the same logits as unpadded."""
        model, params = _model_params()
        T, P_len = 8, 5
        ids = jax.random.randint(jax.random.key(3), (1, P_len), 0,
                                 CFG.vocab_size)
        Tmax = 16
        # unpadded
        cache = model.init_cache(1, Tmax, dtype="float32")
        valid = (jnp.arange(Tmax)[None, :] < P_len)
        logits_a, _ = model.apply_cached(
            params, ids, jnp.arange(P_len)[None, :].astype(jnp.int32),
            cache, 0, valid)
        # left-padded to T
        pad = T - P_len
        ids_p = jnp.concatenate(
            [jnp.zeros((1, pad), jnp.int32), ids], axis=1)
        cache = model.init_cache(1, Tmax, dtype="float32")
        valid = ((jnp.arange(Tmax)[None, :] >= pad)
                 & (jnp.arange(Tmax)[None, :] < T))
        pos = jnp.maximum(jnp.arange(T)[None, :] - pad, 0).astype(jnp.int32)
        logits_b, _ = model.apply_cached(params, ids_p, pos, cache, 0, valid)
        np.testing.assert_allclose(np.asarray(logits_a[:, -1]),
                                   np.asarray(logits_b[:, -1]),
                                   rtol=2e-4, atol=2e-4)


class TestInferenceEngine:
    def test_greedy_generate_matches_manual(self):
        model, params = _model_params()
        engine = deepspeed_tpu.init_inference(
            model, params=params, dtype="float32",
            config={"dtype": "float32", "prompt_bucket": 16})
        prompt = np.arange(7)[None, :] % CFG.vocab_size
        out = engine.generate(prompt, max_new_tokens=5, temperature=0.0)
        assert out.shape == (1, 5)
        # manual greedy roll-out with full forwards
        ids = prompt.astype(np.int32)
        for i in range(5):
            logits = np.asarray(model.apply(params, jnp.asarray(ids)))
            nxt = int(np.argmax(logits[0, -1]))
            assert nxt == out[0, i], f"token {i}: {nxt} != {out[0, i]}"
            ids = np.concatenate([ids, [[nxt]]], axis=1)

    def test_variable_length_batch(self):
        model, params = _model_params()
        engine = deepspeed_tpu.init_inference(
            model, params=params,
            config={"dtype": "float32", "prompt_bucket": 16})
        prompts = [np.arange(3), np.arange(9), np.arange(5)]
        out = engine.generate(prompts, max_new_tokens=4, temperature=0.0)
        assert out.shape == (3, 4)
        # each row must equal its single-prompt greedy generation
        for i, p in enumerate(prompts):
            solo = engine.generate([p], max_new_tokens=4, temperature=0.0)
            np.testing.assert_array_equal(out[i], solo[0])

    def test_tp_generate_matches_single(self):
        model, params = _model_params()
        groups.reset()
        topo = groups.initialize(TopologyConfig(tensor_parallel_size=4))
        engine_tp = deepspeed_tpu.init_inference(
            model, params=params, topology=topo,
            config={"dtype": "float32", "prompt_bucket": 8,
                    "tensor_parallel": {"tp_size": 4}})
        prompt = (np.arange(6)[None, :] * 7) % CFG.vocab_size
        out_tp = engine_tp.generate(prompt, max_new_tokens=6,
                                    temperature=0.0)
        groups.reset()
        engine_1 = deepspeed_tpu.init_inference(
            model, params=params,
            config={"dtype": "float32", "prompt_bucket": 8})
        out_1 = engine_1.generate(prompt, max_new_tokens=6, temperature=0.0)
        np.testing.assert_array_equal(out_tp, out_1)

    def test_eos_stops_sequence(self):
        model, params = _model_params()
        engine = deepspeed_tpu.init_inference(
            model, params=params,
            config={"dtype": "float32", "prompt_bucket": 8})
        prompt = np.arange(4)[None, :]
        # force eos = the greedy first token -> everything after is eos
        first = engine.generate(prompt, max_new_tokens=1,
                                temperature=0.0)[0, 0]
        out = engine.generate(prompt, max_new_tokens=5, temperature=0.0,
                              eos_token_id=int(first))
        assert (out[0] == first).all()

    def test_sampling_reproducible(self):
        model, params = _model_params()
        engine = deepspeed_tpu.init_inference(
            model, params=params,
            config={"dtype": "float32", "prompt_bucket": 8})
        prompt = np.arange(4)[None, :]
        a = engine.generate(prompt, max_new_tokens=6, temperature=1.0,
                            top_k=50, seed=3)
        b = engine.generate(prompt, max_new_tokens=6, temperature=1.0,
                            top_k=50, seed=3)
        np.testing.assert_array_equal(a, b)

    def test_load_training_checkpoint(self, tmp_path):
        model, params = _model_params()
        groups.reset()
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=GPT2(CFG),
            config={"train_micro_batch_size_per_gpu": 2,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                    "steps_per_print": 0})
        batch = {"input_ids": np.random.RandomState(0).randint(
            0, CFG.vocab_size, (engine.config.train_batch_size, 32))
            .astype(np.int32)}
        engine.train_batch(batch)
        engine.save_checkpoint(str(tmp_path))
        groups.reset()
        inf = deepspeed_tpu.init_inference(
            GPT2(CFG), config={"dtype": "float32", "prompt_bucket": 8})
        inf.load_checkpoint(str(tmp_path))
        trained = jax.device_get(engine.state["master"])
        loaded = jax.device_get(inf.params)
        np.testing.assert_allclose(
            np.asarray(loaded["wte"], np.float32),
            np.asarray(trained["wte"], np.float32), rtol=1e-6)



class TestTracedSamplingPrograms:
    """Sampling params are traced (v2 parity): differing temperature /
    top_k / top_p tuples share ONE compiled program per shape bucket;
    only the greedy/sampling structure splits programs."""

    def test_one_program_across_sampling_configs(self):
        from deepspeed_tpu.models import GPT2, GPT2Config
        cfg = GPT2Config(n_layer=1, n_head=2, d_model=64, max_seq_len=64,
                         vocab_size=128, dtype="float32", remat=False)
        from deepspeed_tpu.inference.engine import InferenceEngine
        groups.reset()
        eng = InferenceEngine(GPT2(cfg), config={"dtype": "float32",
                                                 "prompt_bucket": 8})
        ids = np.random.RandomState(0).randint(0, 128, (1, 6))
        for t, k, p in [(0.7, 0, 1.0), (1.3, 5, 1.0), (0.9, 0, 0.8),
                        (1.0, 10, 0.95)]:
            eng.generate(ids, max_new_tokens=3, temperature=t, top_k=k,
                         top_p=p, seed=0)
        # 4 sampling configs -> ONE cached program (plus none for greedy)
        assert len(eng._generate_cache) == 1
        eng.generate(ids, max_new_tokens=3, temperature=0.0, seed=0)
        assert len(eng._generate_cache) == 2   # greedy structure splits

    def test_traced_topk_matches_static_semantics(self):
        """Traced top-k (dynamic k-th-largest threshold) restricts
        sampling to exactly the k most likely tokens."""
        from deepspeed_tpu.inference.engine import _sample
        rng = np.random.RandomState(0)
        logits = jnp.asarray(rng.randn(4, 64) * 3, jnp.float32)
        top3 = np.argsort(np.asarray(logits), axis=-1)[:, -3:]
        draws = [_sample(logits, jax.random.key(i), jnp.float32(1.0),
                         jnp.int32(3), jnp.float32(1.0), False)
                 for i in range(32)]
        for d in draws:
            for b in range(4):
                assert int(np.asarray(d)[b]) in top3[b]
