"""Serving-fleet router (tier-1): admission control + typed load
shedding, deadline enforcement through the flush()/unref path, the
replica health state machine with chaos-tested failover (armed
replica_death mid-decode -> byte-identical replay on a survivor),
drained scale-down, prefix-affinity dispatch, the Serve/Router/* tag
emission, and the engine cancel() pool-accounting audit.

Engines here follow the test_prefix_cache.py fast pattern: tiny GPT2,
module-cached params, small pools — every test runs inside tier-1."""

import numpy as np
import pytest

import jax

from deepspeed_tpu.autotuning import kernel_dispatch
from deepspeed_tpu.inference.v2 import (DeadlineExceeded,
                                        InferenceEngineV2, Overloaded,
                                        Router, RouterConfig)
from deepspeed_tpu.inference.v2.replica import Replica
from deepspeed_tpu.models import GPT2, GPT2Config
from deepspeed_tpu.monitor.tag_schema import TAG_SCHEMA
from deepspeed_tpu.utils import fault_injection, groups


@pytest.fixture(autouse=True)
def _pristine_dispatch(tmp_path, monkeypatch):
    """Private winner cache + reset process-global dispatch state, and
    no armed faults leaking across tests."""
    monkeypatch.setenv("DSTPU_AUTOTUNE_CACHE",
                       str(tmp_path / "kernel_autotune.json"))
    monkeypatch.delenv("DSTPU_AUTOTUNE", raising=False)
    kernel_dispatch.reset()
    fault_injection.reset()
    yield
    fault_injection.reset()
    kernel_dispatch.reset()


_CFG = GPT2Config(n_layer=2, n_head=4, d_model=64, max_seq_len=128,
                  vocab_size=256, remat=False, dtype="float32")
_PARAMS = None


def _params():
    global _PARAMS
    if _PARAMS is None:
        _PARAMS = GPT2(_CFG).init(jax.random.key(0))
    return _PARAMS


_BASE = {"dtype": "float32", "kv_block_size": 8, "prompt_bucket": 16,
         "max_batch_size": 2, "splitfuse_tokens": 16,
         "decode_steps_per_dispatch": 2,   # small unroll = fast compiles
         "prefix_cache_min_match": 1}


def _engine(**kw):
    groups.reset()
    return InferenceEngineV2(GPT2(_CFG), params=_params(),
                             config=dict(_BASE, **kw))


# Engine compiles dominate this file's runtime, so clean-completion
# tests share one module-cached pair (every request leaves through
# get()/typed exits, so the engines stay reusable; each test builds its
# OWN Router + Replica wrappers around them). Tests that poison an
# engine — kill/step-death strand sequences, telemetry-count asserts —
# build fresh ones.
_FLEET = None
_REF = None


def _fleet():
    global _FLEET
    if _FLEET is None:
        _FLEET = (_engine(prefix_cache=True), _engine(prefix_cache=True))
    return _FLEET


def _ref_outputs():
    """Uninterrupted single-replica reference for _prompts(1, 4) at
    max_new 8 (shared by the roundtrip + chaos byte-identity tests)."""
    global _REF
    if _REF is None:
        _REF = [_fleet()[0].generate_all([p], max_new_tokens=8)[0]
                for p in _prompts(1, 4)]
    return _REF


def _prompts(seed, n, lo=6, hi=20):
    rs = np.random.RandomState(seed)
    return [rs.randint(1, 255, size=rs.randint(lo, hi)).astype(np.int32)
            for _ in range(n)]


def _run(router, max_rounds=400):
    rounds = 0
    while router.has_work:
        router.step()
        rounds += 1
        assert rounds < max_rounds, "router failed to drain"
    return rounds


def _pool_closed(eng):
    """The overload/deadline acceptance invariant: every block is back
    in the free list or adopted by the prefix tree — nothing leaked."""
    alloc = eng.state_mgr.allocator
    tree = eng.prefix_cache.tree_blocks if eng.prefix_cache else 0
    assert alloc.free_blocks + tree == alloc.total_blocks, (
        f"leaked blocks: free={alloc.free_blocks} tree={tree} "
        f"total={alloc.total_blocks}")


# ---------------------------------------------------------------------------
# config validation (the planner-lint construction-probe contract)
# ---------------------------------------------------------------------------

class TestRouterConfig:
    def test_auto_knobs_accept_auto_and_reject_junk(self):
        RouterConfig(router_queue_depth="auto", shed_policy="auto",
                     prefix_affinity="auto")
        for field in ("router_queue_depth", "shed_policy",
                      "prefix_affinity"):
            with pytest.raises(ValueError):
                RouterConfig(**{field: "___junk___"})

    def test_numeric_validation(self):
        with pytest.raises(ValueError):
            RouterConfig(router_queue_depth=0)
        with pytest.raises(ValueError):
            RouterConfig(breach_rounds=0)
        with pytest.raises(ValueError):
            RouterConfig(shed_low_pct=80, shed_high_pct=50)
        with pytest.raises(ValueError):
            RouterConfig(slo_ttft_ms=-1)

    def test_queue_depth_resolution(self):
        r = Router(list(_fleet()))
        # "auto" = 4x aggregate slots (2 replicas x max_batch 2)
        assert r.resolved_queue_depth() == 16
        r.replicas[1].mark_dead("test")
        assert r.resolved_queue_depth() == 8   # capacity-proportional
        r2 = Router([r.replicas[0]], router_queue_depth=5)
        assert r2.resolved_queue_depth() == 5


# ---------------------------------------------------------------------------
# basics: multi-replica roundtrip, byte-identity, prefix affinity
# ---------------------------------------------------------------------------

class TestRouterBasics:
    def test_roundtrip_matches_single_engine(self):
        prompts = _prompts(1, 4)
        want = _ref_outputs()
        router = Router(list(_fleet()))
        uids = [router.put(p, max_new_tokens=8) for p in prompts]
        _run(router)
        for uid, w in zip(uids, want):
            assert router.is_done(uid)
            np.testing.assert_array_equal(router.get(uid), w)
        snap = router.snapshot()
        assert snap["admitted"] == snap["completed"] == 4
        assert snap["shed"] == snap["expired"] == 0
        assert snap["failovers"] == snap["replayed"] == 0
        # work actually spread over the fleet
        assert all(r.steps > 0 for r in router.replicas)
        for rep in router.replicas:
            _pool_closed(rep.engine)

    def test_prefix_affinity_routes_to_the_cached_replica(self):
        # shared fleet is safe here: earlier tests cached only random
        # prompts, which cannot share a full 8-token block with the
        # arange template, so the affinity signal is unambiguous
        router = Router(list(_fleet()))
        template = np.arange(1, 33, dtype=np.int32)   # 4 full blocks
        uid = router.put(template, max_new_tokens=4)
        _run(router)
        home = router._reqs[uid].replica
        router.get(uid)
        assert home is not None
        # the shared-prefix follow-ups all land on the template's home
        for i in range(3):
            ext = np.concatenate(
                [template, np.asarray([100 + i], np.int32)])
            u2 = router.put(ext, max_new_tokens=4)
            router.step()              # dispatch boundary
            assert router._reqs[u2].replica == home, \
                "affinity ignored the radix-tree match"
            _run(router)
            router.get(u2)


# ---------------------------------------------------------------------------
# chaos acceptance: replica death mid-decode, drain, step-failure health
# ---------------------------------------------------------------------------

@pytest.mark.chaos
class TestChaosFailover:
    def test_replica_death_mid_decode_replays_byte_identical(self):
        """The ISSUE-17 chaos acceptance test: armed ``replica_death``
        kills one of two replicas mid-decode; every in-flight request
        completes on the survivor, greedy outputs byte-identical to an
        uninterrupted single-replica run, counters match, zero drops."""
        prompts = _prompts(1, 4)
        want = _ref_outputs()
        # fresh engines: the victim's engine keeps stranded sequences
        # after the kill, so the shared fleet must not be used here
        router = Router([_engine(prefix_cache=True),
                         _engine(prefix_cache=True)])
        uids = [router.put(p, max_new_tokens=8) for p in prompts]
        for _ in range(3):              # get decodes genuinely mid-flight
            router.step()
        victim = next(r for r in router.replicas if r.has_work)
        n_inflight = len(victim.inflight)
        assert n_inflight > 0, "nothing in flight before the kill"
        fault_injection.arm("replica_death", fails=1)
        _run(router)
        snap = router.snapshot()
        assert snap["failovers"] == 1
        assert snap["replayed"] == n_inflight
        assert snap["completed"] == 4            # zero dropped requests
        assert snap["replicas"][victim.name] == "dead"
        assert not victim.drained                # died, not drained
        survivors = [r for r in router.replicas if not r.dead]
        assert len(survivors) == 1 and survivors[0].live
        for uid, w in zip(uids, want):
            np.testing.assert_array_equal(router.get(uid), w)
        _pool_closed(survivors[0].engine)

    def test_drain_finishes_inflight_without_replay(self):
        """The drain() variant of the acceptance test: scale-down
        finishes in-flight work (no replay) and removes the replica;
        new work lands on the survivor."""
        prompts = _prompts(2, 4)
        router = Router(list(_fleet()))
        uids = [router.put(p, max_new_tokens=6) for p in prompts]
        router.step()
        router.drain("r0")
        assert router.snapshot()["draining"] == 1
        _run(router)
        snap = router.snapshot()
        assert snap["completed"] == 4
        assert snap["failovers"] == 0 and snap["replayed"] == 0
        assert snap["replicas"]["r0"] == "dead"
        assert router.replicas[0].drained        # clean exit, not death
        u_new = router.put(prompts[0], max_new_tokens=4)
        _run(router)
        assert len(router.get(u_new)) == 4
        assert router._reqs.get(u_new) is None   # flushed by get
        assert router.snapshot()["replicas"]["r1"] == "live"

    def test_step_failures_break_the_heartbeat_then_fail_over(self):
        """Retryable ``serve_step`` faults are absorbed below the
        health threshold; max_step_failures CONSECUTIVE failures mean
        no recent step progress — the replica dies and the router
        replays on the survivor."""
        router = Router([_engine(), _engine()], max_step_failures=3)
        uid = router.put(_prompts(3, 1)[0], max_new_tokens=6)
        fault_injection.arm("serve_step", fails=2)   # absorbed: 2 < 3
        _run(router)
        assert router.replicas[0].live
        assert router.replicas[0].step_failures == 2
        assert len(router.get(uid)) == 6
        assert router.snapshot()["failovers"] == 0

        uid2 = router.put(_prompts(4, 1)[0], max_new_tokens=6)
        fault_injection.arm("serve_step", fails=3)   # breaks heartbeat
        _run(router)
        snap = router.snapshot()
        assert snap["failovers"] == 1 and snap["replayed"] == 1
        # exactly one replica broke its heartbeat; the other served the
        # replay (which one depends on the round-robin cursor)
        assert sum(r.dead for r in router.replicas) == 1
        assert sum(r.live for r in router.replicas) == 1
        assert len(router.get(uid2)) == 6

    def test_dispatch_fault_requeues_and_retries(self):
        """Retryable ``serve_dispatch``: an injected dispatch failure
        leaves no partial state — the request re-queues at the front
        and lands cleanly next round."""
        router = Router([_fleet()[0]])
        fault_injection.arm("serve_dispatch", fails=1)
        uid = router.put(_prompts(5, 1)[0], max_new_tokens=4)
        router.step()                                # dispatch fails
        assert router._reqs[uid].state == "queued"
        assert router.snapshot()["dispatch_retries"] == 1
        _run(router)
        assert len(router.get(uid)) == 4
        assert router.snapshot()["failovers"] == 0

    def test_all_replicas_dead_fails_loudly(self):
        router = Router([_engine()])
        router.put(_prompts(6, 1)[0], max_new_tokens=4)
        fault_injection.arm("replica_death", fails=1)
        with pytest.raises(RuntimeError, match="no live replicas"):
            _run(router)


# ---------------------------------------------------------------------------
# overload acceptance: admission bound, watermark shedding, advisory point
# ---------------------------------------------------------------------------

class TestRouterOverload:
    def test_admission_and_shedding_protect_the_admitted_class(self):
        """The ISSUE-17 overload acceptance test: traffic past capacity
        -> the queue bound rejects at put() and the watermark sheds the
        lowest class with typed Overloaded rejections, the admitted
        class completes with p99 TPOT within noise of the uncontended
        baseline, and the pool accounting closes."""
        eng = _fleet()[0]
        router = Router([eng], router_queue_depth=8, breach_rounds=1,
                        shed_high_pct=75, shed_low_pct=50)
        # warm + uncontended baseline (class 0): compiles amortized
        base_uids = [router.put(p, max_new_tokens=6)
                     for p in _prompts(7, 4)]
        _run(router)
        for uid in base_uids:
            router.get(uid)
        baseline = router.snapshot()["classes"][0]["tpot_ms_p99"]
        assert baseline is not None

        # overload: class 1 (admitted) + class 2 (sheddable) past the
        # high watermark, then one past the hard bound
        keep = [router.put(p, max_new_tokens=6, klass=1)
                for p in _prompts(8, 4)]
        low = [router.put(p, max_new_tokens=6, klass=2)
               for p in _prompts(9, 4)]
        with pytest.raises(Overloaded) as exc:
            router.put(_prompts(10, 1)[0], max_new_tokens=6, klass=2)
        assert exc.value.klass == 2 and exc.value.queue_depth == 8
        _run(router)
        snap = router.snapshot()
        # queue was 8 >= 75% watermark: shed down to 4 — all of class 2
        # (4 watermark sheds + the 1 admission rejection above = 5)
        assert snap["classes"][2]["shed"] == 5
        assert snap["classes"][2]["completed"] == 0
        for uid in low:
            with pytest.raises(Overloaded) as err:
                router.get(uid)
            assert err.value.klass == 2          # typed, never a success
        # the admitted class rode through untouched
        assert snap["classes"][1]["completed"] == 4
        assert snap["classes"][1]["shed"] == 0
        for uid in keep:
            assert len(router.get(uid)) == 6
        admitted = snap["classes"][1]["tpot_ms_p99"]
        assert admitted is not None
        # within noise of uncontended (generous CI bound: the shed
        # class never dispatched, so the admitted class saw an idle
        # engine; SERVE_local rows carry the measured comparison)
        assert admitted <= max(10 * baseline, baseline + 500), \
            f"admitted-class p99 TPOT {admitted} vs baseline {baseline}"
        assert snap["replicas"]["r0"] == "live"
        # no leaked blocks: shed requests never touched the engine
        _pool_closed(eng)

    @pytest.mark.chaos
    def test_router_overload_point_is_advisory(self):
        """The blast-radius contract for the serving plane, enforced
        behaviorally (the lint's exact-list advisory drive covers the
        checkpoint points): fault_injection.arm("router_overload") with
        an unlimited budget forces overload rounds on EVERY step —
        nothing may raise, no replica may die, and admitted work below
        the low watermark completes untouched."""
        router = Router([_fleet()[0]])
        fault_injection.arm("router_overload", fails=10_000)
        uids = [router.put(p, max_new_tokens=4) for p in _prompts(11, 3)]
        _run(router)
        assert fault_injection.injector.hits("router_overload") > 0
        snap = router.snapshot()
        assert snap["completed"] == 3 and snap["shed"] == 0
        assert all(s == "live" for s in snap["replicas"].values())
        for uid in uids:
            assert len(router.get(uid)) == 4

    def test_shed_policy_newest_first_ignores_class(self):
        router = Router([_fleet()[0]], router_queue_depth=4,
                        breach_rounds=1, shed_high_pct=75,
                        shed_low_pct=25, shed_policy="newest-first")
        uids = [router.put(p, max_new_tokens=4, klass=k)
                for k, p in enumerate(_prompts(12, 4))]
        router.step()
        # depth 4 >= 3 (75%): shed to 1 — the three NEWEST, class-blind
        states = [router._reqs[u].state for u in uids]
        assert states[1] == states[2] == states[3] == "shed"
        assert states[0] in ("queued", "inflight", "done")
        _run(router)
        assert len(router.get(uids[0])) == 4


# ---------------------------------------------------------------------------
# deadline enforcement (fake clock: no wall-time flakiness)
# ---------------------------------------------------------------------------

class TestDeadlines:
    def _router(self, eng=None, **kw):
        if eng is None:
            eng = _engine(prefix_cache=False)
        router = Router([eng], **kw)
        self.clock = {"t": 0.0}
        router._now = lambda: self.clock["t"]
        return router, eng

    def test_queued_ttft_deadline_expires_before_dispatch(self):
        # shared fleet is fine: the request must never reach the engine
        router, eng = self._router(eng=_fleet()[0])
        uid = router.put(_prompts(13, 1)[0], max_new_tokens=4,
                         ttft_deadline_ms=100)
        self.clock["t"] = 0.2                    # 200ms > 100ms
        router.step()
        assert router.is_done(uid)
        with pytest.raises(DeadlineExceeded) as exc:
            router.get(uid)
        assert exc.value.which == "ttft"
        # never dispatched: the engine never saw the request
        assert not eng.state_mgr._seqs and not eng._pending
        assert router.snapshot()["expired"] == 1

    def test_inflight_deadline_flushes_through_cancel(self):
        """Mid-decode expiry: the request is withdrawn through
        engine.cancel() -> state_mgr.flush() (unref, no insert) — the
        pool accounting closes and the request is never returned as a
        success."""
        router, eng = self._router()
        uid = router.put(_prompts(14, 1)[0], max_new_tokens=32,
                         deadline_ms=5000)
        for _ in range(3):
            router.step()                        # genuinely decoding
        req = router._reqs[uid]
        assert req.state == "inflight" and req.n_tokens > 0
        self.clock["t"] = 10.0                   # 10s > 5s deadline
        router.step()
        assert router.is_done(uid)
        with pytest.raises(DeadlineExceeded) as exc:
            router.get(uid)
        assert exc.value.which == "total"
        snap = router.snapshot()
        assert snap["expired"] == 1 and snap["completed"] == 0
        # allocator pool accounting closed, no leaked blocks
        alloc = eng.state_mgr.allocator
        assert alloc.free_blocks == alloc.total_blocks
        assert not eng.state_mgr._seqs
        assert uid not in eng._results
        # the engine's TTFT/TPOT windows exclude the expired request
        assert eng.telemetry.completed == 0
        assert eng.telemetry.rejected == 1
        assert router.replicas[0].live           # replica unharmed
        assert not router.has_work


# ---------------------------------------------------------------------------
# engine cancel(): the flush()/unref path the router's expiry rides
# ---------------------------------------------------------------------------

class TestEngineCancel:
    def test_cancel_every_lifecycle_stage(self):
        eng = _engine(prefix_cache=True)
        alloc = eng.state_mgr.allocator

        # queued (never admitted): dropped from the pending queue
        u1 = eng.put(np.arange(1, 9, dtype=np.int32), max_new_tokens=4)
        assert eng.cancel(u1) is True
        assert not eng._pending
        with pytest.raises(KeyError):
            eng.is_done(u1)

        # mid-chunked-prefill (long prompt > one SplitFuse chunk):
        # removed from the prefill queue, blocks unreffed, NO tree
        # insert (contents past the frontier are unverified)
        long_prompt = np.arange(1, 41, dtype=np.int32) % 255 + 1
        u2 = eng.put(long_prompt, max_new_tokens=8)
        eng.step()
        assert u2 in eng._prefill_q
        assert eng.cancel(u2) is True
        assert u2 not in eng._prefill_q
        _pool_closed(eng)

        # decoding: same unref path
        u3 = eng.put(np.arange(50, 60, dtype=np.int32), max_new_tokens=16)
        for _ in range(2):
            eng.step()
        assert len(eng.get(u3, flush=False)) > 0
        assert eng.cancel(u3) is True
        _pool_closed(eng)
        assert eng.telemetry.rejected >= 1
        assert eng.telemetry.completed == 0

        # finished-but-unfetched: result forgotten
        u4 = eng.put(np.arange(70, 80, dtype=np.int32), max_new_tokens=2)
        while eng.has_work:
            eng.step()
        assert eng.cancel(u4) is True
        with pytest.raises(KeyError):
            eng.get(u4)

        # unknown uid: False, no side effects
        assert eng.cancel(12345) is False

        # the engine still serves cleanly after all that
        out = eng.generate_all([np.arange(5, 15, dtype=np.int32)],
                               max_new_tokens=4)
        assert len(out[0]) == 4
        _pool_closed(eng)


# ---------------------------------------------------------------------------
# telemetry: Serve/Router/* tags ride the linted schema
# ---------------------------------------------------------------------------

class _Mon:
    enabled = True

    def __init__(self):
        self.events = []

    def write_events(self, events):
        self.events.extend(events)


class TestRouterTelemetry:
    def test_emitted_tags_are_documented_and_complete(self):
        mon = _Mon()
        router = Router([_fleet()[0]], monitor=mon, emit_interval=1)
        uids = [router.put(p, max_new_tokens=4) for p in _prompts(15, 2)]
        _run(router)
        for uid in uids:
            router.get(uid)
        tags = {t for t, _v, _s in mon.events}
        undocumented = tags - set(TAG_SCHEMA)
        assert not undocumented, undocumented
        assert {"Serve/Router/shed", "Serve/Router/expired",
                "Serve/Router/replayed", "Serve/Router/failovers",
                "Serve/Router/queue_depth",
                "Serve/Router/draining"} <= tags
        # events are stepped by the completed-request count
        assert all(isinstance(s, int) for _t, _v, s in mon.events)

    def test_router_off_engine_snapshot_is_byte_identical(self):
        """The router adds a layer — a plain engine run must produce
        exactly the pre-router snapshot keys (no 'rejected' key, no
        router counters bleeding in)."""
        eng = _engine(prefix_cache=False)
        eng.generate_all(_prompts(16, 2), max_new_tokens=4)
        snap = eng.telemetry_snapshot()
        assert set(snap) == {"ttft_ms_p50", "ttft_ms_p99",
                             "tpot_ms_p50", "tpot_ms_p99",
                             "completed", "active"}


# replica-handle unit coverage that needs no engine compile
class TestReplicaHandle:
    def test_named_replica_wrapping_and_duplicate_names_raise(self):
        e = _fleet()[0]
        rep = Replica("decode-a", e)
        router = Router([rep])
        assert router.replicas[0].name == "decode-a"
        with pytest.raises(ValueError, match="duplicate"):
            Router([Replica("x", e), Replica("x", e)])

    def test_oversized_request_refused_at_the_router(self):
        router = Router([_fleet()[0]])
        with pytest.raises(ValueError, match="never fit"):
            router.put(np.arange(1, 100, dtype=np.int32),
                       max_new_tokens=120)
