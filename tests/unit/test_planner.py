"""Auto-parallelism planner: golden plans, calibration discipline, the
``parallelism: "auto"`` engine path, and cold-cache byte-identity.

The golden cases pin the planner's load-bearing answers: the 13B
preset on a small pod MUST come back as zero-bubble + host offload
(ROADMAP item 4's measured point — nothing else fits HBM), a tiny model
on one chip MUST come back as "do nothing", and an infeasible
model/pod pair must yield an empty ranking, never a plan that would
OOM at step one. Byte-identity pins the other contract: with a cold
winner cache, every "auto" knob lowers the exact program the previous
hand-set defaults did.
"""

import importlib.util
import os

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.autotuning.kernel_cache import KernelCache, seed_entries
from deepspeed_tpu.autotuning.planner import (ModelDesc, PodDesc,
                                              calibrate_links, plan)
from deepspeed_tpu.models import GPT2, GPT2Config
from deepspeed_tpu.models.gpt2 import GPT2_13B
from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.groups import TopologyConfig

CFG = GPT2Config(n_layer=4, n_head=2, d_model=64, max_seq_len=32,
                 vocab_size=256, remat=False, dtype="float32")

# the acceptance pod: 8 chips x 16 GB — small enough that a 13B-class
# model cannot keep device-resident Adam moments anywhere on the mesh
SMALL_POD = dict(n_chips=8, hbm_bytes=16 << 30, n_slices=1)


def _empty_cache_env(monkeypatch, tmp_path):
    monkeypatch.setenv("DSTPU_AUTOTUNE_CACHE",
                       str(tmp_path / "cold_cache.json"))


# ----------------------------------------------------------- golden plans

def test_13b_small_pod_plans_zb_plus_offload():
    """The headline golden case: GPT2-13B on an 8x16GB pod with pp >= 2
    ranks zero-bubble + host offload first — every non-offload variant
    is HBM-pruned (the +12 bytes/param moments never fit), and among
    the offload survivors zb's tick sum is minimal."""
    m = ModelDesc.from_model_config(GPT2_13B)
    report = plan(m, PodDesc(**SMALL_POD), pp_min=2)
    top = report.top()
    assert top is not None
    assert top.schedule == "zb"
    assert top.offload is True
    assert top.mesh["pipe"] >= 2
    # the pruning actually happened (the case is non-vacuous) and every
    # surviving rank fits
    assert report.pruned_hbm > 0
    assert all(p.hbm_fits for p in report.plans)
    assert all(p.offload for p in report.plans), \
        "a non-offload 13B plan survived HBM pruning on a 16GB chip"


def test_tiny_model_single_chip_plans_identity():
    m = ModelDesc(params=1 << 20, n_layer=2, d_model=64, n_head=2,
                  max_seq_len=128)
    report = plan(m, PodDesc(n_chips=1, hbm_bytes=16 << 30))
    top = report.top()
    assert top.mesh == {"pipe": 1, "data_outer": 1, "data": 1,
                        "expert": 1, "seq": 1, "tensor": 1}
    assert top.schedule == "none"
    assert top.micro_batches == 1
    # both offload variants fit; the staging cost must rank device-
    # resident first
    assert top.offload is False


def test_infeasible_pod_is_never_ranked():
    """13B on 2x1GB chips with no host memory tier: nothing fits, and
    the report says so (empty ranking + a non-zero pruned counter)
    instead of recommending an OOM."""
    m = ModelDesc.from_model_config(GPT2_13B)
    report = plan(m, PodDesc(n_chips=2, hbm_bytes=1 << 30,
                             host_offload=False))
    assert report.plans == []
    assert report.pruned_hbm > 0


def test_mesh_enumeration_respects_model_dims():
    """Axis admissibility: tp must divide heads, sp the half-sequence,
    pp the chip count and stay <= layers, and every mesh multiplies out
    to the chip count."""
    m = ModelDesc(params=1 << 22, n_layer=2, d_model=64, n_head=2,
                  max_seq_len=128)
    report = plan(m, PodDesc(**SMALL_POD), max_plans=64)
    assert report.plans
    for p in report.plans:
        sizes = p.mesh
        total = 1
        for v in sizes.values():
            total *= v
        assert total == SMALL_POD["n_chips"]
        assert m.n_head % sizes["tensor"] == 0
        assert sizes["pipe"] <= m.n_layer
        if sizes["seq"] > 1:
            assert m.max_seq_len % (2 * sizes["seq"]) == 0
        # no experts in this model: the expert axis may never be carved
        assert sizes["expert"] == 1


def test_plan_config_and_topology_roundtrip():
    m = ModelDesc.from_model_config(GPT2_13B)
    top = plan(m, PodDesc(**SMALL_POD), pp_min=2).top()
    cfg = top.config({"train_batch_size": 64})
    assert cfg["tensor_parallel"]["size"] == top.mesh["tensor"]
    assert cfg["pipeline"]["stages"] == top.mesh["pipe"]
    assert cfg["pipeline"]["schedule"] == "zb"
    assert cfg["pipeline"]["offload_activations"] is True
    assert cfg["train_batch_size"] == 64  # base keys survive the merge
    # the topology kwargs build a real mesh of the planned shape
    groups.reset()
    topo = groups.initialize(TopologyConfig(**top.topology_kwargs()),
                             force=True)
    shape = dict(topo.mesh.shape)
    assert shape["tensor"] == top.mesh["tensor"]
    assert shape["pipe"] == top.mesh["pipe"]
    assert shape["data"] * shape["data_outer"] == \
        top.mesh["data"] * top.mesh["data_outer"]


# ------------------------------------------------- alpha-beta calibration

def _link_row(kind, alpha_us, beta_gbps, device_kind="cpu"):
    return {"device_kind": device_kind, "op": "comm_link",
            "bucket": f"pp1,do1,dp8,ep1,sp1,tp1,k{kind}",
            "dtype": "float32",
            "params": {"kind": kind, "alpha_us": alpha_us,
                       "beta_gbps": beta_gbps, "busbw_gbps": beta_gbps}}


def test_calibrate_links_reads_seeded_rows(tmp_path):
    path = str(tmp_path / "cache.json")
    n = seed_entries([_link_row("ici", 2.0, 40.0),
                      _link_row("dcn", 50.0, 3.0)], path=path)
    assert n == 2
    pod = PodDesc(**SMALL_POD, device_kind="cpu")
    links = calibrate_links(pod, cache=KernelCache.load(path))
    assert links["ici"] == pytest.approx((2.0e-6, 40.0e9))
    assert links["dcn"] == pytest.approx((50.0e-6, 3.0e9))


def test_calibrate_links_refuses_foreign_device_kind(tmp_path):
    """The cache's device-kind refusal rule applies to calibration too:
    CPU-measured link speeds must never steer a TPU plan."""
    path = str(tmp_path / "cache.json")
    seed_entries([_link_row("ici", 2.0, 40.0, device_kind="cpu")],
                 path=path)
    pod = PodDesc(**SMALL_POD, device_kind="TPU v5e")
    links = calibrate_links(pod, cache=KernelCache.load(path))
    assert links["ici"] == (pod.ici_alpha_us * 1e-6, pod.ici_gbps * 1e9)


def test_comm_bench_cache_rows_shape():
    """comm_bench.cache_rows distills a sweep into seedable comm_link
    entries: alpha from the small payload, beta from the slope."""
    spec = importlib.util.spec_from_file_location(
        "comm_bench", os.path.join(os.path.dirname(__file__), os.pardir,
                                   os.pardir, "benchmarks",
                                   "comm_bench.py"))
    cb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cb)
    groups.reset()
    topo = groups.initialize(force=True)
    results = [
        {"op": "ppermute", "mb": 1, "ms": 1.0, "gbps": 1.0,
         "busbw_gbps": 1.0},
        {"op": "ppermute", "mb": 9, "ms": 2.0, "gbps": 4.5,
         "busbw_gbps": 4.5},
        {"op": "all_to_all", "mb": 1, "ms": 1.0, "gbps": 1.0,
         "busbw_gbps": 0.875},
    ]
    rows = cb.cache_rows(results, mesh=topo.mesh)
    assert [r["op"] for r in rows] == ["comm_link"]  # no dcn axis here
    (row,) = rows
    assert row["bucket"].endswith(",kici")
    W = topo.mesh.shape["data"]
    # t = alpha + bytes/beta through (1MB/W, 1ms) and (9MB/W, 2ms):
    # beta = 8MB/W per ms, alpha = 1ms - (1MB/W)/beta = 0.875 ms
    assert row["params"]["alpha_us"] == pytest.approx(875.0)
    assert row["params"]["beta_gbps"] == pytest.approx(8e6 / W / 1e-3
                                                       / 1e9)
    # the rows round-trip through the seeder into a loadable cache
    assert seed_entries(rows, path=os.path.join(
        os.environ.get("TMPDIR", "/tmp"), "_planner_rows.json")) == 1


# ------------------------------------------- parallelism: "auto" engine

def _auto_engine(monkeypatch, tmp_path, **extra):
    _empty_cache_env(monkeypatch, tmp_path)
    groups.reset()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2(CFG), config={
            "train_batch_size": 8,
            "steps_per_print": 0,
            "parallelism": "auto",
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1},
            **extra,
        })
    return engine


def test_parallelism_auto_builds_planned_mesh(monkeypatch, tmp_path):
    """End-to-end on the virtual mesh: parallelism='auto' plans, adopts
    the top plan's topology and pipeline picks, and the engine trains a
    step on the planned mesh."""
    engine = _auto_engine(monkeypatch, tmp_path)
    ap = engine._auto_plan
    assert ap is not None
    assert engine.plan_report.top() is ap
    shape = dict(engine.mesh.shape)
    for axis in ("pipe", "tensor", "seq", "expert"):
        assert shape[axis] == ap.mesh[axis]
    if ap.schedule != "none":
        assert engine._pipe.schedule == ap.schedule
        assert engine._pipe.micro_batches == ap.micro_batches
    rng = np.random.RandomState(0)
    batch = {"input_ids": rng.randint(0, CFG.vocab_size, (8, 32))
             .astype(np.int32)}
    loss = float(engine.train_batch(batch))
    assert np.isfinite(loss)


def test_parallelism_auto_defers_to_explicit_topology(monkeypatch,
                                                      tmp_path):
    """An explicit topology= argument wins: the planner must never
    override a mesh the caller constructed."""
    _empty_cache_env(monkeypatch, tmp_path)
    groups.reset()
    topo = groups.initialize(
        TopologyConfig(data_parallel_size=2), devices=jax.devices()[:2],
        force=True)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2(CFG), topology=topo, config={
            "train_batch_size": 8, "steps_per_print": 0,
            "parallelism": "auto",
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1},
        })
    assert engine._auto_plan is None
    assert dict(engine.mesh.shape)["data"] == 2


# ------------------------------------------------ cold-cache byte-identity

def _lowered_text(engine, batch):
    batch = jax.tree.map(engine._add_gas_dim, batch)
    batch = engine._shard_batch(batch, with_gas_dim=True)
    with jax.set_mesh(engine.mesh):
        return engine._train_step_jit.lower(
            engine.state, batch, engine._current_lr(), None).as_text()


def _overlap_engine(dp, shard=-1, **co):
    groups.reset()
    topo = groups.initialize(
        TopologyConfig(data_parallel_size=dp, zero_shard_size=shard),
        devices=jax.devices()[:dp], force=True)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2(CFG), topology=topo, config={
            "train_batch_size": 4, "steps_per_print": 0,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 2},
            "comm_overlap": {"enabled": True, **co},
        })
    return engine


def _batch(n=4):
    rng = np.random.RandomState(0)
    return {"input_ids": rng.randint(0, CFG.vocab_size,
                                     (n, CFG.max_seq_len))
            .astype(np.int32)}


def test_cold_cache_bucket_auto_is_byte_identical(monkeypatch, tmp_path):
    """With no measured winners, comm_overlap bucket_mb/dcn_quantize
    'auto' must lower the exact program of the previous hand-set
    defaults (bucket_mb=32, dcn_quantize off) — dispatch's cold-cache
    answer IS the old default, so the HLO may not move by a byte."""
    _empty_cache_env(monkeypatch, tmp_path)
    batch = _batch()
    auto = _lowered_text(_overlap_engine(
        2, bucket_mb="auto", dcn_quantize="auto"), batch)
    hand = _lowered_text(_overlap_engine(
        2, bucket_mb=32, dcn_quantize=False), batch)
    assert auto == hand


def test_cold_cache_hierarchical_auto_is_byte_identical(monkeypatch,
                                                        tmp_path):
    """Same identity for the hierarchical grad staging knob on a real
    data_outer split (dp=4, shard=2 -> do=2): 'auto' resolves through
    the grad_staging op whose cold default is the do>1 heuristic."""
    _empty_cache_env(monkeypatch, tmp_path)
    batch = _batch()
    auto = _lowered_text(_overlap_engine(
        4, shard=2, hierarchical="auto"), batch)
    hand = _lowered_text(_overlap_engine(
        4, shard=2, hierarchical=True), batch)
    assert auto == hand
