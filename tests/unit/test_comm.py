import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P
from jax import shard_map

import deepspeed_tpu.comm as dist
from deepspeed_tpu.utils import groups


def _mesh():
    return groups.initialize(force=True).mesh


def test_all_reduce_sum():
    mesh = _mesh()
    x = jnp.arange(8.0)

    f = shard_map(lambda v: dist.all_reduce(v, "data"), mesh=mesh,
                  in_specs=P("data"), out_specs=P("data"))
    out = f(x)
    np.testing.assert_allclose(np.asarray(out), np.full(8, x.sum()))


def test_reduce_scatter_allgather_roundtrip():
    mesh = _mesh()
    x = jnp.ones((8, 16))

    def body(v):
        # v: (1, 16) local shard of rows; flatten rows, rs over 16 cols
        s = dist.reduce_scatter(v[0], "data")  # (2,) per device
        g = dist.all_gather(s, "data")         # (16,)
        return g[None, :]

    f = shard_map(body, mesh=mesh, in_specs=P("data", None),
                  out_specs=P("data", None))
    out = f(x)
    np.testing.assert_allclose(np.asarray(out), np.full((8, 16), 8.0))


def test_all_to_all():
    mesh = _mesh()
    x = jnp.arange(8 * 8, dtype=jnp.float32).reshape(8, 8)

    def body(v):
        # local (1, 8) row -> split cols across devices, concat rows:
        # device i ends with column i as a (8, 1) local block.
        return dist.all_to_all(v, "data", split_dimension=1,
                               concat_dimension=0)

    # out_specs shards dim1: globally this is exactly a resharding of x
    # (row-sharded -> col-sharded) with identical contents.
    f = shard_map(body, mesh=mesh, in_specs=P("data", None),
                  out_specs=P(None, "data"))
    out = np.asarray(f(x))
    np.testing.assert_allclose(out, np.asarray(x))


def test_ppermute_ring():
    mesh = _mesh()
    x = jnp.arange(8.0)
    f = shard_map(lambda v: dist.send_forward(v, "data"), mesh=mesh,
                  in_specs=P("data"), out_specs=P("data"))
    out = np.asarray(f(x))
    np.testing.assert_allclose(out, np.roll(np.arange(8.0), 1))


def test_broadcast():
    mesh = _mesh()
    x = jnp.arange(8.0).reshape(8, 1)
    f = shard_map(lambda v: dist.broadcast(v, "data", src=3), mesh=mesh,
                  in_specs=P("data", None), out_specs=P("data", None))
    out = np.asarray(f(x))
    np.testing.assert_allclose(out, np.full((8, 1), 3.0))


def test_comms_logger_records_volume():
    mesh = _mesh()
    lg = dist.get_comms_logger()
    lg.reset()
    lg.enabled = True
    try:
        x = jnp.ones((8, 4), jnp.float32)
        f = shard_map(lambda v: dist.all_reduce(v, "data"), mesh=mesh,
                      in_specs=P("data", None), out_specs=P("data", None))
        jax.block_until_ready(f(x))
        assert lg.total_bytes() == 4 * 4  # local shard (1,4) fp32
    finally:
        lg.enabled = False
        lg.reset()


def test_init_distributed_single_host():
    dist.init_distributed()
    assert dist.is_initialized()
    assert dist.get_rank() == 0


# ------------------------------------------------- byte-payload contract

def test_ring_exchange_bytes_single_process_zero_length():
    """Zero-length payloads are legal; single-process worlds return the
    no-peer sentinel without touching any collective."""
    from deepspeed_tpu.comm import comm as comm_mod
    assert comm_mod.ring_exchange_bytes(b"") == (None, None)
    assert comm_mod.allgather_bytes(b"") is None


def test_padded_width_floors_all_empty_exchange_at_one():
    """The zero-length guard itself: an all-empty ring still sizes a
    one-byte buffer (zeros((0,)) is not a valid per-process operand)."""
    from deepspeed_tpu.comm import comm as comm_mod
    assert comm_mod._padded_width(np.zeros((4,), np.int64)) == 1
    assert comm_mod._padded_width(np.asarray([0, 7, 3])) == 7


def test_oversize_payload_raises_typed_error(monkeypatch):
    """Payloads above MAX_PAYLOAD_BYTES raise CommPayloadError BEFORE
    any collective — checked first, so the contract holds (and is
    testable) even in a single-process world."""
    from deepspeed_tpu.comm import comm as comm_mod
    monkeypatch.setattr(comm_mod, "MAX_PAYLOAD_BYTES", 8)
    import pytest as _pytest
    with _pytest.raises(comm_mod.CommPayloadError):
        comm_mod.ring_exchange_bytes(b"123456789")
    with _pytest.raises(comm_mod.CommPayloadError):
        comm_mod.allgather_bytes(b"123456789")
    # at the limit is fine
    assert comm_mod.ring_exchange_bytes(b"12345678") == (None, None)
