"""Measured kernel dispatch: registry, winner cache, dispatch modes.

Fast tier covers the dispatch CONTRACT (registry completeness, cache
round-trip determinism, cache_only never searching, r05-default
fallback on miss, interpret-mode/CPU cache refusal on device-kind
mismatch, and the warm-cache HLO-identity guarantee — a tuned "auto"
config lowers to the byte-identical program a hand-set config does).
Real measured searches (device timing loops) are `slow`.
"""

import json
import os

import numpy as np
import pytest

import jax

from deepspeed_tpu.autotuning import (KernelCache, kernel_dispatch,
                                      kernel_registry)
from deepspeed_tpu.autotuning.kernel_cache import entry_key


@pytest.fixture(autouse=True)
def _pristine_dispatch(tmp_path, monkeypatch):
    """Every test runs with a private cache path and a reset dispatch
    state (the state is process-global by design)."""
    monkeypatch.setenv("DSTPU_AUTOTUNE_CACHE",
                       str(tmp_path / "kernel_autotune.json"))
    monkeypatch.delenv("DSTPU_AUTOTUNE", raising=False)
    kernel_dispatch.reset()
    yield
    kernel_dispatch.reset()


# sample buckets per op (tiny shapes — several tests build real steps)
_BUCKETS = {
    "flash_attention": "T128,d32,c1,q1",
    "mlp_matmul": "T128,D128,F512",
    "layernorm": "R256,D128",
    "fused_ce": "N128,D128,V384",
    "ring_block": "T64,d32",
    "moe_grouped_mm": "S128,E4,M128,F256",
    "mlp_int8": "T128,D128,F512",
    "moe_grouped_int8": "S128,E4,M128,F256",
    "paged_decode": "B4,MB4,BS16,kh2,g2,d32",
    "paged_chunk": "C16,MB4,BS16,kh2,g2,d32",
    "pipe_microbatch": "S2,B8,T128,D128",
    "prefix_cache": "B4,NB16,BS16",
    "spec_decode": "B4,NB16,BS16",
    "kv_handoff": "B4",
    # collective-bearing ops (autotuning/collective_ops.py): the mesh
    # topology signature is folded into the bucket string; the step
    # builders clamp requested axes to the devices actually present, so
    # these trace on the 1-CPU tier as loopback collectives
    "comm_bucket": "pp1,do1,dp4,ep1,sp1,tp1,L32",
    "grad_staging": "pp1,do2,dp2,ep1,sp1,tp1,L32",
    "a2a_staging": "pp1,do2,dp1,ep2,sp1,tp1,S256,M64",
    "dcn_quantize": "pp1,do2,dp2,ep1,sp1,tp1,L32",
    "ring_rotate": "pp1,do1,dp1,ep1,sp2,tp1,R2,T128,d64",
    "scan_unroll": "pp1,do1,dp4,ep1,sp1,tp1,N4,D128",
    "hot_replicas": "pp1,do1,dp4,ep1,sp1,tp1,G16",
}


class TestRegistry:
    def test_every_tunable_kernel_has_candidates(self):
        """Registry completeness: the tunable Pallas kernel ops
        each expose defaults + a non-empty candidate set whose params
        all share the defaults' key set (a winner can always be merged
        over the defaults)."""
        assert set(kernel_registry.REGISTRY) == set(_BUCKETS)
        for op, spec in kernel_registry.REGISTRY.items():
            b = kernel_registry.parse_bucket(_BUCKETS[op])
            defaults = spec["defaults"](b)
            cands = spec["candidates"](b)
            assert defaults and cands, op
            assert defaults in cands, f"{op}: defaults not a candidate"
            for c in cands:
                assert set(c) == set(defaults), (op, c)

    def test_candidates_deduped(self):
        for op, spec in kernel_registry.REGISTRY.items():
            cands = spec["candidates"](
                kernel_registry.parse_bucket(_BUCKETS[op]))
            seen = [tuple(sorted((k, repr(v)) for k, v in c.items()))
                    for c in cands]
            assert len(seen) == len(set(seen)), op

    def test_parse_bucket_roundtrip(self):
        assert kernel_registry.parse_bucket("T1024,d64,c1,q0") == {
            "T": 1024, "d": 64, "c": 1, "q": 0}

    def test_make_step_runs(self):
        """Each op's search step builds and runs at a tiny bucket (the
        exact harness a real search times)."""
        for op, spec in kernel_registry.REGISTRY.items():
            b = kernel_registry.parse_bucket(_BUCKETS[op])
            step, args = spec["make_step"](b, "float32",
                                           spec["defaults"](b))
            out = jax.block_until_ready(step(args))
            assert jax.tree.structure(out) == jax.tree.structure(args)


class TestCache:
    def test_roundtrip_deterministic(self, tmp_path):
        c = KernelCache()
        c.put("cpu", "layernorm", "R256,D128", "bfloat16",
              {"variant": "fused", "block_rows": 128},
              measured_ms=0.5, default_ms=0.7, candidates=5)
        c.put("cpu", "fused_ce", "N128,D128,V384", "bfloat16",
              {"block_m": 256, "block_n": 512})
        p = tmp_path / "c.json"
        c.save(str(p))
        c2 = KernelCache.load(str(p))
        assert c2.entries == c.entries
        assert c2.to_json() == c.to_json()
        c2.save(str(p))
        assert KernelCache.load(str(p)).to_json() == c.to_json()

    def test_survives_process_restart_shape(self, tmp_path):
        """The on-disk form alone (no in-process state) reproduces the
        lookup — what a process restart relies on."""
        p = str(tmp_path / "c.json")
        c = KernelCache()
        c.put("cpu", "layernorm", "R256,D128", "float32",
              {"variant": "bwd", "block_rows": 512})
        c.save(p)
        got = KernelCache.load(p).lookup("cpu", "layernorm", "R256,D128",
                                         "float32")
        assert got == {"variant": "bwd", "block_rows": 512}

    def test_missing_and_corrupt_files_are_empty(self, tmp_path):
        assert len(KernelCache.load(str(tmp_path / "nope.json"))) == 0
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        assert len(KernelCache.load(str(bad))) == 0
        wrong = tmp_path / "v0.json"
        wrong.write_text(json.dumps({"version": 99, "entries": {}}))
        assert len(KernelCache.load(str(wrong))) == 0

    def test_device_kind_mismatch_refused(self, tmp_path):
        """A cache produced in interpret mode on CPU must be REFUSED on
        device (and vice versa) — both through the key (normal path)
        and through the recorded device_kind field (hand-edited key)."""
        c = KernelCache()
        c.put("cpu", "layernorm", "R256,D128", "float32",
              {"variant": "fused", "block_rows": 128})
        # normal path: the key simply never matches another chip
        assert c.lookup("TPU v5e", "layernorm", "R256,D128",
                        "float32") is None
        # tampered path: key claims v5e, recorded field says cpu
        k = entry_key("TPU v5e", "layernorm", "R256,D128", "float32")
        c.entries[k] = dict(
            c.entries[entry_key("cpu", "layernorm", "R256,D128",
                                "float32")])
        assert c.entries[k]["device_kind"] == "cpu"
        assert c.lookup("TPU v5e", "layernorm", "R256,D128",
                        "float32") is None
        # the honest key still resolves
        assert c.lookup("cpu", "layernorm", "R256,D128",
                        "float32") is not None


class TestDispatch:
    def test_fallback_to_defaults_on_miss(self):
        d = {"block_m": 512, "block_n": 512}
        got = kernel_dispatch.resolve("fused_ce", "N128,D128,V384",
                                      "float32", d)
        assert got == d and got is not d

    def test_off_mode_ignores_cache(self, tmp_path):
        path = os.environ["DSTPU_AUTOTUNE_CACHE"]
        c = KernelCache()
        c.put(kernel_dispatch.device_kind(), "fused_ce",
              "N128,D128,V384", "float32",
              {"block_m": 256, "block_n": 256})
        c.save(path)
        kernel_dispatch.configure(mode="off")
        got = kernel_dispatch.resolve("fused_ce", "N128,D128,V384",
                                      "float32",
                                      {"block_m": 512, "block_n": 512})
        assert got == {"block_m": 512, "block_n": 512}

    def test_cache_only_never_triggers_search(self, monkeypatch):
        """cache_only on a cold key: defaults come back and the search
        driver is NEVER invoked."""
        from deepspeed_tpu.autotuning import kernel_autotuner

        def boom(*a, **k):
            raise AssertionError("search invoked under cache_only")

        monkeypatch.setattr(kernel_autotuner, "search", boom)
        kernel_dispatch.configure(mode="cache_only")
        d = dict(kernel_registry.FLASH_DEFAULTS)
        got = kernel_dispatch.resolve("flash_attention",
                                      _BUCKETS["flash_attention"],
                                      "bfloat16", d)
        assert got == d

    def test_cached_winner_wins_and_memoizes(self, monkeypatch):
        path = os.environ["DSTPU_AUTOTUNE_CACHE"]
        dk = kernel_dispatch.device_kind()
        c = KernelCache()
        c.put(dk, "layernorm", "R256,D128", "float32",
              {"variant": "fused", "block_rows": 128})
        c.save(path)
        kernel_dispatch.configure(mode="cache_only")
        d = {"variant": "jnp", "block_rows": 256}
        got = kernel_dispatch.resolve("layernorm", "R256,D128",
                                      "float32", d)
        assert got == {"variant": "fused", "block_rows": 128}
        # second resolve must not re-read the file (memoized)
        monkeypatch.setattr(KernelCache, "load",
                            classmethod(lambda cls, p: (_ for _ in ())
                                        .throw(AssertionError("re-read"))))
        again = kernel_dispatch.resolve("layernorm", "R256,D128",
                                        "float32", d)
        assert again == got

    def test_winner_filtered_to_callers_keys(self):
        """A caller tuning a subset of an op's params (the layernorm
        wrapper only needs block_rows) gets exactly its own keys."""
        path = os.environ["DSTPU_AUTOTUNE_CACHE"]
        c = KernelCache()
        c.put(kernel_dispatch.device_kind(), "layernorm", "R256,D128",
              "float32", {"variant": "fused", "block_rows": 128})
        c.save(path)
        got = kernel_dispatch.resolve("layernorm", "R256,D128",
                                      "float32", {"block_rows": 256})
        assert got == {"block_rows": 128}

    def test_on_first_use_searches_once_and_persists(self, monkeypatch):
        """on_first_use: a miss invokes the search driver exactly once
        per key, and the winner lands in the cache FILE (restart
        durability)."""
        from deepspeed_tpu.autotuning import kernel_autotuner
        calls = []

        def fake_search(op, bucket, dtype, defaults=None, **kw):
            calls.append((op, bucket))
            winner = {"block_m": 256, "block_n": 256}
            return winner, {"op": op, "bucket": bucket, "dtype": dtype,
                            "candidates": [{"params": winner, "ms": 1.0,
                                            "error": None}],
                            "winner": winner, "winner_ms": 1.0,
                            "default_ms": 2.0}

        monkeypatch.setattr(kernel_autotuner, "search", fake_search)
        kernel_dispatch.configure(mode="on_first_use")
        d = {"block_m": 512, "block_n": 512}
        got = kernel_dispatch.resolve("fused_ce", "N128,D128,V384",
                                      "float32", d)
        assert got == {"block_m": 256, "block_n": 256}
        kernel_dispatch.resolve("fused_ce", "N128,D128,V384",
                                "float32", d)
        assert len(calls) == 1
        on_disk = KernelCache.load(os.environ["DSTPU_AUTOTUNE_CACHE"])
        e = on_disk.lookup(kernel_dispatch.device_kind(), "fused_ce",
                           "N128,D128,V384", "float32")
        assert e == {"block_m": 256, "block_n": 256}

    def test_search_mode_remeasures_cached_keys(self, monkeypatch):
        """mode=search ignores an existing entry and re-measures (once
        per process), overwriting the cache."""
        from deepspeed_tpu.autotuning import kernel_autotuner
        path = os.environ["DSTPU_AUTOTUNE_CACHE"]
        dk = kernel_dispatch.device_kind()
        c = KernelCache()
        c.put(dk, "fused_ce", "N128,D128,V384", "float32",
              {"block_m": 512, "block_n": 512})
        c.save(path)
        calls = []

        def fake_search(op, bucket, dtype, defaults=None, **kw):
            calls.append(op)
            w = {"block_m": 1024, "block_n": 256}
            return w, {"op": op, "bucket": bucket, "dtype": dtype,
                       "candidates": [], "winner": w, "winner_ms": 0.5,
                       "default_ms": 1.0}

        monkeypatch.setattr(kernel_autotuner, "search", fake_search)
        kernel_dispatch.configure(mode="search")
        got = kernel_dispatch.resolve("fused_ce", "N128,D128,V384",
                                      "float32",
                                      {"block_m": 512, "block_n": 512})
        assert calls == ["fused_ce"]
        assert got == {"block_m": 1024, "block_n": 256}
        assert KernelCache.load(path).lookup(
            dk, "fused_ce", "N128,D128,V384", "float32") == got

    def test_failed_search_degrades_to_defaults(self, monkeypatch):
        from deepspeed_tpu.autotuning import kernel_autotuner

        def broken(*a, **k):
            raise RuntimeError("no device time today")

        monkeypatch.setattr(kernel_autotuner, "search", broken)
        kernel_dispatch.configure(mode="on_first_use")
        d = {"block_m": 512, "block_n": 512}
        got = kernel_dispatch.resolve("fused_ce", "N128,D128,V384",
                                      "float32", d)
        assert got == d

    def test_unknown_op_falls_back(self):
        kernel_dispatch.configure(mode="on_first_use")
        got = kernel_dispatch.resolve("not_a_kernel", "X1", "float32",
                                      {"a": 1})
        assert got == {"a": 1}


class TestEngineWiring:
    def test_engine_config_block_sets_global_state(self, tmp_path):
        import deepspeed_tpu
        from deepspeed_tpu.models.gpt2 import GPT2, GPT2_TINY
        from deepspeed_tpu.utils import groups
        from dataclasses import replace
        groups.reset()
        p = str(tmp_path / "engine_cache.json")
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=GPT2(replace(GPT2_TINY, remat=False)),
            config={
                "train_micro_batch_size_per_gpu": 1,
                "steps_per_print": 0,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
                "bf16": {"enabled": True},
                "autotune": {"mode": "cache_only", "cache_path": p,
                             "chain_lengths": [4, 12], "reps": 2},
            })
        assert kernel_dispatch.current_mode() == "cache_only"
        assert kernel_dispatch.cache_path() == p
        assert kernel_dispatch._STATE["chain_lengths"] == (4, 12)
        groups.reset()


def _warm_winner_cache(path, dk, dtype="bfloat16"):
    """Winners for the 350M bench buckets, chosen to be expressible as
    hand-set config values (non-default where the config can express
    it: full-T flash blocks, block_h=1, q-major backward, mlp 'down')."""
    c = KernelCache()
    c.put(dk, "flash_attention", "T1024,d64,c1,q1", dtype,
          {"block_q": 1024, "block_k": 1024, "block_h": 1,
           "block_q_bwd": 0, "block_k_bwd": 0, "bwd_qmajor": True})
    c.put(dk, "mlp_matmul", "T1024,D1024,F4096", dtype,
          {"mode": "down", "fuse_dw": True, "block_t": 256,
           "block_o": 256, "block_k": 512})
    # rows bucket: pow2(B * T) = pow2(1 * 1024)
    c.put(dk, "layernorm", "R1024,D1024", dtype,
          {"variant": "jnp", "block_rows": 256})
    c.put(dk, "fused_ce", "N512,D1024,V50304", dtype,
          {"block_m": 512, "block_n": 512})
    c.save(path)


class TestHLOIdentity:
    def test_350m_train_step_matches_hand_set_config(self):
        """Acceptance: with a warm cache, autotune dispatch resolves
        entirely at trace time — the lowered program for the 350M train
        step under an all-"auto" config is BYTE-IDENTICAL to the same
        step with the best-known values hand-set (and dispatch off).
        Lowering uses abstract params, so no 350M weights materialize;
        flash runs its interpreter path off-TPU in both programs."""
        from dataclasses import replace
        from deepspeed_tpu.models.gpt2 import GPT2, GPT2_350M
        path = os.environ["DSTPU_AUTOTUNE_CACHE"]
        _warm_winner_cache(path, kernel_dispatch.device_kind())

        common = dict(use_flash_attention=True, remat=True,
                      remat_policy="save_flash", loss_chunk=512,
                      fused_loss=True, fused_loss_kernel=True)
        auto = GPT2(replace(
            GPT2_350M, **common, flash_block_q="auto",
            flash_block_k="auto", flash_block_h="auto",
            flash_block_q_bwd="auto", flash_block_k_bwd="auto",
            flash_bwd_qmajor="auto", mlp_kernel="auto",
            fused_layernorm="auto"))
        hand = GPT2(replace(
            GPT2_350M, **common, flash_block_q=1024, flash_block_k=1024,
            flash_block_h=1, flash_bwd_qmajor=True, mlp_kernel="down",
            fused_layernorm=False))

        batch = {"input_ids": np.zeros((1, 1024), np.int32)}

        def lower_text(model):
            ab = jax.eval_shape(model.init, jax.random.PRNGKey(0))
            arg = jax.tree.map(
                lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype), ab)
            f = jax.jit(lambda p: jax.value_and_grad(
                lambda q: model.loss(q, batch, train=False))(p))
            return f.lower(arg).as_text()

        kernel_dispatch.configure(mode="cache_only")
        t_auto = lower_text(auto)
        # the auto trace really consulted the cache (all four ops)
        assert len(kernel_dispatch._STATE["resolved"]) >= 4
        kernel_dispatch.configure(mode="off")
        t_hand = lower_text(hand)
        assert t_auto == t_hand

    def test_cold_cache_matches_r05_defaults(self):
        """Dispatch miss == the r05 default program, proven at the HLO
        level on a tiny model (fast twin of the warm-cache test)."""
        from dataclasses import replace
        from deepspeed_tpu.models.gpt2 import GPT2, GPT2_TINY
        common = dict(use_flash_attention=True, remat=False)
        auto = GPT2(replace(GPT2_TINY, **common, flash_block_q="auto",
                            flash_block_k="auto", flash_block_h="auto",
                            flash_block_q_bwd="auto",
                            flash_block_k_bwd="auto",
                            flash_bwd_qmajor="auto", mlp_kernel="auto",
                            fused_layernorm="auto"))
        hand = GPT2(replace(GPT2_TINY, **common))
        batch = {"input_ids": np.zeros((2, 128), np.int32)}

        def lower_text(model):
            ab = jax.eval_shape(model.init, jax.random.PRNGKey(0))
            arg = jax.tree.map(
                lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype), ab)
            f = jax.jit(lambda p: jax.value_and_grad(
                lambda q: model.loss(q, batch, train=False))(p))
            return f.lower(arg).as_text()

        kernel_dispatch.configure(mode="cache_only")   # empty cache
        t_auto = lower_text(auto)
        kernel_dispatch.configure(mode="off")
        assert t_auto == lower_text(hand)


@pytest.mark.slow
class TestRealSearch:
    """Full measured searches (device timing loops) — slow tier."""

    def test_layernorm_search_persists_and_redispatches(self):
        path = os.environ["DSTPU_AUTOTUNE_CACHE"]
        kernel_dispatch.configure(mode="on_first_use",
                                  chain_lengths=(2, 4), reps=1)
        d = {"variant": "jnp", "block_rows": 256}
        got = kernel_dispatch.resolve("layernorm", "R64,D128",
                                      "float32", d)
        assert set(got) == set(d)
        on_disk = KernelCache.load(path)
        e = on_disk.lookup(kernel_dispatch.device_kind(), "layernorm",
                           "R64,D128", "float32")
        assert e is not None and set(e) == set(d)
        # a fresh process (simulated by reset) resolves from the file
        # without searching
        kernel_dispatch.reset()
        kernel_dispatch.configure(mode="cache_only")
        assert kernel_dispatch.resolve("layernorm", "R64,D128",
                                       "float32", d) == got

    def test_search_report_times_every_candidate(self):
        from deepspeed_tpu.autotuning import kernel_autotuner
        winner, report = kernel_autotuner.search(
            "fused_ce", "N128,D128,V384", "float32",
            defaults={"block_m": 512, "block_n": 512},
            chain_lengths=(2, 4), reps=1)
        assert report["default_ms"] is not None
        assert len(report["candidates"]) >= 2
        assert winner == report["winner"]
        assert all(("ms" in r) for r in report["candidates"])

    def test_winner_parity_validates(self):
        """The search's winner passed the tuned-vs-reference parity
        check by construction; re-run it standalone."""
        spec = kernel_registry.REGISTRY["layernorm"]
        b = kernel_registry.parse_bucket("R64,D128")
        for params in spec["candidates"](b):
            spec["parity"](b, "float32", params)
