"""Diffusion family: UNet2D + VAE decoder behind the DSUNet/DSVAE
serving wrappers (reference module_inject/containers/{unet,vae}.py +
model_implementations/diffusers/) over the ops/spatial.py fused-bias
surface."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.diffusion import (DSUNet, DSVAE, UNet2D,
                                            UNet2DConfig, VAEDecoder,
                                            VAEDecoderConfig)

CFG = UNet2DConfig(in_channels=4, out_channels=4, channels=(32, 64),
                   n_heads=4, cross_dim=48, groups=8)


class TestUNet2D:
    def test_shapes_and_conditioning(self):
        model = UNet2D(CFG)
        params = model.init(jax.random.key(0))
        lat = jax.random.normal(jax.random.key(1), (2, 16, 16, 4))
        t = jnp.asarray([3, 700], jnp.int32)
        ctx = jax.random.normal(jax.random.key(2), (2, 7, 48))
        out = model.apply(params, lat, t, ctx)
        assert out.shape == (2, 16, 16, 4)
        assert np.isfinite(np.asarray(out)).all()
        # conditioning must matter: different context -> different output
        ctx2 = ctx + 1.0
        out2 = model.apply(params, lat, t, ctx2)
        assert float(jnp.abs(out - out2).max()) > 1e-6
        # timestep must matter
        out3 = model.apply(params, lat, jnp.asarray([500, 5], jnp.int32),
                           ctx)
        assert float(jnp.abs(out - out3).max()) > 1e-6

    def test_unconditioned(self):
        model = UNet2D(CFG)
        params = model.init(jax.random.key(0))
        lat = jax.random.normal(jax.random.key(1), (1, 8, 8, 4))
        out = model.apply(params, lat, jnp.asarray([10], jnp.int32))
        assert out.shape == (1, 8, 8, 4)

    def test_dsunet_compiles_once_per_shape(self):
        """The reference wrapper's CUDA-graph property: repeated calls at
        one shape replay a single compiled program."""
        model = UNet2D(CFG)
        params = model.init(jax.random.key(0))
        eng = DSUNet(model, params)
        lat = jax.random.normal(jax.random.key(1), (1, 8, 8, 4))
        t = jnp.asarray([1], jnp.int32)
        ctx = jax.random.normal(jax.random.key(2), (1, 5, 48))
        a = eng(lat, t, ctx)
        b = eng(lat + 0.1, t, ctx)
        assert eng.compiles == 1
        eng(jax.random.normal(jax.random.key(3), (1, 16, 16, 4)), t, ctx)
        assert eng.compiles == 2
        assert a.shape == b.shape == (1, 8, 8, 4)

    def test_denoise_loop_smoke(self):
        """A tiny DDIM-style loop through the jitted wrapper: latents
        stay finite and move."""
        model = UNet2D(CFG)
        params = model.init(jax.random.key(0))
        eng = DSUNet(model, params)
        lat = jax.random.normal(jax.random.key(9), (1, 8, 8, 4))
        x0 = np.asarray(lat)
        for step in (900, 600, 300, 0):
            eps = eng(lat, jnp.asarray([step], jnp.int32), None)
            lat = lat - 0.1 * eps
        assert eng.compiles == 1
        assert np.isfinite(np.asarray(lat)).all()
        assert float(jnp.abs(lat - x0).max()) > 0


class TestVAEDecoder:
    def test_decode_shape_and_upsampling(self):
        cfg = VAEDecoderConfig(latent_channels=4, out_channels=3,
                               channels=(32, 16), groups=8)
        model = VAEDecoder(cfg)
        params = model.init(jax.random.key(0))
        lat = jax.random.normal(jax.random.key(1), (2, 8, 8, 4))
        img = model.apply(params, lat)
        # 2 levels of 2x upsampling
        assert img.shape == (2, 32, 32, 3)
        assert np.isfinite(np.asarray(img)).all()

    def test_dsvae_wrapper(self):
        cfg = VAEDecoderConfig(latent_channels=4, out_channels=3,
                               channels=(16, 16), groups=8)
        model = VAEDecoder(cfg)
        eng = DSVAE(model, model.init(jax.random.key(0)))
        lat = jax.random.normal(jax.random.key(1), (1, 4, 4, 4))
        a = eng(lat)
        b = eng(lat * 2)
        assert eng.compiles == 1
        assert a.shape == (1, 16, 16, 3)
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(eng(lat)), rtol=1e-6)
