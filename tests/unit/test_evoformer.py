"""Evoformer (DS4Science) biased attention parity: chunked path vs the
direct dense computation, forward and backward, with the reference's
bias1 (row mask) + bias2 (pair bias) shapes."""

import math

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.evoformer_attn import evoformer_attention

# kernel-parity tier: excluded from the fast core set
pytestmark = pytest.mark.slow


def _dense_reference(q, k, v, biases, scale):
    s = jnp.einsum("bsnhd,bsmhd->bshnm", q, k,
                   preferred_element_type=jnp.float32) * scale
    for b in biases:
        s = s + b
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bshnm,bsmhd->bsnhd", p.astype(q.dtype), v)


def _inputs(B=2, S=3, N=24, H=4, d=16, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, S, N, H, d), jnp.float32) * 0.4
    q, k, v = mk(), mk(), mk()
    bias1 = jnp.asarray(
        np.where(rng.rand(B, S, 1, 1, N) > 0.15, 0.0, -1e9), jnp.float32)
    bias2 = jnp.asarray(rng.randn(B, 1, H, N, N), jnp.float32)
    return q, k, v, bias1, bias2


class TestEvoformerAttention:
    @pytest.mark.parametrize("chunk", [0, 2, 100])
    def test_forward_matches_dense(self, chunk):
        q, k, v, b1, b2 = _inputs()
        scale = 1.0 / math.sqrt(q.shape[-1])
        got = evoformer_attention(q, k, v, (b1, b2), chunk=chunk)
        want = _dense_reference(q, k, v, (b1, b2), scale)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_no_bias(self):
        q, k, v, *_ = _inputs()
        got = evoformer_attention(q, k, v, chunk=2)
        want = _dense_reference(q, k, v, (),
                                1.0 / math.sqrt(q.shape[-1]))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_grads_match_dense(self):
        q, k, v, b1, b2 = _inputs(B=1, S=2, N=16)
        scale = 1.0 / math.sqrt(q.shape[-1])

        def loss_c(q, k, v, b2):
            return jnp.sum(evoformer_attention(
                q, k, v, (b1, b2), chunk=1) ** 2)

        def loss_d(q, k, v, b2):
            return jnp.sum(_dense_reference(
                q, k, v, (b1, b2), scale) ** 2)

        gc = jax.grad(loss_c, (0, 1, 2, 3))(q, k, v, b2)
        gd = jax.grad(loss_d, (0, 1, 2, 3))(q, k, v, b2)
        for a, b in zip(gc, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_bad_bias_rank_rejected(self):
        q, k, v, b1, _ = _inputs()
        with pytest.raises(ValueError, match="5D"):
            evoformer_attention(q, k, v, (b1[0],))

    @pytest.mark.parametrize("impl", ["kernel", "xla"])
    def test_impls_match_dense(self, impl):
        # both implementations against the direct dense computation,
        # with non-divisible N (padding) and both reference biases
        q, k, v, b1, b2 = _inputs(B=1, S=4, N=40, H=2, d=16)
        scale = 1.0 / math.sqrt(q.shape[-1])
        got = evoformer_attention(q, k, v, (b1, b2), impl=impl)
        want = _dense_reference(q, k, v, (b1, b2), scale)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_kernel_pair_bias_grad(self):
        # d(bias2) flows through the fused backward's in-kernel
        # accumulator (reference kernel_backward.h computes dB the same
        # way); q/k/v grads too
        q, k, v, b1, b2 = _inputs(B=2, S=4, N=32, H=2, d=16, seed=3)
        scale = 1.0 / math.sqrt(q.shape[-1])

        def loss_k(q, k, v, b2):
            return jnp.sum(evoformer_attention(
                q, k, v, (b1, b2), impl="kernel") ** 2)

        def loss_d(q, k, v, b2):
            return jnp.sum(_dense_reference(
                q, k, v, (b1, b2), scale) ** 2)

        gk = jax.grad(loss_k, (0, 1, 2, 3))(q, k, v, b2)
        gd = jax.grad(loss_d, (0, 1, 2, 3))(q, k, v, b2)
        for a, b in zip(gk, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_full_per_instance_bias(self):
        # a bias with every dim present takes the identity row map
        q, k, v, *_ = _inputs(B=1, S=2, N=16, H=2)
        rng = np.random.RandomState(7)
        bias = jnp.asarray(rng.randn(1, 2, 2, 16, 16), jnp.float32)
        scale = 1.0 / math.sqrt(q.shape[-1])
        got = evoformer_attention(q, k, v, (bias,))
        want = _dense_reference(q, k, v, (bias,), scale)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)


class TestSpatialOps:
    """csrc/spatial/opt_bias_add.cu family (diffusers UNet/VAE adds)."""

    def test_variants(self):
        from deepspeed_tpu.ops.spatial import (opt_bias_add,
                                               opt_bias_add_add,
                                               opt_bias_add_res)
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(2, 8, 8, 16), jnp.float32)
        o = jnp.asarray(rng.randn(2, 8, 8, 16), jnp.float32)
        b = jnp.asarray(rng.randn(16), jnp.float32)
        rb = jnp.asarray(rng.randn(16), jnp.float32)
        np.testing.assert_allclose(np.asarray(opt_bias_add(x, b)),
                                   np.asarray(x + b))
        np.testing.assert_allclose(np.asarray(opt_bias_add_add(x, b, o)),
                                   np.asarray(x + b + o))
        np.testing.assert_allclose(
            np.asarray(opt_bias_add_res(x, b, o, rb)),
            np.asarray(x + b + o + rb))

    def test_channel_mismatch_rejected(self):
        from deepspeed_tpu.ops.spatial import opt_bias_add
        with pytest.raises(ValueError, match="channel"):
            opt_bias_add(jnp.zeros((2, 4, 4, 8)), jnp.zeros((16,)))
