import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models import GPT2, GPT2Config, GPT2_TINY
from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.groups import TopologyConfig

# compile-heavy: excluded from the fast core set (pytest -m 'not slow')
pytestmark = pytest.mark.slow



def _batch(rng, cfg, bsz=4):
    return {"input_ids": jax.random.randint(
        rng, (bsz, cfg.max_seq_len), 0, cfg.vocab_size, dtype=jnp.int32)}


def test_forward_shapes_and_dtype():
    model = GPT2(GPT2_TINY)
    params = model.init(jax.random.key(0))
    ids = jnp.zeros((2, 16), jnp.int32)
    logits = model.apply(params, ids)
    assert logits.shape == (2, 16, GPT2_TINY.vocab_size)
    assert logits.dtype == jnp.float32


def test_param_count_matches_formula():
    cfg = GPT2_TINY
    model = GPT2(cfg)
    params = model.init(jax.random.key(0))
    n = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
    assert n == cfg.num_params()


def test_loss_decreases_with_sgd():
    cfg = GPT2Config(n_layer=2, n_head=2, d_model=64, max_seq_len=32,
                     vocab_size=256, remat=False, dtype="float32")
    model = GPT2(cfg)
    params = model.init(jax.random.key(0))
    batch = _batch(jax.random.key(1), cfg, bsz=8)

    @jax.jit
    def step(p):
        loss, g = jax.value_and_grad(model.loss)(p, batch)
        return loss, jax.tree.map(lambda a, b: a - 0.1 * b, p, g)

    losses = []
    for _ in range(15):
        loss, params = step(params)
        losses.append(float(loss))
    assert losses[-1] < losses[0] - 0.3, losses
    assert losses[0] < 1.2 * np.log(cfg.vocab_size)  # sane init loss


def test_causality():
    """Changing a future token must not affect past logits."""
    cfg = GPT2Config(n_layer=2, n_head=2, d_model=64, max_seq_len=16,
                     vocab_size=128, remat=False, dtype="float32")
    model = GPT2(cfg)
    params = model.init(jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (1, 16), 0, 128, jnp.int32)
    ids2 = ids.at[0, 10].set((ids[0, 10] + 1) % 128)
    l1 = model.apply(params, ids)
    l2 = model.apply(params, ids2)
    np.testing.assert_allclose(np.asarray(l1[0, :10]), np.asarray(l2[0, :10]),
                               atol=1e-5)
    assert not np.allclose(np.asarray(l1[0, 10:]), np.asarray(l2[0, 10:]))


def test_tp_matches_single_device():
    """TP=2 sharded forward must equal replicated forward (GSPMD inserts
    the megatron collectives; numerics identical in fp32)."""
    cfg = GPT2Config(n_layer=2, n_head=4, d_model=64, max_seq_len=32,
                     vocab_size=256, remat=False, dtype="float32")
    model = GPT2(cfg)
    params = model.init(jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (4, 32), 0, 256, jnp.int32)
    ref = model.apply(params, ids)

    topo = groups.initialize(TopologyConfig(tensor_parallel_size=2),
                             force=True)
    specs = model.partition_specs(topo)
    sharded = jax.device_put(
        params, jax.tree.map(lambda s: topo.sharding(*s), specs,
                             is_leaf=lambda x: isinstance(x, type(specs["wte"]))))
    with jax.set_mesh(topo.mesh):
        out = jax.jit(lambda p, i: model.apply(p, i))(sharded, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


def test_remat_same_loss():
    cfg = GPT2Config(n_layer=2, n_head=2, d_model=64, max_seq_len=32,
                     vocab_size=256, remat=False, dtype="float32")
    cfg_r = GPT2Config(n_layer=2, n_head=2, d_model=64, max_seq_len=32,
                       vocab_size=256, remat=True, dtype="float32")
    m, mr = GPT2(cfg), GPT2(cfg_r)
    params = m.init(jax.random.key(0))
    batch = _batch(jax.random.key(1), cfg)
    l = float(m.loss(params, batch))
    lr_ = float(mr.loss(params, batch))
    assert abs(l - lr_) < 1e-5


class TestChunkedLoss:
    def test_chunked_matches_dense_any_seq_len(self):
        """loss_chunk path must be numerically identical to dense CE,
        including when (T-1) is not a chunk multiple (the production
        case: T=1024, chunk=256 -> 1023 tokens padded+masked)."""
        import jax
        from dataclasses import replace
        from deepspeed_tpu.models import GPT2, GPT2Config
        base = GPT2Config(n_layer=2, n_head=2, d_model=32, max_seq_len=64,
                          vocab_size=128, remat=False, dtype="float32")
        ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, (3, 64)),
                          jnp.int32)
        dense = GPT2(base)
        params = dense.init(jax.random.key(0))
        l0 = float(dense.loss(params, {"input_ids": ids}, train=False))
        for chunk in (16, 24, 63):
            m = GPT2(replace(base, loss_chunk=chunk))
            l1 = float(jax.jit(lambda p, b: m.loss(p, b, train=False))(
                params, {"input_ids": ids}))
            assert abs(l0 - l1) < 1e-5, (chunk, l0, l1)
        # gradients too (chunk that does not divide T-1)
        m = GPT2(replace(base, loss_chunk=24))
        g0 = jax.grad(lambda p: dense.loss(p, {"input_ids": ids},
                                           train=False))(params)
        g1 = jax.grad(lambda p: m.loss(p, {"input_ids": ids},
                                       train=False))(params)
        err = max(float(jnp.abs(a - b).max())
                  for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)))
        assert err < 1e-4, err

    def test_fused_loss_matches_dense(self):
        """fused_loss (grad-in-forward CE) must match the dense path in
        value AND gradient — including the wte leaf, whose cotangent sums
        the embedding-path and unembed-path contributions."""
        import jax
        from dataclasses import replace
        from deepspeed_tpu.models import GPT2, GPT2Config
        base = GPT2Config(n_layer=2, n_head=2, d_model=32, max_seq_len=64,
                          vocab_size=128, remat=False, dtype="float32")
        ids = jnp.asarray(np.random.RandomState(1).randint(0, 128, (3, 64)),
                          jnp.int32)
        dense = GPT2(base)
        params = dense.init(jax.random.key(2))
        fused = GPT2(replace(base, loss_chunk=24, fused_loss=True))
        l0, g0 = jax.value_and_grad(
            lambda p: dense.loss(p, {"input_ids": ids}, train=False))(params)
        l1, g1 = jax.jit(jax.value_and_grad(
            lambda p: fused.loss(p, {"input_ids": ids}, train=False)))(params)
        assert abs(float(l0) - float(l1)) < 1e-5, (float(l0), float(l1))
        err = max(float(jnp.abs(a - b).max())
                  for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)))
        assert err < 1e-4, err
        # eval (no-AD) primal path
        le = float(jax.jit(lambda p: fused.loss(p, {"input_ids": ids},
                                                train=False))(params))
        assert abs(le - float(l0)) < 1e-5

    def test_fused_loss_kernel_matches_dense(self):
        """fused_loss_kernel (Pallas unembed + online softmax stats)
        must match dense CE in value and gradient. fp32 model: the bf16
        logits materialization only affects d_logits at the MXU's own
        truncation level — tolerance matches the generic-path test."""
        import jax
        from dataclasses import replace
        from deepspeed_tpu.models import GPT2, GPT2Config
        base = GPT2Config(n_layer=2, n_head=2, d_model=32, max_seq_len=64,
                          vocab_size=200, remat=False, dtype="float32")
        ids = jnp.asarray(np.random.RandomState(4).randint(0, 200, (3, 64)),
                          jnp.int32)
        dense = GPT2(base)
        params = dense.init(jax.random.key(6))
        fk = GPT2(replace(base, loss_chunk=24, fused_loss=True,
                          fused_loss_kernel=True))
        l0, g0 = jax.value_and_grad(
            lambda p: dense.loss(p, {"input_ids": ids}, train=False))(params)
        l1, g1 = jax.jit(jax.value_and_grad(
            lambda p: fk.loss(p, {"input_ids": ids}, train=False)))(params)
        assert abs(float(l0) - float(l1)) < 2e-5, (float(l0), float(l1))
        err = max(float(jnp.abs(a - b).max())
                  for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)))
        # d_logits passes through bf16 logits: grads agree to bf16-level
        assert err < 5e-3, err
        le = float(jax.jit(lambda p: fk.loss(p, {"input_ids": ids},
                                             train=False))(params))
        assert abs(le - float(l0)) < 2e-5
