"""ZeRO++ / MiCS tests: hierarchical topology, sharding plans, quantized
collectives (reference tests/unit/runtime/comm/ + zero tests)."""

import numpy as np
import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P
import pytest

import deepspeed_tpu
from deepspeed_tpu import comm as dist
from deepspeed_tpu.models import GPT2, GPT2Config
from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.groups import TopologyConfig

# compile-heavy: excluded from the fast core set (pytest -m 'not slow')
pytestmark = pytest.mark.slow



TINY = GPT2Config(n_layer=2, n_head=2, d_model=64, max_seq_len=32,
                  vocab_size=128, remat=False, dtype="float32")


def _train(config_extra, topology=None, steps=4, seed=0):
    groups.reset()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2(TINY), topology=topology, seed=seed,
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "AdamW", "params": {"lr": 2e-3}},
                "steps_per_print": 0, **config_extra})
    data = np.random.RandomState(3).randint(
        0, TINY.vocab_size, (steps, engine.config.train_batch_size, 32)
    ).astype(np.int32)
    losses = [float(engine.train_batch({"input_ids": data[i]}))
              for i in range(steps)]
    return engine, losses


class TestHierarchicalTopology:
    def test_zero_shard_size_splits_data_axis(self):
        groups.reset()
        topo = groups.initialize(TopologyConfig(zero_shard_size=2))
        assert topo.mesh.shape["data"] == 2
        assert topo.mesh.shape["data_outer"] == 4
        assert topo.get_data_parallel_world_size() == 8
        assert topo.get_zero_shard_group_size() == 2

    def test_default_no_split(self):
        groups.reset()
        topo = groups.initialize(TopologyConfig())
        assert topo.mesh.shape["data_outer"] == 1
        assert topo.mesh.shape["data"] == 8

    def test_indivisible_raises(self):
        groups.reset()
        with pytest.raises(ValueError, match="zero_shard_size"):
            groups.initialize(TopologyConfig(zero_shard_size=3))


class TestMiCS:
    def test_mics_shards_master_within_subgroup(self):
        engine, _ = _train({"zero_optimization": {"stage": 2,
                                                  "mics_shard_size": 2}},
                           steps=1)
        assert engine.topology.mesh.shape["data"] == 2
        # master shards must NOT be partitioned over data_outer
        wqkv_spec = engine.plan.master_specs["blocks"]["wqkv"]
        flat_axes = [a for e in wqkv_spec if e is not None
                     for a in (e if isinstance(e, tuple) else (e,))]
        assert "data" in flat_axes and "data_outer" not in flat_axes

    def test_mics_loss_matches_plain_zero2(self):
        _, base = _train({"zero_optimization": {"stage": 2}})
        _, mics = _train({"zero_optimization": {"stage": 2,
                                                "mics_shard_size": 2}})
        np.testing.assert_allclose(base, mics, rtol=2e-4, atol=2e-4)


class TestHpZ:
    def test_hpz_param_shard_is_inner_master_is_full(self):
        engine, _ = _train({"zero_optimization": {"stage": 3,
                                                  "hpz_partition_size": 2}},
                           steps=1)

        def axes_of(spec):
            return [a for e in spec if e is not None
                    for a in (e if isinstance(e, tuple) else (e,))]

        p_axes = axes_of(engine.plan.param_specs["blocks"]["wqkv"])
        m_axes = axes_of(engine.plan.master_specs["blocks"]["wqkv"])
        assert "data_outer" not in p_axes      # secondary: intra-slice
        assert "data" in p_axes
        assert "data_outer" in m_axes          # optimizer: full DP

    def test_hpz_loss_matches_plain_zero3(self):
        _, base = _train({"zero_optimization": {"stage": 3}})
        _, hpz = _train({"zero_optimization": {"stage": 3,
                                               "hpz_partition_size": 2}})
        np.testing.assert_allclose(base, hpz, rtol=2e-4, atol=2e-4)


class TestQuantizedCollectives:
    def _mesh(self, shard=4):
        groups.reset()
        return groups.initialize(
            TopologyConfig(zero_shard_size=shard)).mesh

    def test_quantized_reduce_scatter_close_to_exact(self):
        mesh = self._mesh(shard=8)
        x = np.random.RandomState(0).randn(8, 1024).astype(np.float32)

        @jax.jit
        def run(x):
            def body(xs):
                x = xs.reshape(-1)
                return dist.quantized_reduce_scatter(x, "data")
            return shard_map(body, mesh=mesh,
                             in_specs=P("data"), out_specs=P("data"))(x)

        out = np.asarray(run(x)).reshape(8, 128)
        exact = x.sum(0).reshape(8, 128)
        scale = np.abs(x).max()
        np.testing.assert_allclose(out, exact, atol=scale * 8 * 2 / 127)

    def test_quantized_all_gather_close_to_exact(self):
        mesh = self._mesh(shard=8)
        x = np.random.RandomState(1).randn(8, 256).astype(np.float32)

        @jax.jit
        def run(x):
            def body(xs):
                # stacked (W, M) gather like lax.all_gather; keep device
                # 0's copy
                return dist.quantized_all_gather(xs.reshape(-1), "data")
            return shard_map(body, mesh=mesh,
                             in_specs=P("data"), out_specs=P(None, "data"))(x)

        out = np.asarray(run(x))   # (8, 8*256): gather dim x shard dim
        full = out.reshape(8, 8, 256)[:, 0]  # device 0's gathered stack
        np.testing.assert_allclose(full, x, atol=np.abs(x).max() / 100)

    def test_hierarchical_a2a_quant_reduce(self):
        mesh = self._mesh(shard=2)  # data=2, data_outer=4
        x = np.random.RandomState(2).randn(8, 512).astype(np.float32)

        @jax.jit
        def run(x):
            def body(xs):
                return dist.all_to_all_quant_reduce(
                    xs.reshape(-1), inner_axis="data",
                    outer_axis="data_outer")
            return shard_map(body, mesh=mesh,
                             in_specs=P(("data_outer", "data")),
                             out_specs=P(("data_outer", "data")))(x)

        # output layout now matches a single reduce_scatter over the
        # combined ('data_outer','data') axes: device (o,i) = chunk o*Wi+i
        out = np.asarray(run(x)).reshape(-1)
        exact = x.sum(0)
        np.testing.assert_allclose(out, exact,
                                   atol=np.abs(x).max() * 8 * 4 / 127)

    def test_comm_volume_logged(self):
        mesh = self._mesh(shard=8)
        from deepspeed_tpu.comm import get_comms_logger
        lg = get_comms_logger()
        lg.enabled = True
        lg.reset()
        x = np.zeros((8, 1024), np.float32)

        @jax.jit
        def run(x):
            def body(xs):
                return dist.quantized_reduce_scatter(xs.reshape(-1), "data")
            return shard_map(body, mesh=mesh,
                             in_specs=P("data"), out_specs=P("data"))(x)

        run(x)
        names = list(lg.comms_dict)
        lg.enabled = False
        lg.reset()
        assert any("quantized" in n for n in names), names
