"""Paged-KV host offload: the device pool is an LRU cache over a host
logical block space (inference/v2/kv_offload.py; reference README.md:30
ZeRO-Inference "KV-cache offload").

The core property: an engine whose device pool is far smaller than the
batch's total KV footprint — forcing dispatch grouping, eviction,
write-back, and re-upload — produces EXACTLY the tokens of an engine
with everything device-resident.
"""

import numpy as np
import pytest

import jax

from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                  RaggedInferenceEngineConfig)
from deepspeed_tpu.models import GPT2, GPT2Config
from deepspeed_tpu.utils import groups


def _model():
    cfg = GPT2Config(n_layer=2, n_head=4, d_model=64, max_seq_len=256,
                     vocab_size=512, remat=False, dtype="float32")
    return GPT2(cfg)


def _prompts(n, rng=0):
    r = np.random.RandomState(rng)
    return [r.randint(0, 500, (r.randint(6, 40),)).astype(np.int32)
            for _ in range(n)]


def _run(engine, prompts, max_new=12):
    return [np.asarray(t) for t in
            engine.generate_all(prompts, max_new_tokens=max_new)]


class TestKVOffload:
    def setup_method(self, method):
        groups.reset()

    def test_offload_matches_resident(self):
        model = _model()
        prompts = _prompts(6)
        params = model.init(jax.random.key(0))

        ref_eng = InferenceEngineV2(model, params=params, max_batch_size=4,
                                    kv_block_size=16)
        ref = _run(ref_eng, prompts)

        groups.reset()
        # device pool: 8 blocks (7 usable) vs ~4 seqs x 4 blocks logical
        # footprint — forces per-group dispatch + eviction churn
        eng = InferenceEngineV2(model, params=params, max_batch_size=4,
                                kv_block_size=16, kv_host_offload=True,
                                device_kv_blocks=8)
        got = _run(eng, prompts)
        assert eng.kv_pool.swapped_in > 0, "pool never swapped"
        assert eng.kv_pool.swapped_out > 0, "no dirty write-backs"
        for a, b in zip(ref, got):
            np.testing.assert_array_equal(a, b)

    def test_offload_splitfuse_chunks(self):
        model = _model()
        prompts = _prompts(4, rng=3)
        prompts[0] = np.arange(100, 190).astype(np.int32) % 500  # long
        params = model.init(jax.random.key(1))

        ref_eng = InferenceEngineV2(model, params=params, max_batch_size=3,
                                    kv_block_size=16)
        ref = _run(ref_eng, prompts)

        groups.reset()
        eng = InferenceEngineV2(model, params=params, max_batch_size=3,
                                kv_block_size=16, splitfuse_tokens=32,
                                kv_host_offload=True, device_kv_blocks=9)
        got = _run(eng, prompts)
        assert eng.kv_pool.swapped_in > 0
        for a, b in zip(ref, got):
            np.testing.assert_array_equal(a, b)

    def test_footprint_exceeds_device_pool(self):
        """The headline capacity claim in miniature: total logical KV
        of the admitted batch exceeds the device pool, yet every
        sequence completes correctly."""
        model = _model()
        params = model.init(jax.random.key(2))
        prompts = _prompts(4, rng=5)
        eng = InferenceEngineV2(model, params=params, max_batch_size=4,
                                kv_block_size=16, num_kv_blocks=64,
                                kv_host_offload=True, device_kv_blocks=6)
        # footprint check: each seq needs ceil((len+12)/16) blocks
        need = sum(-(-(len(p) + 12) // 16) for p in prompts)
        assert need > 6 - 1, "test must oversubscribe the device pool"
        got = _run(eng, prompts)
        groups.reset()
        ref_eng = InferenceEngineV2(model, params=params, max_batch_size=4,
                                    kv_block_size=16)
        ref = _run(ref_eng, prompts)
        for a, b in zip(ref, got):
            np.testing.assert_array_equal(a, b)

    def test_request_too_big_for_pool_raises(self):
        model = _model()
        params = model.init(jax.random.key(2))
        eng = InferenceEngineV2(model, params=params, max_batch_size=2,
                                kv_block_size=16, kv_host_offload=True,
                                device_kv_blocks=4)
        with pytest.raises(ValueError, match="device pool"):
            eng.put(np.arange(100).astype(np.int32), max_new_tokens=50)

    def test_offload_requires_pool_size(self):
        model = _model()
        params = model.init(jax.random.key(2))
        with pytest.raises(ValueError, match="device_kv_blocks"):
            InferenceEngineV2(model, params=params, kv_host_offload=True)


class TestStaleHandleGuard:
    def setup_method(self, method):
        groups.reset()

    def test_stale_prepare_handle_raises(self):
        """A prepare() handle built for a DIFFERENT block list must make
        ensure() fail loudly — not silently leave the extra blocks
        routed at the scratch slot (attending garbage)."""
        model = _model()
        params = model.init(jax.random.key(0))
        eng = InferenceEngineV2(model, params=params, max_batch_size=4,
                                kv_block_size=16, kv_host_offload=True,
                                device_kv_blocks=8)
        pool = eng.kv_pool
        handle = pool.prepare([1])            # upload payload for 1 only
        with pytest.raises(RuntimeError, match="stale prepare"):
            eng.cache = pool.ensure(eng.cache, [1, 2], prepared=handle)

    def test_fresh_handle_commits(self):
        model = _model()
        params = model.init(jax.random.key(0))
        eng = InferenceEngineV2(model, params=params, max_batch_size=4,
                                kv_block_size=16, kv_host_offload=True,
                                device_kv_blocks=8)
        pool = eng.kv_pool
        handle = pool.prepare([1, 2])
        eng.cache = pool.ensure(eng.cache, [1, 2], prepared=handle)
        assert pool.slot_of[1] >= 0 and pool.slot_of[2] >= 0
