"""Launcher + env-report tests (reference tests/unit/launcher/)."""

import os
import subprocess
import sys

import pytest

from deepspeed_tpu.launcher.runner import (build_worker_cmds, fetch_hostfile,
                                           parse_inclusion_exclusion,
                                           parse_args)


@pytest.fixture
def hostfile(tmp_path):
    f = tmp_path / "hostfile"
    f.write_text("""
# training pod
tpu-a slots=4
tpu-b slots=4
tpu-c slots=8
""")
    return str(f)


class TestHostfile:
    def test_parse(self, hostfile):
        pool = fetch_hostfile(hostfile)
        assert pool == {"tpu-a": 4, "tpu-b": 4, "tpu-c": 8}

    def test_malformed_raises(self, tmp_path):
        f = tmp_path / "bad"
        f.write_text("hostx gpus=4\n")
        with pytest.raises(ValueError, match="malformed"):
            fetch_hostfile(str(f))

    def test_duplicate_raises(self, tmp_path):
        f = tmp_path / "dup"
        f.write_text("h1 slots=2\nh1 slots=4\n")
        with pytest.raises(ValueError, match="duplicate"):
            fetch_hostfile(str(f))


class TestFilters:
    POOL = {"a": 4, "b": 4, "c": 8}

    def test_include(self):
        assert parse_inclusion_exclusion(self.POOL, include_str="a@c") == \
            {"a": 4, "c": 8}

    def test_exclude(self):
        assert parse_inclusion_exclusion(self.POOL, exclude_str="b") == \
            {"a": 4, "c": 8}

    def test_both_raises(self):
        with pytest.raises(ValueError):
            parse_inclusion_exclusion(self.POOL, "a", "b")

    def test_unknown_host_raises(self):
        with pytest.raises(ValueError, match="unknown host"):
            parse_inclusion_exclusion(self.POOL, include_str="zzz")


class TestWorkerCmds:
    def test_env_triplet(self):
        cmds = build_worker_cmds(["h0", "h1", "h2"], "h0:8476",
                                 "train.py", ["--lr", "1e-4"])
        assert len(cmds) == 3
        for pid, (host, argv, env) in enumerate(cmds):
            assert env["COORDINATOR_ADDRESS"] == "h0:8476"
            assert env["NUM_PROCESSES"] == "3"
            assert env["PROCESS_ID"] == str(pid)
            assert argv[-3:] == ["train.py", "--lr", "1e-4"]

    def test_passthrough(self, monkeypatch):
        monkeypatch.setenv("MY_FLAG", "7")
        cmds = build_worker_cmds(["h0"], "h0:1", "t.py", [],
                                 env_passthrough=("MY_FLAG", "ABSENT"))
        assert cmds[0][2]["MY_FLAG"] == "7"
        assert "ABSENT" not in cmds[0][2]


class TestArgs:
    def test_script_args_remainder(self):
        a = parse_args(["--launcher", "ssh", "train.py", "--deepspeed_config",
                        "ds.json"])
        assert a.script == "train.py"
        assert a.script_args == ["--deepspeed_config", "ds.json"]


class TestEnvReport:
    def test_report_runs(self, capsys):
        from deepspeed_tpu.env_report import report, op_compatibility
        report()
        out = capsys.readouterr().out
        assert "deepspeed_tpu" in out and "jax" in out
        rows = {name: ok for name, ok, _ in op_compatibility()}
        # quantizer + flash attention are interpretable on CPU via jit;
        # they must at least import and trace
        assert set(rows) == {"pallas_flash_attention", "pallas_quantizer",
                             "native_ckpt_writer"}


class TestSlurmRunner:
    def test_srun_command_shape(self):
        """reference multinode_runner.py:340: one srun for the whole job;
        rank mapped from SLURM_PROCID at runtime."""
        from deepspeed_tpu.launcher.runner import (SlurmRunner,
                                                   build_worker_cmds)
        import argparse
        cmds = build_worker_cmds(["node1", "node2", "node3"], "node1:8476",
                                 "train.py", ["--lr", "1e-4"])
        r = SlurmRunner(argparse.Namespace())
        argv = r.build_cmd(cmds)
        assert argv[0] == "srun"
        assert "--nodes=3" in argv and "--ntasks=3" in argv
        assert "--ntasks-per-node=1" in argv
        assert "--nodelist=node1,node2,node3" in argv
        inner = argv[-1]
        assert "PROCESS_ID=$SLURM_PROCID" in inner
        assert "NUM_PROCESSES=3" in inner
        # coordinator resolves from Slurm's OWN node ordering at runtime
        # (srun sorts --nodelist; rank 0 must own the coordinator port)
        assert ("COORDINATOR_ADDRESS=$(scontrol show hostnames "
                '"$SLURM_JOB_NODELIST" | head -n1):8476') in inner
        assert "train.py --lr 1e-4" in inner
        # static rendezvous values must NOT leak into the shared exports
        assert "PROCESS_ID=0" not in inner
        assert "COORDINATOR_ADDRESS=node1" not in inner

    def test_autotuning_cli_end_to_end(self, tmp_path, monkeypatch):
        """dstpu --autotuning tune <script>: the full CLI path —
        ResourceManager over localhost, subprocess trials via the
        --exp JSON protocol, best_config.json + report emitted
        (reference launcher/runner.py:359 deepspeed --autotuning)."""
        import json
        from deepspeed_tpu.launcher import runner as R
        script = tmp_path / "trial.py"
        # synthetic objective: best at micro=24, bq=1024
        script.write_text(
            "import json, sys\n"
            "exp = json.loads(sys.argv[sys.argv.index('--exp') + 1])\n"
            "m = int(exp['BENCH_MICRO_BS']); bq = int(exp['BENCH_FLASH_BQ'])\n"
            "v = 100 - abs(m - 24) + (5 if bq == 1024 else 0)\n"
            "print(json.dumps({'value': v}))\n")
        space = tmp_path / "space.json"
        space.write_text(json.dumps({
            "BENCH_MICRO_BS": [16, 24, 32],
            "BENCH_FLASH_BQ": [512, 1024]}))
        results = tmp_path / "results"
        rc = R.main(["--autotuning", "tune",
                     "--autotuning_space", str(space),
                     "--autotuning_trials", "6",
                     "--autotuning_results", str(results),
                     str(script)])
        assert rc == 0
        best = json.loads((results / "best_config.json").read_text())
        assert best == {"BENCH_MICRO_BS": 24, "BENCH_FLASH_BQ": 1024}
        lines = (results / "exps.jsonl").read_text().strip().splitlines()
        assert 1 <= len(lines) <= 6
        assert (results / "report.txt").exists()

    def test_elastic_rejected_with_slurm(self, tmp_path):
        from deepspeed_tpu.launcher import runner as R
        import pytest
        hostfile = tmp_path / "hosts"
        hostfile.write_text("node1 slots=4\nnode2 slots=4\n")
        with pytest.raises(SystemExit, match="per-host launcher"):
            R.main(["-H", str(hostfile), "--launcher", "slurm",
                    "--elastic", "train.py"])

    def test_selected_by_flag(self, monkeypatch, tmp_path):
        """--launcher slurm routes through SlurmRunner (Popen captured)."""
        from deepspeed_tpu.launcher import runner as R
        hostfile = tmp_path / "hosts"
        hostfile.write_text("node1 slots=4\nnode2 slots=4\n")
        captured = []

        class FakeProc:
            def wait(self):
                return 0

        monkeypatch.setattr(R.subprocess, "Popen",
                            lambda argv, **kw: captured.append(argv)
                            or FakeProc())
        monkeypatch.setattr(R.SlurmRunner, "available", lambda self: True)
        rc = R.main(["-H", str(hostfile), "--launcher", "slurm",
                     "train.py"])
        assert rc == 0
        assert len(captured) == 1 and captured[0][0] == "srun"


class TestElasticLauncher:
    def test_relaunch_through_killed_worker(self, tmp_path):
        """dstpu --elastic end to end on local 'hosts': generation 0 has a
        worker die; the agent drops that host and relaunches the world,
        which then completes cleanly (reference bin/ds_elastic +
        launcher/runner.py:373)."""
        import sys
        from deepspeed_tpu.launcher import runner as R
        log = tmp_path / "events.log"
        script = tmp_path / "worker.py"
        script.write_text(
            "import os, sys\n"
            "gen = os.environ['ELASTIC_GENERATION']\n"
            "pid = os.environ['PROCESS_ID']\n"
            "n = os.environ['NUM_PROCESSES']\n"
            "with open(sys.argv[1], 'a') as f:\n"
            "    f.write(f'{gen} {pid} {n}\\n')\n"
            "if gen == '0' and pid == '1':\n"
            "    sys.exit(3)\n"
        )
        hostfile = tmp_path / "hosts"
        # two 'hosts' the SSHRunner treats as local (no ssh involved)
        hostfile.write_text("localhost slots=1\n127.0.0.1 slots=1\n")
        rc = R.main(["-H", str(hostfile), "--elastic",
                     "--max_elastic_restarts", "2",
                     str(script), str(log)])
        assert rc == 0
        events = [l.split() for l in log.read_text().splitlines()]
        # generation 0: 2 workers (world=2); PROCESS_ID 1 died
        gen0 = [e for e in events if e[0] == "0"]
        assert sorted(e[1] for e in gen0) == ["0", "1"]
        assert all(e[2] == "2" for e in gen0)
        # generation 1: relaunched on the surviving host only (world=1)
        gen1 = [e for e in events if e[0] == "1"]
        assert [e[1] for e in gen1] == ["0"]
        assert all(e[2] == "1" for e in gen1)
