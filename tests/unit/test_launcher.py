"""Launcher + env-report tests (reference tests/unit/launcher/)."""

import os
import subprocess
import sys

import pytest

from deepspeed_tpu.launcher.runner import (build_worker_cmds, fetch_hostfile,
                                           parse_inclusion_exclusion,
                                           parse_args)


@pytest.fixture
def hostfile(tmp_path):
    f = tmp_path / "hostfile"
    f.write_text("""
# training pod
tpu-a slots=4
tpu-b slots=4
tpu-c slots=8
""")
    return str(f)


class TestHostfile:
    def test_parse(self, hostfile):
        pool = fetch_hostfile(hostfile)
        assert pool == {"tpu-a": 4, "tpu-b": 4, "tpu-c": 8}

    def test_malformed_raises(self, tmp_path):
        f = tmp_path / "bad"
        f.write_text("hostx gpus=4\n")
        with pytest.raises(ValueError, match="malformed"):
            fetch_hostfile(str(f))

    def test_duplicate_raises(self, tmp_path):
        f = tmp_path / "dup"
        f.write_text("h1 slots=2\nh1 slots=4\n")
        with pytest.raises(ValueError, match="duplicate"):
            fetch_hostfile(str(f))


class TestFilters:
    POOL = {"a": 4, "b": 4, "c": 8}

    def test_include(self):
        assert parse_inclusion_exclusion(self.POOL, include_str="a@c") == \
            {"a": 4, "c": 8}

    def test_exclude(self):
        assert parse_inclusion_exclusion(self.POOL, exclude_str="b") == \
            {"a": 4, "c": 8}

    def test_both_raises(self):
        with pytest.raises(ValueError):
            parse_inclusion_exclusion(self.POOL, "a", "b")

    def test_unknown_host_raises(self):
        with pytest.raises(ValueError, match="unknown host"):
            parse_inclusion_exclusion(self.POOL, include_str="zzz")


class TestWorkerCmds:
    def test_env_triplet(self):
        cmds = build_worker_cmds(["h0", "h1", "h2"], "h0:8476",
                                 "train.py", ["--lr", "1e-4"])
        assert len(cmds) == 3
        for pid, (host, argv, env) in enumerate(cmds):
            assert env["COORDINATOR_ADDRESS"] == "h0:8476"
            assert env["NUM_PROCESSES"] == "3"
            assert env["PROCESS_ID"] == str(pid)
            assert argv[-3:] == ["train.py", "--lr", "1e-4"]

    def test_passthrough(self, monkeypatch):
        monkeypatch.setenv("MY_FLAG", "7")
        cmds = build_worker_cmds(["h0"], "h0:1", "t.py", [],
                                 env_passthrough=("MY_FLAG", "ABSENT"))
        assert cmds[0][2]["MY_FLAG"] == "7"
        assert "ABSENT" not in cmds[0][2]


class TestArgs:
    def test_script_args_remainder(self):
        a = parse_args(["--launcher", "ssh", "train.py", "--deepspeed_config",
                        "ds.json"])
        assert a.script == "train.py"
        assert a.script_args == ["--deepspeed_config", "ds.json"]


class TestEnvReport:
    def test_report_runs(self, capsys):
        from deepspeed_tpu.env_report import report, op_compatibility
        report()
        out = capsys.readouterr().out
        assert "deepspeed_tpu" in out and "jax" in out
        rows = {name: ok for name, ok, _ in op_compatibility()}
        # quantizer + flash attention are interpretable on CPU via jit;
        # they must at least import and trace
        assert set(rows) == {"pallas_flash_attention", "pallas_quantizer",
                             "native_ckpt_writer"}
