"""Speculative decoding (tier-1, ISSUE 19): acceptance math, knob
resolution, host-side rollback accounting (seen_tokens unwind, draft
block frees, prefix-cache refcounts surviving rejection), greedy
byte-identity spec-on vs spec-off, the acceptance-floor fallback latch,
DeadlineExceeded withdrawal mid-speculation through the router, and the
``serve_verify`` chaos point (retryable absorb + failover replay).

Engines follow the test_router.py fast pattern: tiny GPT2, module-cached
params, compile-heavy clean-completion tests share engines."""

import numpy as np
import pytest

import jax

from deepspeed_tpu.autotuning import kernel_dispatch
from deepspeed_tpu.inference.v2 import (DeadlineExceeded,
                                        InferenceEngineV2, Router)
from deepspeed_tpu.inference.v2.ragged import DSStateManager
from deepspeed_tpu.inference.v2.speculative import (SPEC_DEFAULTS,
                                                    SPEC_MIN_ROUNDS,
                                                    longest_accept,
                                                    resolve_spec)
from deepspeed_tpu.models import GPT2, GPT2Config
from deepspeed_tpu.utils import fault_injection, groups


@pytest.fixture(autouse=True)
def _pristine_dispatch(tmp_path, monkeypatch):
    monkeypatch.setenv("DSTPU_AUTOTUNE_CACHE",
                       str(tmp_path / "kernel_autotune.json"))
    monkeypatch.delenv("DSTPU_AUTOTUNE", raising=False)
    kernel_dispatch.reset()
    fault_injection.reset()
    yield
    fault_injection.reset()
    kernel_dispatch.reset()


_CFG = GPT2Config(n_layer=2, n_head=4, d_model=64, max_seq_len=128,
                  vocab_size=256, remat=False, dtype="float32")
_DCFG = GPT2Config(n_layer=1, n_head=2, d_model=32, max_seq_len=128,
                   vocab_size=256, remat=False, dtype="float32")
_PARAMS = {}


def _params(which="t"):
    if which not in _PARAMS:
        _PARAMS[which] = (GPT2(_CFG).init(jax.random.key(0)) if which == "t"
                          else GPT2(_DCFG).init(jax.random.key(1)))
    return _PARAMS[which]


_BASE = {"dtype": "float32", "kv_block_size": 8, "prompt_bucket": 16,
         "max_batch_size": 2, "splitfuse_tokens": 16,
         "decode_steps_per_dispatch": 2}


def _engine(spec=False, **kw):
    groups.reset()
    draft = {}
    if spec:
        draft = dict(draft_model=GPT2(_DCFG), draft_params=_params("d"))
        kw.setdefault("spec_draft", True)
        kw.setdefault("spec_k", 4)
    return InferenceEngineV2(GPT2(_CFG), params=_params("t"),
                             config=dict(_BASE, **kw), **draft)


# compile-heavy clean-completion tests share one plain + one spec engine
_SHARED = {}


def _shared(spec):
    key = "spec" if spec else "plain"
    if key not in _SHARED:
        _SHARED[key] = _engine(spec=spec)
    return _SHARED[key]


def _prompts(seed, n, lo=6, hi=20):
    rs = np.random.RandomState(seed)
    return [rs.randint(1, 255, size=rs.randint(lo, hi)).astype(np.int32)
            for _ in range(n)]


def _run(router, max_rounds=400):
    rounds = 0
    while router.has_work:
        router.step()
        rounds += 1
        assert rounds < max_rounds, "router failed to drain"
    return rounds


def _pools_closed(eng):
    alloc = eng.state_mgr.allocator
    tree = eng.prefix_cache.tree_blocks if eng.prefix_cache else 0
    assert alloc.free_blocks + tree == alloc.total_blocks
    da = eng.state_mgr.draft_allocator
    if da is not None:
        assert da.free_blocks == da.total_blocks, "leaked draft blocks"


# ---------------------------------------------------------------------------
# pure host math + knob resolution
# ---------------------------------------------------------------------------

class TestAcceptanceMath:
    def test_longest_accept(self):
        assert longest_accept([1, 2, 3], [1, 2, 3, 4]) == 3
        assert longest_accept([1, 9, 3], [1, 2, 3, 4]) == 1
        assert longest_accept([9, 2, 3], [1, 2, 3, 4]) == 0
        assert longest_accept([], [7]) == 0
        # stops at the FIRST mismatch even if later positions re-align
        assert longest_accept([1, 9, 3, 4], [1, 2, 3, 4, 5]) == 1

    def test_resolve_spec_cold_defaults(self):
        on, k, floor = resolve_spec("auto", "auto", B=4, NB=64, BS=8,
                                    dtype="float32")
        assert on == bool(SPEC_DEFAULTS["enabled"])
        assert k == SPEC_DEFAULTS["spec_k"]
        assert floor == SPEC_DEFAULTS["floor_pct"] / 100.0

    def test_resolve_spec_forced(self):
        on, k, _ = resolve_spec(False, 8, B=4, NB=64, BS=8,
                                dtype="float32")
        assert on is False and k == 8
        on, k, _ = resolve_spec(True, 2, B=4, NB=64, BS=8,
                                dtype="float32")
        assert on is True and k == 2


# ---------------------------------------------------------------------------
# rollback accounting: host-only, no device programs involved
# ---------------------------------------------------------------------------

class TestRollbackAccounting:
    def _mgr(self):
        from deepspeed_tpu.inference.v2 import BlockedAllocator
        m = DSStateManager(num_blocks=17, block_size=4, max_batch=2,
                           max_blocks_per_seq=4)
        m.draft_allocator = BlockedAllocator(17)
        return m

    def test_rollback_unwinds_seen_tokens_exactly(self):
        m = self._mgr()
        _, seq = m.admit(1, np.arange(6), max_new_tokens=8)
        seq.generated.append(42)
        pre = seq.seen_tokens
        m.begin_spec(seq, [7, 8, 9, 10])
        assert seq.seen_tokens == pre + 4
        with pytest.raises(AssertionError):
            m.begin_spec(seq, [1])          # nested span forbidden
        assert m.rollback_spec(seq) == 4
        assert seq.seen_tokens == pre
        assert seq.generated == [42]
        assert seq.spec_inflight == 0

    def test_rollback_keeps_accepted_prefix(self):
        m = self._mgr()
        _, seq = m.admit(1, np.arange(6), max_new_tokens=8)
        seq.generated.append(42)
        m.begin_spec(seq, [7, 8, 9, 10])
        assert m.rollback_spec(seq, keep=2) == 2
        assert seq.generated == [42, 7, 8]

    def test_draft_blocks_freed_on_every_exit_path(self):
        m = self._mgr()
        da = m.draft_allocator
        total = da.free_blocks
        _, s1 = m.admit(1, np.arange(6), max_new_tokens=8)
        _, s2 = m.admit(2, np.arange(6), max_new_tokens=8)
        assert m.alloc_draft(s1) and m.alloc_draft(s2)
        assert da.free_blocks == total - len(s1.blocks) - len(s2.blocks)
        m.retire(1)                      # EOS/budget exit
        m.flush(1)
        m.flush(2)                       # cancel exit (no retire first)
        assert da.free_blocks == total
        assert s1.draft_blocks == [] and s2.draft_blocks == []

    def test_draft_pool_exhaustion_latches_plain_decode(self):
        from deepspeed_tpu.inference.v2 import BlockedAllocator
        m = DSStateManager(num_blocks=17, block_size=4, max_batch=2,
                           max_blocks_per_seq=4)
        m.draft_allocator = BlockedAllocator(3)   # room for 2 blocks
        _, s1 = m.admit(1, np.arange(9), max_new_tokens=7)  # 4 blocks
        assert not m.alloc_draft(s1)
        assert s1.spec_on is False                # latched, not an error
        assert not m.alloc_draft(s1)              # latch is sticky

    def test_prefix_cache_refcounts_survive_rollback(self):
        """begin/rollback never touch block state: a sequence whose
        prompt was served from shared (refcount > 1) prefix-cache
        blocks keeps exactly its refs across a rejected span, and
        retire closes the accounting."""
        from deepspeed_tpu.inference.v2 import BlockedAllocator
        from deepspeed_tpu.inference.v2.prefix_cache import PrefixCache
        m = DSStateManager(num_blocks=17, block_size=4, max_batch=2,
                           max_blocks_per_seq=4)
        m.draft_allocator = BlockedAllocator(17)
        m.prefix_cache = PrefixCache(m.allocator, 4, min_match_blocks=1)
        toks = np.arange(8, dtype=np.int32)
        m.prefix_cache.release(toks.tolist(), m.allocator.allocate(2))
        _, seq = m.admit(1, np.concatenate([toks, [99, 98, 97]]),
                         max_new_tokens=5)
        assert seq.cached_len > 0, "prefix hit expected"
        shared = seq.blocks[0]
        refs_before = m.allocator.refcount(shared)
        assert refs_before == 2          # tree ref + sequence ref
        seq.generated.append(42)
        m.begin_spec(seq, [7, 8, 9])
        m.rollback_spec(seq)
        assert m.allocator.refcount(shared) == refs_before
        m.retire(1)
        m.flush(1)
        # every block free or tree-adopted, nothing double-unreffed
        assert m.allocator.free_blocks + m.prefix_cache.tree_blocks \
            == m.allocator.total_blocks


# ---------------------------------------------------------------------------
# engine: greedy byte-identity + acceptance-floor fallback
# ---------------------------------------------------------------------------

class TestEngineSpeculates:
    def test_greedy_spec_on_matches_spec_off(self):
        prompts = _prompts(1, 3)
        ref = _shared(False).generate_all(prompts, max_new_tokens=10)
        eng = _shared(True)
        assert eng.draft_model is not None
        outs = eng.generate_all(prompts, max_new_tokens=10)
        for o, r in zip(outs, ref):
            np.testing.assert_array_equal(o, r)
        _pools_closed(eng)
        # speculation actually ran (not silently plain decode) and the
        # telemetry guard keys appeared
        p = eng.telemetry.percentiles()
        assert p.get("spec_rounds", 0) > 0
        assert p["spec_tokens_per_verify_step"] >= 1.0

    def test_sampled_sequences_never_speculate(self):
        """temperature > 0 rides plain decode: speculation is greedy
        acceptance only."""
        eng = _shared(True)
        uid = eng.put(_prompts(2, 1)[0], max_new_tokens=6,
                      temperature=0.8, top_k=4)
        rounds0 = eng.telemetry.spec_rounds
        while eng.has_work:
            eng.step()
        out = eng.get(uid)
        assert len(out) == 6
        assert eng.telemetry.spec_rounds == rounds0
        _pools_closed(eng)

    def test_acceptance_floor_latches_fallback_and_output_is_identical(
            self):
        """With the floor forced above any achievable EMA, every
        sequence latches to plain decode after SPEC_MIN_ROUNDS verify
        rounds — and the output stays byte-identical (fallback is the
        unchanged plain program)."""
        prompts = _prompts(1, 2)
        # long enough that SPEC_MIN_ROUNDS verify rounds happen before
        # the budget retires the sequence even at full acceptance
        # (k+1 commits per round)
        ref = _shared(False).generate_all(prompts, max_new_tokens=18)
        eng = _shared(True)
        floor0 = eng._spec_floor
        try:
            eng._spec_floor = 1.1
            uids = [eng.put(p, max_new_tokens=18) for p in prompts]
            latched = {}
            while eng.has_work:
                eng.step()
                for uid in uids:
                    seq = eng.state_mgr._seqs.get(uid)
                    if seq is not None and not seq.spec_on:
                        latched[uid] = (seq.spec_rounds,
                                        list(seq.draft_blocks))
            for uid, r in zip(uids, ref):
                np.testing.assert_array_equal(eng.get(uid), r)
            assert set(latched) == set(uids), "floor never latched"
            for rounds, draft_blocks in latched.values():
                assert rounds >= SPEC_MIN_ROUNDS
                assert draft_blocks == []    # latch returned the blocks
            _pools_closed(eng)
        finally:
            eng._spec_floor = floor0

    def test_spec_draft_true_without_draft_model_raises(self):
        with pytest.raises(ValueError, match="requires a draft model"):
            groups.reset()
            InferenceEngineV2(GPT2(_CFG), params=_params("t"),
                              config=dict(_BASE, spec_draft=True))

    def test_vocab_mismatch_raises(self):
        bad = GPT2Config(n_layer=1, n_head=2, d_model=32,
                         max_seq_len=128, vocab_size=128, remat=False,
                         dtype="float32")
        with pytest.raises(ValueError, match="vocab mismatch"):
            groups.reset()
            InferenceEngineV2(GPT2(_CFG), params=_params("t"),
                              config=dict(_BASE, spec_draft=True),
                              draft_model=GPT2(bad))


# ---------------------------------------------------------------------------
# router: deadline withdrawal mid-speculation + serve_verify chaos
# ---------------------------------------------------------------------------

class TestRouterIntegration:
    def test_deadline_withdrawal_mid_speculation(self):
        """A request expiring while its sequence is actively
        speculating is withdrawn through cancel() -> flush(): typed
        DeadlineExceeded, target AND draft pools close with zero
        leaked blocks."""
        eng = _shared(True)
        router = Router([eng])
        clock = {"t": 0.0}
        router._now = lambda: clock["t"]
        uid = router.put(_prompts(3, 1)[0], max_new_tokens=64,
                         deadline_ms=5000)
        for _ in range(3):
            router.step()                      # genuinely decoding
        req = router._reqs[uid]
        assert req.state == "inflight" and req.n_tokens > 0
        seq = eng.state_mgr._seqs[uid]
        assert seq.draft_blocks, "speculation never engaged"
        clock["t"] = 10.0
        router.step()
        with pytest.raises(DeadlineExceeded):
            router.get(uid)
        assert uid not in eng.state_mgr._seqs
        _pools_closed(eng)
        assert not router.has_work

    def test_serve_verify_fault_is_absorbed_and_output_identical(self):
        """Retryable ``serve_verify`` faults below the health threshold
        are absorbed by the replica health machine; the engine's
        rollback leaves no speculative tokens behind, so the final
        stream is still byte-identical to plain decode."""
        prompts = _prompts(1, 1)
        ref = _shared(False).generate_all(prompts, max_new_tokens=10)
        eng = _shared(True)
        router = Router([eng], max_step_failures=3)
        fault_injection.arm("serve_verify", fails=2)   # absorbed: 2 < 3
        uid = router.put(prompts[0], max_new_tokens=10)
        _run(router)
        assert router.replicas[0].live
        assert router.replicas[0].step_failures == 2
        assert fault_injection.injector.hits("serve_verify") == 2
        np.testing.assert_array_equal(router.get(uid), ref[0])
        _pools_closed(eng)

    def test_serve_verify_heartbeat_break_fails_over_byte_identically(
            self):
        """PR 17 failover replay covering speculation state: the
        speculating replica breaks its heartbeat on armed serve_verify
        faults mid-speculation, the router replays on the survivor, and
        the replayed greedy stream is byte-identical."""
        prompts = _prompts(1, 1)
        ref = _shared(False).generate_all(prompts, max_new_tokens=10)
        # one fresh engine to kill; the survivor reuses the shared spec
        # engine (nothing after this test touches it) — a full fresh
        # compile of a second spec engine buys no extra coverage
        e1, e2 = _engine(spec=True), _shared(True)
        router = Router([e1, e2], max_step_failures=2)
        uid = router.put(prompts[0], max_new_tokens=10)
        fault_injection.arm("serve_verify", fails=2)   # breaks heartbeat
        _run(router)
        snap = router.snapshot()
        assert snap["failovers"] == 1 and snap["replayed"] == 1
        assert sum(r.dead for r in router.replicas) == 1
        np.testing.assert_array_equal(router.get(uid), ref[0])
        # the survivor ran verify rounds -> snapshot surfaces its EMA
        snap = router.snapshot()
        assert "spec_acceptance_ema" in snap
        survivor = next(r for r in router.replicas if r.live)
        assert 0.0 <= snap["spec_acceptance_ema"][survivor.name] <= 1.0
        _pools_closed(next(r.engine for r in router.replicas if r.live))

    def test_spec_off_snapshot_has_no_spec_keys(self):
        """Zero-verify guard at the router layer: a spec-off fleet's
        snapshot carries no spec_acceptance_ema key and the engine's
        percentiles no spec_* keys — shapes stay byte-identical to the
        pre-speculation serving stack."""
        eng = _shared(False)
        router = Router([eng])
        uid = router.put(_prompts(4, 1)[0], max_new_tokens=4)
        _run(router)
        router.get(uid)
        assert "spec_acceptance_ema" not in router.snapshot()
        assert not any(k.startswith("spec")
                       for k in eng.telemetry.percentiles())
