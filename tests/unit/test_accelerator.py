"""Accelerator abstraction tests (reference tests/accelerator)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.accelerator import (CpuAccelerator, DeepSpeedAccelerator,
                                       get_accelerator, set_accelerator)


@pytest.fixture(autouse=True)
def _cpu_accel():
    prev = get_accelerator()
    set_accelerator(CpuAccelerator())
    yield
    set_accelerator(prev)


def test_singleton_and_abc():
    acc = get_accelerator()
    assert isinstance(acc, DeepSpeedAccelerator)
    assert acc is get_accelerator()


def test_device_mgmt():
    acc = get_accelerator()
    assert acc.is_available()
    assert acc.device_count() == len(jax.devices())
    assert acc.device_name() == "cpu"
    assert acc.device_name(3) == "cpu:3"
    assert acc.device(0) is jax.devices()[0]
    acc.synchronize()


def test_rng():
    acc = get_accelerator()
    acc.manual_seed(42)
    assert acc.initial_seed() == 42
    k1 = acc.split_key()
    k2 = acc.split_key()
    a = jax.random.normal(k1, (4,))
    b = jax.random.normal(k2, (4,))
    assert not np.allclose(a, b)


def test_streams_events_noop():
    acc = get_accelerator()
    with acc.stream():
        x = jnp.ones((8,)) * 2
    ev = acc.Event()
    ev.record(value=x)
    ev.synchronize()
    assert ev.query()


def test_memory_and_dtypes():
    acc = get_accelerator()
    assert acc.total_memory() > 0
    assert acc.is_bf16_supported()
    assert jnp.bfloat16 in acc.supported_dtypes()


def test_op_builder_dispatch():
    acc = get_accelerator()
    b = acc.create_op_builder("quantizer")
    mod = b.load()
    assert hasattr(mod, "quantize_int8_blockwise") or mod is not None
    assert acc.get_op_builder("nonexistent") is None


def test_communication_backend():
    assert get_accelerator().communication_backend_name() == "xla"


def test_env_override(monkeypatch):
    import deepspeed_tpu.accelerator.real_accelerator as ra
    monkeypatch.setattr(ra, "_accelerator", None)
    monkeypatch.setenv("DS_ACCELERATOR", "cpu")
    assert isinstance(ra.get_accelerator(), CpuAccelerator)
    monkeypatch.setattr(ra, "_accelerator", None)
    monkeypatch.setenv("DS_ACCELERATOR", "bogus")
    with pytest.raises(ValueError):
        ra.get_accelerator()
