"""Sparse attention, eigenvalue, PLD, MoQ, OnDevice, hybrid engine tests
(reference tests/unit/ops/sparse_attention + runtime misc coverage)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import GPT2, GPT2Config
from deepspeed_tpu.ops.sparse_attention import (
    BigBirdSparsityConfig, BSLongformerSparsityConfig, DenseSparsityConfig,
    FixedSparsityConfig, SparseSelfAttention, sparse_attention)
from deepspeed_tpu.runtime.eigenvalue import Eigenvalue
from deepspeed_tpu.runtime.progressive_layer_drop import ProgressiveLayerDrop
from deepspeed_tpu.runtime.quantize import Quantizer
from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.init_on_device import (OnDevice, abstract_init,
                                                materialize)

# compile-heavy: excluded from the fast core set (pytest -m 'not slow')
pytestmark = pytest.mark.slow


class TestSparsityConfigs:
    def test_dense_layout_full(self):
        lay = DenseSparsityConfig(num_heads=2, block=16).make_layout(64)
        assert lay.shape == (2, 4, 4) and lay.all()

    def test_fixed_local_window(self):
        cfg = FixedSparsityConfig(num_heads=1, block=16, num_local_blocks=2,
                                  num_global_blocks=1,
                                  attention="unidirectional")
        lay = cfg.make_layout(128)  # 8 blocks
        # block 3 (window 1): local blocks 2..3, global = last of window 0
        assert lay[0, 3, 2] and lay[0, 3, 3]
        assert lay[0, 3, 1]          # global: last block of window 0
        assert not lay[0, 3, 4]      # causal: no future
        assert not lay[0, 3, 0]

    def test_bigbird_window_and_global(self):
        cfg = BigBirdSparsityConfig(num_heads=1, block=16,
                                    num_sliding_window_blocks=3,
                                    num_global_blocks=1,
                                    num_random_blocks=1)
        lay = cfg.make_layout(128)
        assert lay[0, 0, :].all() and lay[0, :, 0].all()  # global
        for q in range(1, 7):
            assert lay[0, q, q] and lay[0, q, q - 1]       # window

    def test_longformer_global_indices(self):
        cfg = BSLongformerSparsityConfig(num_heads=1, block=16,
                                         global_block_indices=(2,))
        lay = cfg.make_layout(128)
        assert lay[0, 2, :].all() and lay[0, :, 2].all()

    def test_indivisible_raises(self):
        with pytest.raises(ValueError):
            FixedSparsityConfig(num_heads=1, block=16).make_layout(100)


class TestSparseAttention:
    def test_dense_layout_matches_full_attention(self):
        B, T, H, hd = 2, 64, 2, 16
        rs = np.random.RandomState(0)
        q, k, v = (jnp.asarray(rs.randn(B, T, H, hd), jnp.float32)
                   for _ in range(3))
        lay = DenseSparsityConfig(num_heads=H, block=16).make_layout(T)
        out = sparse_attention(q, k, v, lay, 16, causal=True)
        # reference: plain causal attention
        scores = jnp.einsum("bthd,bshd->bhts", q, k) / np.sqrt(hd)
        causal = jnp.tril(jnp.ones((T, T), bool))
        probs = jax.nn.softmax(jnp.where(causal[None, None], scores,
                                         -1e30), axis=-1)
        ref = jnp.einsum("bhts,bshd->bthd", probs, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_blocked_mask_zeroes_blocked_scores(self):
        """Tokens must not attend outside their allowed blocks."""
        B, T, H, hd = 1, 64, 1, 8
        rs = np.random.RandomState(1)
        q = jnp.asarray(rs.randn(B, T, H, hd), jnp.float32)
        k, v = q, jnp.asarray(rs.randn(B, T, H, hd), jnp.float32)
        # only diagonal blocks allowed
        lay = np.eye(4, dtype=bool)[None]
        out = sparse_attention(q, k, v, lay, 16)
        # per-block attention computed separately must match
        for blk in range(4):
            sl = slice(blk * 16, (blk + 1) * 16)
            sub = sparse_attention(q[:, sl], k[:, sl], v[:, sl],
                                   np.ones((1, 1, 1), bool), 16)
            np.testing.assert_allclose(np.asarray(out[:, sl]),
                                       np.asarray(sub), rtol=1e-5,
                                       atol=1e-5)

    def test_module_density(self):
        att = SparseSelfAttention(FixedSparsityConfig(
            num_heads=2, block=16, num_local_blocks=2,
            attention="unidirectional"), causal=True)
        assert att.density(128) < 0.6
        q = jnp.ones((1, 128, 2, 8), jnp.float32)
        out = att(q, q, q)
        assert out.shape == (1, 128, 2, 8)


class TestEigenvalue:
    def test_quadratic_exact(self):
        """For loss = 0.5 x^T A x the dominant eigenvalue is max |eig A|."""
        rs = np.random.RandomState(0)
        M = rs.randn(8, 8)
        A = (M + M.T) / 2
        true = np.abs(np.linalg.eigvalsh(A)).max()

        def loss(params, batch):
            x = params["x"]
            return 0.5 * x @ jnp.asarray(A) @ x

        eig, vec = Eigenvalue(max_iter=200, tol=1e-5).compute_eigenvalue(
            loss, {"x": jnp.asarray(rs.randn(8), jnp.float32)}, None)
        assert abs(eig - true) / true < 0.05

    def test_model_eigenvalue_positive(self):
        cfg = GPT2Config(n_layer=1, n_head=2, d_model=16, max_seq_len=16,
                         vocab_size=32, remat=False, dtype="float32")
        model = GPT2(cfg)
        params = model.init(jax.random.key(0))
        batch = {"input_ids": np.zeros((2, 16), np.int32)}
        eig, _ = Eigenvalue(max_iter=20, tol=1e-2).compute_eigenvalue(
            lambda p, b: model.loss(p, b, train=False), params, batch)
        assert eig > 0


class TestPLDAndMoQ:
    def test_pld_schedule_decays_to_theta(self):
        pld = ProgressiveLayerDrop(theta=0.5, gamma=0.01)
        assert pld.update_state(0) == 1.0
        mid = pld.update_state(100)
        assert 0.5 < mid < 1.0
        assert abs(pld.update_state(10**6) - 0.5) < 1e-6
        assert pld.get_state()["progressive_layer_drop"]

    def test_moq_bit_schedule(self):
        q = Quantizer(q_target_bits=8, q_start_bits=12, q_period=10)
        bits = [q.update(s) for s in range(0, 500, 10)]
        assert bits[0] == 12
        assert min(bits) == 8
        assert sorted(bits, reverse=True) == bits  # monotone decreasing

    def test_moq_quantize_tree(self):
        q = Quantizer(q_target_bits=4, q_start_bits=4, q_period=1)
        q.current_bits = 4
        tree = {"w": jnp.asarray(np.random.RandomState(0).randn(32, 32),
                                 jnp.float32),
                "b": jnp.ones((32,), jnp.float32)}
        out = q.quantize(tree)
        assert len(np.unique(np.asarray(out["w"]))) <= 16
        np.testing.assert_array_equal(np.asarray(out["b"]),
                                      np.asarray(tree["b"]))  # 1-D skipped


class TestOnDevice:
    def test_abstract_init_no_memory(self):
        cfg = GPT2Config(n_layer=2, n_head=2, d_model=32, max_seq_len=32,
                         vocab_size=64)
        abstract = abstract_init(GPT2(cfg))
        leaf = jax.tree.leaves(abstract)[0]
        assert isinstance(leaf, jax.ShapeDtypeStruct)

    def test_materialize_matches_init(self):
        cfg = GPT2Config(n_layer=1, n_head=2, d_model=16, max_seq_len=16,
                         vocab_size=32, dtype="float32")
        model = GPT2(cfg)
        a = materialize(model, jax.random.key(0))
        b = model.init(jax.random.key(0))
        jax.tree.map(
            lambda x, y: np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), rtol=1e-6, atol=1e-7),
            a, b)

    def test_context_intercepts_init(self):
        cfg = GPT2Config(n_layer=1, n_head=2, d_model=16, max_seq_len=16,
                         vocab_size=32)
        model = GPT2(cfg)
        assert not OnDevice.is_active()
        with OnDevice(model, device="meta"):
            assert OnDevice.is_active()
            abstract = model.init(jax.random.key(0))
            assert all(isinstance(l, jax.ShapeDtypeStruct)
                       for l in jax.tree.leaves(abstract))
        assert not OnDevice.is_active()
        real = model.init(jax.random.key(0))  # restored
        assert not isinstance(jax.tree.leaves(real)[0],
                              jax.ShapeDtypeStruct)


class TestHybridEngine:
    def test_train_and_generate_share_weights(self):
        from deepspeed_tpu.runtime.hybrid_engine import DeepSpeedHybridEngine
        groups.reset()
        cfg = GPT2Config(n_layer=2, n_head=2, d_model=32, max_seq_len=64,
                         vocab_size=64, remat=False, dtype="float32")
        engine = DeepSpeedHybridEngine(
            model=GPT2(cfg),
            config={"train_micro_batch_size_per_gpu": 1,
                    "optimizer": {"type": "AdamW", "params": {"lr": 5e-3}},
                    "steps_per_print": 0},
            inference_config={"prompt_bucket": 16, "dtype": "float32"})
        data = (np.arange(engine.config.train_batch_size * 32)
                .reshape(-1, 32) % 64).astype(np.int32)
        out_before = engine.generate(data[:1, :8], max_new_tokens=4,
                                     temperature=0.0)
        for _ in range(10):
            engine.train_batch({"input_ids": data})
        out_after = engine.generate(data[:1, :8], max_new_tokens=4,
                                    temperature=0.0)
        # training a memorizable ramp changes the generation
        ids = data[0, :8]
        # after training on the ramp, generation continues it
        expect = (np.arange(8, 12)) % 64
        assert (out_after[0] == expect).sum() >= 3, (out_after, expect)
        assert not np.array_equal(out_before, out_after)


class TestDeepSpeedTransformerLayer:
    def _layer(self, **kw):
        from deepspeed_tpu.ops.transformer import (
            DeepSpeedTransformerConfig, DeepSpeedTransformerLayer)
        cfg = DeepSpeedTransformerConfig(hidden_size=32, heads=4, **kw)
        layer = DeepSpeedTransformerLayer(cfg)
        params = layer.init(jax.random.key(0))
        return layer, params

    def test_forward_shapes_pre_and_post_ln(self):
        x = jnp.asarray(np.random.RandomState(0).randn(2, 16, 32),
                        jnp.float32)
        for pre in (True, False):
            layer, params = self._layer(pre_layer_norm=pre)
            out = layer(params, x)
            assert out.shape == x.shape
            assert np.isfinite(np.asarray(out)).all()

    def test_mask_blocks_attention(self):
        """Padding positions must not affect valid positions' outputs."""
        layer, params = self._layer()
        rs = np.random.RandomState(1)
        x = jnp.asarray(rs.randn(1, 8, 32), jnp.float32)
        mask = jnp.asarray([[1, 1, 1, 1, 0, 0, 0, 0]], bool)
        out_a = layer(params, x, mask=mask)
        x_b = x.at[:, 4:].set(jnp.asarray(rs.randn(1, 4, 32)))
        out_b = layer(params, x_b, mask=mask)
        np.testing.assert_allclose(np.asarray(out_a[:, :4]),
                                   np.asarray(out_b[:, :4]), rtol=1e-5,
                                   atol=1e-5)

    def test_differentiable(self):
        layer, params = self._layer()
        x = jnp.asarray(np.random.RandomState(2).randn(1, 8, 32),
                        jnp.float32)
        g = jax.grad(lambda p: jnp.sum(layer(p, x) ** 2))(params)
        assert float(jnp.abs(g["wqkv"]).max()) > 0
