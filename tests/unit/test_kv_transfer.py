"""Disaggregated prefill/decode serving (tier-1): the KV handoff wire
format (framing, CRC, typed corruption rejection), the in-process and
DCN transports, engine-level export/import with colocated byte-identity
and pool-closure audits, the router's phase-aware dispatch (1P+1D
greedy streams byte-identical to colocated, including a
prefix-cache-hit prompt), TTFT accounting spanning the handoff (one
sample per request), and the chaos paths: retryable kv_stream /
kv_import faults, decode-replica death mid-transfer -> front-of-queue
byte-identical replay, and a cancel while parked awaiting handoff with
both replicas' accounting closed.

Engines follow the test_router.py fast pattern: tiny GPT2,
module-cached params + a module-cached P/D engine pair for
clean-completion tests."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.autotuning import kernel_dispatch
from deepspeed_tpu.inference.v2 import (DeadlineExceeded,
                                        InferenceEngineV2, Router)
from deepspeed_tpu.inference.v2 import kv_transfer
from deepspeed_tpu.inference.v2.kv_transfer import (DcnRingTransport,
                                                    InProcQueueTransport,
                                                    KVTransferError,
                                                    KVWireError,
                                                    pack_handoff,
                                                    unpack_handoff)
from deepspeed_tpu.inference.v2.replica import Replica
from deepspeed_tpu.models import GPT2, GPT2Config
from deepspeed_tpu.monitor.tag_schema import TAG_SCHEMA
from deepspeed_tpu.utils import fault_injection, groups


@pytest.fixture(autouse=True)
def _pristine_dispatch(tmp_path, monkeypatch):
    monkeypatch.setenv("DSTPU_AUTOTUNE_CACHE",
                       str(tmp_path / "kernel_autotune.json"))
    monkeypatch.delenv("DSTPU_AUTOTUNE", raising=False)
    kernel_dispatch.reset()
    fault_injection.reset()
    yield
    fault_injection.reset()
    kernel_dispatch.reset()


_CFG = GPT2Config(n_layer=2, n_head=4, d_model=64, max_seq_len=128,
                  vocab_size=256, remat=False, dtype="float32")
_PARAMS = None


def _params():
    global _PARAMS
    if _PARAMS is None:
        _PARAMS = GPT2(_CFG).init(jax.random.key(0))
    return _PARAMS


_BASE = {"dtype": "float32", "kv_block_size": 8, "prompt_bucket": 16,
         "max_batch_size": 2, "splitfuse_tokens": 16,
         "decode_steps_per_dispatch": 2,
         "prefix_cache_min_match": 1}


def _engine(**kw):
    groups.reset()
    return InferenceEngineV2(GPT2(_CFG), params=_params(),
                             config=dict(_BASE, **kw))


# Clean-completion tests share one module-cached P/D pair (the prefill
# engine carries a prefix cache so the handoff release's retire/insert
# path is exercised; the decode engine is plain so its pool audit is
# the strict free==total form). Every request leaves through get() or
# a typed exit, so the engines stay reusable; each test builds its OWN
# Replica/Router wrappers.
_PAIR = None
_REF = None


def _pair():
    global _PAIR
    if _PAIR is None:
        _PAIR = (_engine(prefix_cache=True), _engine())
    return _PAIR


def _prompts(seed, n, lo=6, hi=20):
    rs = np.random.RandomState(seed)
    return [rs.randint(1, 255, size=rs.randint(lo, hi)).astype(np.int32)
            for _ in range(n)]


def _refs():
    """Colocated greedy reference for _prompts(3, 4) at max_new 8,
    computed on the plain decode engine (which ends clean)."""
    global _REF
    if _REF is None:
        _REF = [_pair()[1].generate_all([p], max_new_tokens=8)[0]
                for p in _prompts(3, 4)]
    return _REF


def _run(router, max_rounds=400):
    rounds = 0
    while router.has_work:
        router.step()
        rounds += 1
        assert rounds < max_rounds, "router failed to drain"
    return rounds


def _pool_closed(eng):
    alloc = eng.state_mgr.allocator
    tree = eng.prefix_cache.tree_blocks if eng.prefix_cache else 0
    assert alloc.free_blocks + tree == alloc.total_blocks, (
        f"leaked blocks: free={alloc.free_blocks} tree={tree} "
        f"total={alloc.total_blocks}")


def _disagg_router(**kw):
    P, D = _pair()
    reps = [Replica("p0", P, role="prefill"),
            Replica("d0", D, role="decode")]
    return Router(reps, **kw), reps


def _tree():
    return {"k": [np.arange(12, dtype=np.float32).reshape(3, 4)],
            "v": [np.full((3, 4), 0.5, np.float32)]}


_STATE = {"uid": 3, "prompt": [1, 2], "generated": [9],
          "cached_len": 0, "max_new_tokens": 8, "eos_token_id": -1,
          "temperature": 0.0, "top_k": 0, "klass": 1, "t_submit": 12.5}


# ---------------------------------------------------------------------------
# wire format
# ---------------------------------------------------------------------------

class TestWireFormat:
    def test_roundtrip(self):
        tree = _tree()
        state, flat = unpack_handoff(pack_handoff(_STATE, tree))
        assert state == _STATE
        assert set(flat) == {"k/0", "v/0"}
        np.testing.assert_array_equal(flat["k/0"], tree["k"][0])
        np.testing.assert_array_equal(flat["v/0"], tree["v"][0])

    def test_bfloat16_roundtrip(self):
        """npz loses extension dtypes (bfloat16 loads back as raw void
        bytes): the wire carries a dtype map and unpack views the bytes
        back, so a bfloat16-serving fleet hands off losslessly."""
        bf = np.asarray(jnp.arange(24, dtype=jnp.bfloat16).reshape(2, 3, 4))
        state, flat = unpack_handoff(
            pack_handoff(_STATE, {"k": [bf], "v": [bf * 0.5]}))
        assert state == _STATE
        assert flat["k/0"].dtype == np.dtype("bfloat16")
        np.testing.assert_array_equal(flat["k/0"].view(np.uint16),
                                      bf.view(np.uint16))
        np.testing.assert_array_equal(
            flat["v/0"].view(np.uint16),
            np.asarray(bf * 0.5).view(np.uint16))

    def test_truncated_rejected(self):
        payload = pack_handoff(_STATE, _tree())
        with pytest.raises(KVWireError, match="truncated"):
            unpack_handoff(payload[:8])
        with pytest.raises(KVWireError, match="truncated"):
            unpack_handoff(b"")
        with pytest.raises(KVWireError, match="body length"):
            unpack_handoff(payload[:-3])

    def test_bad_magic_and_version_rejected(self):
        payload = bytearray(pack_handoff(_STATE, _tree()))
        bad = bytearray(payload)
        bad[:4] = b"NOPE"
        with pytest.raises(KVWireError, match="magic"):
            unpack_handoff(bytes(bad))
        bad = bytearray(payload)
        bad[4] = 0xEE                      # version field
        with pytest.raises(KVWireError, match="version"):
            unpack_handoff(bytes(bad))

    def test_crc_flip_rejected(self):
        payload = bytearray(pack_handoff(_STATE, _tree()))
        payload[-1] ^= 0xFF
        with pytest.raises(KVWireError):
            unpack_handoff(bytes(payload))

    def test_missing_descriptor_state_rejected(self):
        """A well-formed serialization image that carries no handoff
        descriptor is not a handoff — refuse it, typed."""
        import io
        import struct
        import zlib

        from deepspeed_tpu.runtime.checkpoint_engine import \
            serialization as ser
        body_io = io.BytesIO()
        ser.save_file(body_io, _tree())    # no extra_meta
        body = body_io.getvalue()
        payload = kv_transfer._HEADER.pack(
            kv_transfer.MAGIC, kv_transfer.WIRE_VERSION, len(body),
            zlib.crc32(body) & 0xFFFFFFFF) + body
        with pytest.raises(KVWireError, match="descriptor"):
            unpack_handoff(payload)


class TestTransports:
    def test_inproc_queue_fifo_and_counters(self):
        t = InProcQueueTransport()
        t.send(b"abc")
        t.send(b"defg")
        assert t.sent_bytes == 7
        assert t.recv() == b"abc"
        assert t.recv() == b"defg"
        with pytest.raises(KVTransferError, match="empty"):
            t.recv()

    def test_dcn_transport_needs_multi_process(self):
        with pytest.raises(KVTransferError, match="multi-process"):
            DcnRingTransport().send(b"abc")


# ---------------------------------------------------------------------------
# engine-level handoff
# ---------------------------------------------------------------------------

def _prefill_until_first_token(eng, prompt, max_new=8, uid=None):
    uid = eng.put(prompt, max_new_tokens=max_new, eos_token_id=-1,
                  uid=uid)
    eng.hold_decode(uid)
    for _ in range(64):
        eng.step()
        seq = eng.state_mgr._seqs.get(uid)
        if seq is not None and seq.generated:
            return uid
    raise AssertionError("prefill never posted a first token")


class TestEngineHandoff:
    def test_export_import_byte_identity(self):
        P, D = _pair()
        prompt = _prompts(3, 4)[0]
        want = _refs()[0]
        uid = _prefill_until_first_token(P, prompt, uid=7001)
        payload = kv_transfer.export_sequence(P, uid)
        assert kv_transfer.import_sequence(D, payload) == uid
        P.release_handoff(uid)
        _pool_closed(P)
        while not D.is_done(uid):
            D.step()
        np.testing.assert_array_equal(np.asarray(D.get(uid)),
                                      np.asarray(want))
        _pool_closed(D)

    def test_export_before_first_token_rejected(self):
        P, _ = _pair()
        rs = np.random.RandomState(9)
        prompt = rs.randint(1, 255, size=40).astype(np.int32)
        uid = P.put(prompt, max_new_tokens=4, uid=7002)
        P.hold_decode(uid)
        P.step()                # admits + first chunk: mid-prefill
        assert P.state_mgr._seqs[uid].generated == []
        with pytest.raises(RuntimeError, match="first token"):
            P.export_handoff(uid)
        assert P.cancel(uid) is True
        _pool_closed(P)

    def test_duplicate_import_rejected(self):
        P, D = _pair()
        uid = _prefill_until_first_token(P, _prompts(3, 4)[2], uid=7003)
        payload = kv_transfer.export_sequence(P, uid)
        kv_transfer.import_sequence(D, payload)
        with pytest.raises(RuntimeError, match="already live"):
            kv_transfer.import_sequence(D, payload)
        P.release_handoff(uid)
        assert D.cancel(uid) is True
        _pool_closed(P)
        _pool_closed(D)

    def test_layout_mismatch_rejected(self):
        """The gpt2-vs-llama guard: a payload whose per-block shape
        does not match the importing engine's cache is refused before
        any allocation or scatter."""
        P, _ = _pair()
        other = _engine(kv_block_size=16)  # different block shape
        uid = _prefill_until_first_token(P, _prompts(3, 4)[3], uid=7004)
        payload = kv_transfer.export_sequence(P, uid)
        state, flat = unpack_handoff(payload)
        with pytest.raises(KVWireError, match="layout"):
            other.import_handoff(state, flat)
        alloc = other.state_mgr.allocator
        assert alloc.free_blocks == alloc.total_blocks
        assert P.cancel(uid) is True
        _pool_closed(P)

    def test_cancel_parked_sequence_closes_pool(self):
        P, _ = _pair()
        uid = _prefill_until_first_token(P, _prompts(3, 4)[0], uid=7005)
        assert uid in P._decode_hold
        assert P.cancel(uid) is True
        assert uid not in P._decode_hold
        _pool_closed(P)


# ---------------------------------------------------------------------------
# router: phase-aware dispatch
# ---------------------------------------------------------------------------

class TestDisaggRouter:
    def test_auto_resolution_and_validation(self):
        P, D = _pair()
        r_colo = Router([Replica("a", P), Replica("b", D)])
        assert r_colo._disagg_on() is False
        assert "roles" not in r_colo.snapshot()
        r_dis, _ = _disagg_router()
        assert r_dis._disagg_on() is True
        r_off, _ = _disagg_router(config={"disaggregate": False})
        assert r_off._disagg_on() is False
        with pytest.raises(ValueError, match="prefill"):
            Router([Replica("a", P, role="prefill")],
                   config={"disaggregate": True})
        with pytest.raises(ValueError, match="role"):
            Replica("x", P, role="verifier")

    def test_greedy_byte_identity_and_single_ttft_sample(self):
        """The tentpole invariant: 1P+1D greedy streams byte-identical
        to colocated, TTFT is sampled exactly once per request (on the
        prefill side), the handoff counters account every stream, and
        both pools close."""
        router, reps = _disagg_router()
        want = _refs()
        uids = [router.put(p, max_new_tokens=8)
                for p in _prompts(3, 4)]
        _run(router)
        for uid, w in zip(uids, want):
            np.testing.assert_array_equal(np.asarray(router.get(uid)),
                                          np.asarray(w))
        snap = router.snapshot()
        assert snap["handoffs"] == 4
        assert snap["kv_stream_bytes"] > 0
        assert snap["kv_stream_retries"] == 0
        assert snap["completed"] == 4 and snap["admitted"] == 4
        # exactly one TTFT sample per request, anchored at submit
        assert len(router._cstat(0)["ttft_ms"]) == 4
        assert snap["roles"] == {"p0": "prefill", "d0": "decode"}
        assert snap["prefill_inflight"] == 0
        assert snap["decode_inflight"] == 0
        P, D = _pair()
        _pool_closed(P)
        _pool_closed(D)
        # the decode engine's own telemetry saw the handoffs arrive
        assert D.telemetry.percentiles()["handoffs_in"] >= 4

    def test_prefix_hit_prompt_byte_identity(self):
        """A handed-off sequence whose prompt HITS the prefill
        replica's prefix cache (radix-claimed shared blocks in its
        table) must still stream byte-identically — the export gathers
        claimed blocks read-only and the import re-owns them."""
        router, _ = _disagg_router()
        prompt = _prompts(3, 4)[0]
        want = _refs()[0]
        u1 = router.put(prompt, max_new_tokens=8)
        _run(router)
        # release_handoff retired the verified prompt into p0's prefix
        # cache; the SAME prompt now prefills through a radix hit
        u2 = router.put(prompt, max_new_tokens=8)
        _run(router)
        np.testing.assert_array_equal(np.asarray(router.get(u1)),
                                      np.asarray(want))
        np.testing.assert_array_equal(np.asarray(router.get(u2)),
                                      np.asarray(want))
        assert router.snapshot()["handoffs"] == 2
        P, D = _pair()
        _pool_closed(P)
        _pool_closed(D)

    def test_disagg_tags_registered(self):
        for tag in ("Serve/Router/handoffs", "Serve/Router/kv_stream_bytes",
                    "Serve/Router/kv_stream_ms",
                    "Serve/Router/prefill_inflight",
                    "Serve/Router/decode_inflight"):
            assert tag in TAG_SCHEMA


# ---------------------------------------------------------------------------
# chaos: stream/import faults, death mid-transfer, parked cancel
# ---------------------------------------------------------------------------

@pytest.mark.chaos
class TestHandoffChaos:
    def test_kv_stream_fault_retries_next_round(self):
        """An injected stream failure moves nothing: the sequence stays
        parked on the prefill side and the next round's retry streams
        it — output byte-identical, one retry counted."""
        router, _ = _disagg_router()
        fault_injection.arm("kv_stream", fails=1)
        uid = router.put(_prompts(3, 4)[1], max_new_tokens=8)
        _run(router)
        np.testing.assert_array_equal(np.asarray(router.get(uid)),
                                      np.asarray(_refs()[1]))
        snap = router.snapshot()
        assert snap["kv_stream_retries"] == 1
        assert snap["handoffs"] == 1
        assert snap["failovers"] == 0
        P, D = _pair()
        _pool_closed(P)
        _pool_closed(D)

    def test_kv_import_fault_retries_next_round(self):
        """Same contract on the import half: the point fires before any
        decode-side mutation, so the retry re-exports and re-streams
        from unchanged prefill state."""
        router, _ = _disagg_router()
        fault_injection.arm("kv_import", fails=1)
        uid = router.put(_prompts(3, 4)[2], max_new_tokens=8)
        _run(router)
        np.testing.assert_array_equal(np.asarray(router.get(uid)),
                                      np.asarray(_refs()[2]))
        snap = router.snapshot()
        assert snap["kv_stream_retries"] == 1
        assert snap["handoffs"] == 1
        P, D = _pair()
        _pool_closed(P)
        _pool_closed(D)

    def test_decode_death_mid_transfer_replays_byte_identical(self):
        """Decode replica dies importing the payload: the request is
        re-enqueued at the FRONT, the fleet degrades to colocated, the
        replay re-prefills on the survivor and the output is
        byte-identical; both pools close and accounting stays zero-drop
        (the wire payload it was mid-importing is discarded — the
        import fires before any decode-side state moves)."""
        router, (P_rep, D_rep) = _disagg_router()
        prompt = _prompts(3, 4)[3]
        want = _refs()[3]
        uid = router.put(prompt, max_new_tokens=8)
        # orchestrate up to the brink of the handoff OUTSIDE router
        # rounds so the armed death lands exactly at D's import fire
        router._disagg = router._disagg_on()
        for rep in router.replicas:
            rep.set_disaggregated(True)
        router._dispatch(router._now())
        for _ in range(64):
            if P_rep.handoff_ready():
                break
            P_rep.engine.step()
        assert P_rep.handoff_ready() == [uid]
        # this round: P's step() fires replica_death once (consumed by
        # skip=1), then _do_handoffs reaches D's import fire -> injects
        fault_injection.arm("replica_death", fails=1, skip=1)
        router.step()
        assert D_rep.dead
        assert not P_rep.dead
        req = router._reqs[uid]
        assert req.replays == 1
        _run(router)                       # colocated replay on P
        np.testing.assert_array_equal(np.asarray(router.get(uid)),
                                      np.asarray(want))
        snap = router.snapshot()
        assert snap["failovers"] == 1
        assert snap["replayed"] == 1
        assert snap["handoffs"] == 0
        assert (snap["completed"] + snap["expired"] + snap["shed"]
                == snap["admitted"] == 1)
        # exactly one TTFT sample despite the replay
        assert len(router._cstat(0)["ttft_ms"]) == 1
        P, D = _pair()
        _pool_closed(P)
        _pool_closed(D)

    def test_cancel_while_parked_awaiting_handoff(self):
        """Deadline expiry of a sequence parked for handoff (decode
        side back-pressured): the cancel runs on the PREFILL side
        through the flush/unref path — both replicas' accounting
        closes, nothing streamed."""
        router, (P_rep, D_rep) = _disagg_router()
        P, D = _pair()
        # back-pressure: fill the decode engine's slots directly so
        # _pick_decode finds no capacity and the sequence stays parked
        busy = [D.put(p, max_new_tokens=48, eos_token_id=-1, uid=u)
                for p, u in zip(_prompts(5, 2), (9101, 9102))]
        for _ in range(2):
            D.step()
        uid = router.put(_prompts(3, 4)[0], max_new_tokens=8)
        router.step()
        for _ in range(64):
            if P_rep.handoff_ready():
                break
            P_rep.engine.step()
        router.step()                      # handoff attempt: no capacity
        assert router._reqs[uid].state == "inflight"
        assert router.snapshot()["handoffs"] == 0
        # now the deadline passes while still parked
        router._reqs[uid].deadline_ms = 1e-9
        router.step()
        with pytest.raises(DeadlineExceeded):
            router.get(uid)
        assert uid not in P._decode_hold
        _pool_closed(P)
        snap = router.snapshot()
        assert snap["expired"] == 1 and snap["handoffs"] == 0
        assert (snap["completed"] + snap["expired"] + snap["shed"]
                == snap["admitted"] == 1)
        # drain the back-pressure load; the decode pool closes too
        while not all(D.is_done(u) for u in busy):
            D.step()
        for u in busy:
            D.get(u)
        _pool_closed(D)
