import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.optimizers import (FusedAdam, FusedLamb, FusedLion,
                                          FusedAdagrad, SGD, build_optimizer)
from deepspeed_tpu.runtime.lr_schedules import (WarmupLR, WarmupDecayLR,
                                                WarmupCosineLR, OneCycle,
                                                build_scheduler)
from deepspeed_tpu.runtime.fp16.loss_scaler import (DynamicLossScaler,
                                                    grads_finite)


def _params():
    return {"w": jnp.ones((4, 8)), "b": jnp.zeros((8,))}


def _grads():
    return {"w": jnp.full((4, 8), 0.5), "b": jnp.full((8,), -0.25)}


@pytest.mark.parametrize("opt", [
    FusedAdam(lr=1e-2), FusedAdam(lr=1e-2, weight_decay=0.01),
    FusedAdam(lr=1e-2, adam_w_mode=False, weight_decay=0.01),
    FusedLamb(lr=1e-2), FusedLion(lr=1e-3), FusedAdagrad(lr=1e-2),
    SGD(lr=1e-2, momentum=0.9)])
def test_optimizer_step_moves_params(opt):
    p = _params()
    s = opt.init(p)
    p2, s2 = opt.update(_grads(), s, p)
    assert int(s2["step"]) == 1
    assert not np.allclose(np.asarray(p2["w"]), np.asarray(p["w"]))
    # gradient descent direction on w (positive grads -> weights shrink)
    assert float(p2["w"].mean()) < float(p["w"].mean())


def test_adam_matches_reference_formula():
    opt = FusedAdam(lr=0.1, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0)
    p = {"w": jnp.asarray([1.0])}
    g = {"w": jnp.asarray([0.5])}
    s = opt.init(p)
    p2, _ = opt.update(g, s, p)
    m = 0.1 * 0.5
    v = 0.001 * 0.25
    mh, vh = m / (1 - 0.9), v / (1 - 0.999)
    expect = 1.0 - 0.1 * mh / (np.sqrt(vh) + 1e-8)
    np.testing.assert_allclose(float(p2["w"][0]), expect, rtol=1e-6)


def test_adam_jits_with_traced_lr():
    opt = FusedAdam(lr=1e-3)
    p = _params()
    s = opt.init(p)
    f = jax.jit(lambda g, s, p, lr: opt.update(g, s, p, lr=lr))
    p2, s2 = f(_grads(), s, p, jnp.float32(0.01))
    p3, _ = f(_grads(), s2, p2, jnp.float32(0.02))  # no recompile for new lr
    assert np.isfinite(np.asarray(p3["w"])).all()


def test_build_optimizer_registry():
    opt = build_optimizer("AdamW", {"lr": 1e-4, "weight_decay": 0.01})
    assert isinstance(opt, FusedAdam) and opt.adam_w_mode
    with pytest.raises(ValueError):
        build_optimizer("muon", {})


def test_warmup_lr():
    s = WarmupLR(warmup_min_lr=0.0, warmup_max_lr=1.0, warmup_num_steps=10,
                 warmup_type="linear")
    assert float(s(0)) == 0.0
    assert abs(float(s(5)) - 0.5) < 1e-6
    assert float(s(100)) == 1.0


def test_warmup_decay_lr():
    s = WarmupDecayLR(total_num_steps=100, warmup_max_lr=1.0,
                      warmup_num_steps=10, warmup_type="linear")
    assert abs(float(s(10)) - 1.0) < 1e-6
    assert float(s(100)) == 0.0
    assert 0.0 < float(s(55)) < 1.0


def test_warmup_cosine_lr():
    s = WarmupCosineLR(total_num_steps=100, warmup_num_steps=10, lr=1.0)
    assert abs(float(s(10)) - 1.0) < 1e-2
    assert float(s(100)) <= 0.01


def test_one_cycle():
    s = OneCycle(cycle_min_lr=0.1, cycle_max_lr=1.0,
                 cycle_first_step_size=10)
    assert abs(float(s(0)) - 0.1) < 1e-6
    assert abs(float(s(10)) - 1.0) < 1e-6
    assert abs(float(s(20)) - 0.1) < 1e-6


def test_scheduler_registry():
    s = build_scheduler("WarmupLR", {"warmup_num_steps": 5})
    assert callable(s)
    with pytest.raises(ValueError):
        build_scheduler("Nope", {})


def test_scheduler_stateful_surface():
    s = build_scheduler("WarmupLR",
                        {"warmup_max_lr": 1.0, "warmup_num_steps": 10,
                         "warmup_type": "linear"})
    s.step()
    s.step()
    assert s.state_dict() == {"last_batch_iteration": 1}
    assert s.get_lr()[0] > 0


def test_dynamic_loss_scaler():
    sc = DynamicLossScaler(init_scale=16.0, scale_window=2, min_scale=1.0,
                           delayed_shift=1)
    st = sc.init_state()
    # overflow halves
    st = sc.update(st, jnp.asarray(True))
    assert float(st["scale"]) == 8.0
    # two good steps double
    st = sc.update(st, jnp.asarray(False))
    st = sc.update(st, jnp.asarray(False))
    assert float(st["scale"]) == 16.0
    assert int(st["good_steps"]) == 0


def test_grads_finite():
    assert bool(grads_finite({"a": jnp.ones(3)}))
    assert not bool(grads_finite({"a": jnp.asarray([1.0, jnp.inf])}))
    assert not bool(grads_finite({"a": jnp.asarray([jnp.nan])}))


class TestAdamMomentsDtype:
    """moments_dtype: m/v stored low-precision, update computed fp32
    (the 1.3B-on-one-chip memory lever)."""

    def test_moments_stored_bf16(self):
        opt = FusedAdam(lr=1e-2, moments_dtype="bfloat16")
        p = _params()
        s = opt.init(p)
        assert all(l.dtype == jnp.bfloat16
                   for l in jax.tree.leaves(s["m"]))
        assert all(l.dtype == jnp.bfloat16
                   for l in jax.tree.leaves(s["v"]))
        p2, s2 = opt.update(_grads(), s, p)
        # params stay in their own dtype; moments stay bf16
        assert p2["w"].dtype == p["w"].dtype
        assert jax.tree.leaves(s2["m"])[0].dtype == jnp.bfloat16

    def test_update_close_to_fp32_adam(self):
        """bf16 moment storage rounds the state, not the math: a single
        step matches fp32 Adam to bf16 tolerance."""
        a32 = FusedAdam(lr=1e-2)
        a16 = FusedAdam(lr=1e-2, moments_dtype="bfloat16")
        p = _params()
        p32, _ = a32.update(_grads(), a32.init(p), p)
        p16, _ = a16.update(_grads(), a16.init(p), p)
        np.testing.assert_allclose(np.asarray(p32["w"]),
                                   np.asarray(p16["w"]),
                                   rtol=1e-2, atol=1e-2)

    def test_default_path_unchanged(self):
        """moments_dtype=None stores fp32 — identical to the historical
        behavior (bitwise, fp32 inputs)."""
        a = FusedAdam(lr=0.1, betas=(0.9, 0.999), eps=1e-8)
        p = {"w": jnp.asarray([1.0])}
        g = {"w": jnp.asarray([0.5])}
        p2, _ = a.update(g, a.init(p), p)
        m = 0.1 * 0.5
        v = 0.001 * 0.25
        mh, vh = m / (1 - 0.9), v / (1 - 0.999)
        expect = 1.0 - 0.1 * mh / (np.sqrt(vh) + 1e-8)
        np.testing.assert_allclose(float(p2["w"][0]), expect, rtol=1e-6)

    def test_bf16_grads_upcast(self):
        """bf16 grads (grad_accum_dtype=bf16) update fp32 params without
        silently degrading the moment math to bf16."""
        a = FusedAdam(lr=1e-2)
        p = _params()
        g16 = jax.tree.map(lambda g: g.astype(jnp.bfloat16), _grads())
        p2, s2 = a.update(g16, a.init(p), p)
        assert jax.tree.leaves(s2["m"])[0].dtype == jnp.float32
        assert np.isfinite(np.asarray(p2["w"])).all()


def test_adam_preserves_param_dtype():
    """fp32 update math must not promote a bf16 (master-less) param
    tree to fp32."""
    opt = FusedAdam(lr=1e-2)
    p = jax.tree.map(lambda x: x.astype(jnp.bfloat16), _params())
    g = jax.tree.map(lambda x: x.astype(jnp.bfloat16), _grads())
    p2, _ = opt.update(g, opt.init(p), p)
    assert all(l.dtype == jnp.bfloat16 for l in jax.tree.leaves(p2))
