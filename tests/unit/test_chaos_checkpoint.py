"""Chaos suite: deterministic fault injection over the checkpoint
subsystem (ISSUE 2 tentpole).

Invariant under test, for EVERY engine and EVERY injection point:
the save either completes, or 'latest' keeps naming a fully loadable
prior generation — a fault can cost at most the generation being
written, never the run.

Everything here runs at the engine-plugin/manager layer (plain numpy
trees, no model, no jit) so the whole matrix is fast and deterministic
enough for tier-1. Engine-level (DeepSpeedEngine) robustness rides in
tests/unit/test_checkpoint.py's slow set.
"""

import os
import subprocess
import sys
import textwrap
import types

import numpy as np
import pytest

from deepspeed_tpu.utils import fault_injection
from deepspeed_tpu.runtime.checkpoint_engine import serialization as ser
from deepspeed_tpu.runtime.checkpoint_engine import manager
from deepspeed_tpu.runtime.checkpoint_engine.base import (
    CheckpointSaveError)
from deepspeed_tpu.runtime.checkpoint_engine.engines import (
    ENGINES, AsyncCheckpointEngine, NativeCheckpointEngine,
    NoneCheckpointEngine, SyncCheckpointEngine)

pytestmark = pytest.mark.chaos

# the four distinct engine classes; alias names are covered by the
# ENGINES-wide smoke test at the bottom
ENGINE_NAMES = ["sync", "async", "native", "none"]
POINTS = ["serialize", "write", "rename", "commit"]


@pytest.fixture(autouse=True)
def _reset_faults():
    fault_injection.reset()
    yield
    fault_injection.reset()


def _cfg(**kw):
    base = dict(writer_threads=2, max_inflight=2, save_retries=1,
                retry_backoff_s=0.001, retry_backoff_cap_s=0.002,
                keep_last=0)
    base.update(kw)
    return types.SimpleNamespace(**base)


def _tree(step):
    return {"w": np.full((4, 3), float(step), np.float32),
            "b": {"x": np.arange(5, dtype=np.int64) + step}}


def _save_generation(eng, save_dir, step, keep_last=0):
    """The single-process save protocol from runtime/engine.py
    save_checkpoint: chunked shard write -> on_durable publishes
    'latest' -> retention GC."""
    tag = f"step{step}"
    path = os.path.join(save_dir, tag, "shard-0.npz")
    chunks, index, meta = ser.extract_local_chunks(_tree(step))
    extra = {"index": index, "__tree_meta__": meta,
             "user_extra": {"global_step": step}}

    def on_durable():
        manager.publish_latest(save_dir, tag)
        manager.gc_tags(save_dir, keep_last, counters=eng.counters)

    eng.save((chunks, extra), path, on_durable=on_durable)
    eng.commit(tag)
    return tag


def _load_best(load_dir):
    """The shared load-with-fallback protocol (manager.load_best is the
    single definition both engines use). -> (tag, flat, header) or
    (None, None, None) when nothing is loadable."""
    try:
        return manager.load_best(load_dir)
    except ser.CheckpointCorruptionError:
        return None, None, None


def _assert_loads_step(load_dir, allowed_steps):
    tag, flat, header = _load_best(load_dir)
    assert tag is not None, f"no loadable generation under {load_dir}"
    step = header["extra"]["global_step"]
    assert step in allowed_steps, (tag, step, allowed_steps)
    np.testing.assert_array_equal(flat["w"],
                                  np.full((4, 3), float(step), np.float32))
    np.testing.assert_array_equal(flat["b/x"],
                                  np.arange(5, dtype=np.int64) + step)
    return step


# --------------------------------------------------------------- injector
class TestInjector:
    def test_deterministic_countdown_and_budget(self):
        fault_injection.arm("p", fails=2, skip=1)
        fault_injection.fire("p")                      # skip
        with pytest.raises(fault_injection.FaultError):
            fault_injection.fire("p")
        with pytest.raises(fault_injection.FaultError):
            fault_injection.fire("p")
        fault_injection.fire("p")                      # healed
        assert fault_injection.injector.fired("p") == 4
        assert fault_injection.injector.hits("p") == 2

    def test_kill_is_base_exception(self):
        fault_injection.arm("p", kill=True)
        with pytest.raises(fault_injection.SimulatedKill):
            fault_injection.fire("p")
        assert not isinstance(fault_injection.SimulatedKill("p"),
                              Exception)

    def test_env_arming(self):
        os.environ["DSTPU_FAULT_INJECT"] = "write:2,rename:1:skip=3:kill"
        try:
            inj = fault_injection.FaultInjector()
        finally:
            del os.environ["DSTPU_FAULT_INJECT"]
        assert inj._arms["write"].fails == 2
        assert inj._arms["rename"].skip == 3
        assert inj._arms["rename"].kill is True


# ------------------------------------------------------- the chaos matrix
class TestFaultMatrix:
    """For each engine x injection point: persistent fault (outlives
    retries AND the degraded writer) -> 'latest' still names a loadable
    prior generation; the failed generation never becomes 'latest'."""

    @pytest.mark.parametrize("point", POINTS)
    @pytest.mark.parametrize("name", ENGINE_NAMES)
    def test_fault_never_costs_prior_generation(self, tmp_path, name,
                                                point):
        d = str(tmp_path)
        seed_eng = SyncCheckpointEngine(_cfg())
        _save_generation(seed_eng, d, step=1)
        _assert_loads_step(d, {1})

        eng = ENGINES[name](_cfg())
        fault_injection.arm(point, fails=100)     # persistent
        completed = True
        try:
            _save_generation(eng, d, step=2)
            eng.wait()
        except Exception:  # noqa: BLE001 - surfaced failure is legal
            completed = False
        finally:
            fault_injection.reset()
        if isinstance(eng, NoneCheckpointEngine):
            # no-op engine never writes or publishes: gen 1 must survive
            assert completed
            _assert_loads_step(d, {1})
            return
        if completed:
            step = _assert_loads_step(d, {1, 2})
            # a completed save under a 'commit' fault may legitimately
            # leave latest at gen 1; any other completed point must have
            # published gen 2 durably
            if point != "commit":
                assert step == 2
        else:
            _assert_loads_step(d, {1})
        eng.shutdown()

    @pytest.mark.parametrize("point", ["write", "rename", "commit"])
    @pytest.mark.parametrize("name", ENGINE_NAMES)
    def test_kill_mid_save_keeps_prior_generation(self, tmp_path, name,
                                                  point):
        """SIGKILL model: SimulatedKill (BaseException) aborts the save
        with no retry, no fallback, no cleanup handlers in the write
        path. The previously durable generation must stay intact AND
        remain what 'latest' resolves to."""
        d = str(tmp_path)
        _save_generation(SyncCheckpointEngine(_cfg()), d, step=1)

        eng = ENGINES[name](_cfg())
        fault_injection.arm(point, kill=True)
        try:
            _save_generation(eng, d, step=2)
            eng.wait()
        except BaseException:  # noqa: BLE001 - includes SimulatedKill
            pass
        finally:
            fault_injection.reset()
        _assert_loads_step(d, {1, 2})
        latest = manager.read_latest(d)
        assert latest is not None
        ser.verify_tag(os.path.join(d, latest))
        eng.shutdown()


# ------------------------------------------------------- retry / degrade
class TestRetryDegrade:
    @pytest.mark.parametrize("name", ["sync", "async", "native"])
    def test_transient_write_failure_recovers_via_retry(self, tmp_path,
                                                        name):
        d = str(tmp_path)
        eng = ENGINES[name](_cfg(save_retries=2))
        fault_injection.arm("write", fails=1)     # fail once, then heal
        _save_generation(eng, d, step=3)
        eng.wait()
        assert eng.counters["retries"] >= 1
        assert eng.counters["saves"] == 1
        assert eng.counters["save_errors"] == 0
        assert _assert_loads_step(d, {3}) == 3

    def test_native_degrades_to_python_writer(self, tmp_path):
        d = str(tmp_path)
        eng = NativeCheckpointEngine(_cfg(save_retries=1))

        class DeadWriter:
            def write(self, path, data):
                raise OSError(5, "injected native pool death")

        eng._writer = DeadWriter()
        _save_generation(eng, d, step=4)
        eng.wait()
        assert eng.counters["fallbacks"] == 1
        assert eng.counters["retries"] >= 1
        assert _assert_loads_step(d, {4}) == 4
        eng.shutdown()

    def test_async_dead_pool_degrades_to_sync_write(self, tmp_path):
        d = str(tmp_path)
        eng = AsyncCheckpointEngine(_cfg())
        eng._pool.shutdown(wait=True)             # writer threads dead
        _save_generation(eng, d, step=5)
        assert eng.counters["fallbacks"] == 1
        assert _assert_loads_step(d, {5}) == 5

    def test_failed_save_never_publishes_latest(self, tmp_path):
        d = str(tmp_path)
        _save_generation(SyncCheckpointEngine(_cfg()), d, step=1)
        eng = AsyncCheckpointEngine(_cfg(save_retries=0))
        fault_injection.arm("write", fails=50)
        with pytest.raises(CheckpointSaveError):
            _save_generation(eng, d, step=2)
            eng.wait()
        fault_injection.reset()
        assert manager.read_latest(d) == "step1"
        eng.shutdown()


# ----------------------------------------- inflight bookkeeping (satellite)
class TestFailedSaveBookkeeping:
    def test_wait_raises_exactly_once_then_heals(self, tmp_path):
        """engines.py:86-93 regression: a failed version must be popped
        from _inflight before the error re-raises, so ONE failed save
        raises ONE error — not on every later wait()/load() forever."""
        d = str(tmp_path)
        eng = AsyncCheckpointEngine(_cfg(save_retries=0))
        fault_injection.arm("write", fails=50)
        with pytest.raises(CheckpointSaveError) as ei:
            # commit() inside _save_generation surfaces the failure when
            # the writer thread finishes first; wait() surfaces it
            # otherwise — exactly one of them raises
            _save_generation(eng, d, step=1)
            eng.wait()
        assert "version 1" in str(ei.value)
        fault_injection.reset()
        assert eng._inflight == {}
        assert eng.wait() is True                 # no second raise
        assert eng.commit("t") is True
        # and the engine still saves + loads fine afterwards
        _save_generation(eng, d, step=2)
        eng.wait()
        assert _assert_loads_step(d, {2}) == 2
        eng.shutdown()

    def test_load_drains_without_raising(self, tmp_path):
        d = str(tmp_path)
        eng = AsyncCheckpointEngine(_cfg(save_retries=0))
        _save_generation(eng, d, step=1)
        eng.wait()
        fault_injection.arm("write", fails=50)
        surfaced_early = False
        try:
            _save_generation(eng, d, step=2)
        except CheckpointSaveError:      # commit() won the race
            surfaced_early = True
        eng.drain()          # v2 completes (failed) WITHOUT raising
        fault_injection.reset()
        # load() must return the durable generation even though v2 failed
        flat, header = eng.load(os.path.join(d, "step1", "shard-0.npz"))
        assert header["extra"]["user_extra"]["global_step"] == 1
        # ...and the failure still surfaces exactly once, from wait()
        if not surfaced_early:
            with pytest.raises(CheckpointSaveError):
                eng.wait()
        assert eng.wait() is True        # and never again
        eng.shutdown()

    def test_backpressure_window_never_wedges(self, tmp_path):
        """Old bug shape: a failed future stuck in _inflight kept the
        max_inflight window permanently full. After surfacing the
        failure, later saves must proceed."""
        d = str(tmp_path)
        eng = AsyncCheckpointEngine(_cfg(save_retries=0, max_inflight=1))
        fault_injection.arm("write", fails=50)
        raised = 0
        try:
            # commit() inside may already surface the failure when the
            # writer thread loses the race — that's the "exactly once"
            _save_generation(eng, d, step=1)
        except CheckpointSaveError:
            raised += 1
        eng.drain()          # v1 completes (failed) WITHOUT raising
        fault_injection.reset()
        for step in (2, 3, 4):
            try:
                _save_generation(eng, d, step=step)
            except CheckpointSaveError:
                raised += 1
        eng.wait()
        assert raised == 1   # surfaced exactly once, wherever it landed
        assert _assert_loads_step(d, {4}) == 4
        eng.shutdown()


# ------------------------------------------------- integrity & atomicity
class TestIntegrityAtomicity:
    def test_save_file_is_atomic_under_write_fault(self, tmp_path):
        """Satellite: a crash mid-write must never destroy the
        previously durable shard at the same path."""
        p = str(tmp_path / "x.npz")
        ser.save_file(p, _tree(1), extra_meta={"global_step": 1})
        fault_injection.arm("write", fails=1)
        with pytest.raises(fault_injection.FaultError):
            ser.save_file(p, _tree(2), extra_meta={"global_step": 2})
        fault_injection.reset()
        flat, header = ser.load_file(p)
        assert header["extra"]["global_step"] == 1
        np.testing.assert_array_equal(flat["w"], _tree(1)["w"])

    def test_save_file_is_atomic_under_kill_at_rename(self, tmp_path):
        p = str(tmp_path / "x.npz")
        ser.save_file(p, _tree(1))
        fault_injection.arm("rename", kill=True)
        with pytest.raises(fault_injection.SimulatedKill):
            ser.save_file(p, _tree(2))
        fault_injection.reset()
        flat, _ = ser.load_file(p)
        np.testing.assert_array_equal(flat["w"], _tree(1)["w"])

    def test_crc_detects_bit_corruption(self, tmp_path):
        p = str(tmp_path / "x.npz")
        ser.save_file(p, _tree(7))
        size = os.path.getsize(p)
        with open(p, "r+b") as f:        # flip bytes inside the payload
            f.seek(size // 2)
            chunk = f.read(4)
            f.seek(size // 2)
            f.write(bytes(b ^ 0xFF for b in chunk))
        with pytest.raises(ser.CheckpointCorruptionError):
            ser.load_file(p)

    def test_truncation_detected(self, tmp_path):
        p = str(tmp_path / "x.npz")
        ser.save_file(p, _tree(7))
        size = os.path.getsize(p)
        with open(p, "r+b") as f:
            f.truncate(size // 2)
        with pytest.raises(ser.CheckpointCorruptionError):
            ser.load_file(p)

    def test_verify_tag_passes_good_and_fails_torn(self, tmp_path):
        tagdir = tmp_path / "t"
        os.makedirs(tagdir)
        ser.save_file(str(tagdir / "state.npz"), _tree(1))
        assert ser.verify_tag(str(tagdir)) is True
        with open(tagdir / "state.npz", "r+b") as f:
            f.truncate(10)
        with pytest.raises(ser.CheckpointCorruptionError):
            ser.verify_tag(str(tagdir))


# ------------------------------------------------------- retention & GC
class TestRetention:
    def test_keep_last_k_durable_tags(self, tmp_path):
        d = str(tmp_path)
        eng = SyncCheckpointEngine(_cfg())
        for step in range(1, 6):
            _save_generation(eng, d, step=step, keep_last=2)
        tags = manager.list_tags(d)
        assert sorted(tags) == ["step4", "step5"]
        assert manager.read_latest(d) == "step5"
        assert eng.counters["gc_removed"] == 3
        assert _assert_loads_step(d, {5}) == 5

    def test_gc_refuses_when_newest_tag_is_torn(self, tmp_path):
        d = str(tmp_path)
        eng = SyncCheckpointEngine(_cfg())
        for step in (1, 2, 3):
            _save_generation(eng, d, step=step)
        # tear the newest generation AFTER it was published
        shard = os.path.join(d, "step3", "shard-0.npz")
        with open(shard, "r+b") as f:
            f.truncate(os.path.getsize(shard) // 2)
        removed = manager.gc_tags(d, keep_last=1,
                                  counters=eng.counters)
        assert removed == []                      # nothing deleted
        assert sorted(manager.list_tags(d)) == ["step1", "step2",
                                                "step3"]
        # recovery still has a known-good generation
        assert _assert_loads_step(d, {2}) == 2

    def test_gc_never_deletes_what_latest_names(self, tmp_path):
        d = str(tmp_path)
        eng = SyncCheckpointEngine(_cfg())
        for step in (1, 2, 3):
            _save_generation(eng, d, step=step)
        manager.publish_latest(d, "step1")        # pointer pinned old
        removed = manager.gc_tags(d, keep_last=1)
        assert "step1" not in removed
        assert _assert_loads_step(d, {1}) == 1


# ------------------------------------------------------- load fallback
class TestLoadFallback:
    def test_corrupt_newest_falls_back_to_previous_durable(self,
                                                           tmp_path):
        d = str(tmp_path)
        eng = SyncCheckpointEngine(_cfg())
        _save_generation(eng, d, step=1)
        _save_generation(eng, d, step=2)
        shard = os.path.join(d, "step2", "shard-0.npz")
        with open(shard, "r+b") as f:
            f.truncate(os.path.getsize(shard) - 64)
        tag, flat, header = _load_best(d)
        assert tag == "step1"
        assert header["extra"]["global_step"] == 1

    def test_missing_latest_pointer_still_recovers(self, tmp_path):
        d = str(tmp_path)
        eng = SyncCheckpointEngine(_cfg())
        _save_generation(eng, d, step=1)
        os.remove(os.path.join(d, "latest"))
        tag, _, header = _load_best(d)
        assert tag == "step1" and header["extra"]["global_step"] == 1


# ------------------------------------------ process-kill (real process)
class TestProcessKill:
    def test_os_level_kill_between_write_and_publish(self, tmp_path):
        """A REAL process death (os._exit, no unwinding) at the commit
        boundary: the shard of gen 2 is durable but 'latest' still names
        gen 1 — recovery loads gen 1; nothing is torn."""
        d = str(tmp_path / "ckpt")
        script = textwrap.dedent(f"""
            import os, sys
            sys.path.insert(0, {str(os.getcwd())!r})
            os.environ["JAX_PLATFORMS"] = "cpu"
            os.environ["DSTPU_FAULT_INJECT"] = "commit:1:skip=1"
            import numpy as np
            from deepspeed_tpu.runtime.checkpoint_engine import manager
            from deepspeed_tpu.runtime.checkpoint_engine.engines import (
                SyncCheckpointEngine)

            def save(step):
                tag = f"step{{step}}"
                path = os.path.join({d!r}, tag, "shard-0.npz")
                eng = SyncCheckpointEngine(None)
                eng.save(({{"w": np.full((4, 3), float(step),
                                         np.float32)}},
                          {{"global_step": step}}), path,
                         on_durable=lambda: manager.publish_latest(
                             {d!r}, tag))

            save(1)      # commit fire #1: skipped -> publishes
            try:
                save(2)  # commit fire #2: SimulatedKill
            except BaseException:
                os._exit(137)   # SIGKILL-faithful: no cleanup
            os._exit(0)
        """)
        proc = subprocess.run([sys.executable, "-c", script],
                              capture_output=True, text=True, timeout=120)
        assert proc.returncode == 137, (proc.stdout, proc.stderr)
        assert manager.read_latest(d) == "step1"
        flat, header = ser.load_file(
            os.path.join(d, "step1", "shard-0.npz"))
        assert header["extra"]["global_step"] == 1
        np.testing.assert_array_equal(
            flat["w"], np.full((4, 3), 1.0, np.float32))
        # gen 2's shard is durable (write finished before the kill) —
        # a later load_candidates pass may use it, and it must verify
        assert ser.verify_tag(os.path.join(d, "step2")) is True


# ------------------------------------------------- ENGINES-wide smoke
@pytest.mark.parametrize("name", sorted(ENGINES))
def test_every_engine_roundtrips_under_one_write_failure(tmp_path, name):
    """Satellite: every ENGINES entry (aliases included) completes a
    save/load round-trip with one injected write failure absorbed by
    the retry layer."""
    d = str(tmp_path)
    eng = ENGINES[name](_cfg(save_retries=2))
    fault_injection.arm("write", fails=1)
    _save_generation(eng, d, step=9)
    eng.wait()
    fault_injection.reset()
    if isinstance(eng, NoneCheckpointEngine):
        assert manager.read_latest(d) is None     # writes nothing
        with pytest.raises(RuntimeError):
            eng.load("anything")
        return
    assert eng.counters["save_errors"] == 0
    assert _assert_loads_step(d, {9}) == 9
    eng.shutdown()
