"""AIO op + tensor swapping tests (reference tests/unit/ops/aio/
test_aio.py + runtime/swap_tensor coverage)."""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.ops.native.aio import AsyncIOHandle
from deepspeed_tpu.runtime.swap_tensor import (AsyncTensorSwapper,
                                               OptimizerStateSwapper)


@pytest.fixture(scope="module")
def aio():
    h = AsyncIOHandle(num_threads=2, block_size=1 << 16)
    yield h
    h.close()


class TestAIOHandle:
    def test_sync_roundtrip(self, aio, tmp_path):
        data = np.random.RandomState(0).bytes(300_000)
        arr = np.frombuffer(data, np.uint8)
        path = tmp_path / "sync.bin"
        n = aio.sync_pwrite(arr, path)
        assert n == 300_000 and path.stat().st_size == 300_000
        out = np.empty_like(arr)
        aio.sync_pread(out, path)
        np.testing.assert_array_equal(out, arr)

    def test_async_roundtrip_many(self, aio, tmp_path):
        arrays = [np.random.RandomState(i).randn(1000 + i).astype(np.float32)
                  for i in range(8)]
        reqs = [aio.async_pwrite(a, tmp_path / f"f{i}.bin", fsync=False)
                for i, a in enumerate(arrays)]
        assert aio.wait() == 8
        outs = [np.empty_like(a) for a in arrays]
        reqs = [aio.async_pread(o, tmp_path / f"f{i}.bin")
                for i, o in enumerate(outs)]
        for r in reqs:
            aio.wait(r)
        for a, o in zip(arrays, outs):
            np.testing.assert_array_equal(a, o)

    def test_missing_file_raises(self, aio, tmp_path):
        out = np.empty(16, np.uint8)
        with pytest.raises(OSError):
            aio.sync_pread(out, tmp_path / "absent.bin")

    def test_chunked_write_exceeds_block(self, aio, tmp_path):
        # block_size 64KiB; write 1MiB -> 16 chunks
        arr = np.random.RandomState(1).randn(131072).astype(np.float64)
        aio.sync_pwrite(arr, tmp_path / "big.bin")
        out = np.empty_like(arr)
        aio.sync_pread(out, tmp_path / "big.bin")
        np.testing.assert_array_equal(arr, out)


class TestTensorSwapper:
    def test_swap_roundtrip_numpy_and_jax(self, tmp_path):
        sw = AsyncTensorSwapper(str(tmp_path / "swap"), num_threads=2)
        a = np.random.RandomState(0).randn(64, 32).astype(np.float32)
        b = jnp.arange(100, dtype=jnp.int32)
        sw.swap_out("a", a)
        sw.swap_out("b", b)
        sw.wait()
        np.testing.assert_array_equal(sw.swap_in("a"), a)
        np.testing.assert_array_equal(sw.swap_in("b"), np.asarray(b))
        sw.close()

    def test_swap_in_waits_pending_write(self, tmp_path):
        sw = AsyncTensorSwapper(str(tmp_path / "swap2"), num_threads=1)
        a = np.random.RandomState(1).randn(200_000).astype(np.float32)
        sw.swap_out("x", a)             # async
        out = sw.swap_in("x")           # must see the full write
        np.testing.assert_array_equal(out, a)
        sw.close()

    def test_async_read(self, tmp_path):
        sw = AsyncTensorSwapper(str(tmp_path / "swap3"))
        a = np.arange(5000, dtype=np.float32)
        sw.swap_out("k", a, blocking=True)
        assert sw.swap_in("k", async_=True) is None
        out = sw.wait_in("k")
        np.testing.assert_array_equal(out, a)
        sw.close()

    def test_remove(self, tmp_path):
        sw = AsyncTensorSwapper(str(tmp_path / "swap4"))
        sw.swap_out("gone", np.ones(10), blocking=True)
        sw.remove("gone")
        assert sw.keys() == []
        sw.close()


class TestOptimizerStateSwapper:
    def test_tree_roundtrip(self, tmp_path):
        osw = OptimizerStateSwapper(str(tmp_path / "opt"))
        tree = {"m": {"w": np.random.RandomState(0).randn(32, 16)
                      .astype(np.float32),
                      "b": np.zeros(16, np.float32)},
                "v": {"w": np.ones((32, 16), np.float32),
                      "b": np.full(16, 2.0, np.float32)}}
        osw.swap_out_tree("rank0", tree)
        osw.wait()
        back = osw.swap_in_tree("rank0")
        jax.tree.map(np.testing.assert_array_equal, back, tree)
        osw.close()


class TestFixes:
    def test_double_wait_raises(self, aio, tmp_path):
        a = np.ones(64, np.float32)
        r = aio.async_pwrite(a, tmp_path / "dw.bin")
        aio.wait(r)
        with pytest.raises(KeyError):
            aio.wait(r)

    def test_same_key_overwrite_serializes(self, tmp_path):
        sw = AsyncTensorSwapper(str(tmp_path / "ser"), num_threads=4)
        a = np.zeros(500_000, np.float32)
        b = np.ones(500_000, np.float32)
        sw.swap_out("k", a)           # async
        sw.swap_out("k", b)           # must drain the first write
        out = sw.swap_in("k")
        np.testing.assert_array_equal(out, b)
        sw.close()

    def test_tree_restore_in_fresh_swapper(self, tmp_path):
        d = str(tmp_path / "fresh")
        osw = OptimizerStateSwapper(d)
        tree = {"m": [np.arange(10, dtype=np.float32),
                      np.ones((4, 4), np.int32)],
                "step": np.asarray(7, np.int64)}
        osw.swap_out_tree("r0", tree, blocking=True)
        osw.close()
        # brand-new process simulation: new swapper over the same dir
        osw2 = OptimizerStateSwapper(d)
        back = osw2.swap_in_tree("r0")
        np.testing.assert_array_equal(back["m"][0], tree["m"][0])
        np.testing.assert_array_equal(back["m"][1], tree["m"][1])
        assert back["step"] == 7
        osw2.close()


class TestSwapperDurability:
    def test_manifest_deferred_until_wait(self, tmp_path):
        """swap_out_tree's durable manifest only lands at wait()/
        finalize — a crash before that leaves the PREVIOUS manifest in
        place (the metadata can never name leaves whose writes were
        still in flight). Leaf FILES for a re-used key are overwritten
        in place, so the manifest guarantee is structural (skeleton/
        shape/dtype), not a full previous-generation archive — callers
        needing generational durability key each generation uniquely
        (what checkpoint tags do)."""
        import json
        import os
        d = str(tmp_path / "defer")
        osw = OptimizerStateSwapper(d)
        t1 = {"w": np.arange(8, dtype=np.float32)}
        osw.swap_out_tree("gen", t1, blocking=True)   # manifest v1 durable
        man = os.path.join(d, "gen.manifest.json")
        with open(man) as f:
            v1 = json.load(f)
        # grow the tree; async (no finalize): the durable manifest must
        # still be v1 (one leaf), not the in-flight two-leaf layout
        t2 = {"w": np.arange(8, dtype=np.float32) * 3,
              "b": np.ones(4, np.float32)}
        osw.swap_out_tree("gen", t2)
        with open(man) as f:
            assert json.load(f) == v1
        osw.wait()                                    # manifest v2 lands
        with open(man) as f:
            assert len(json.load(f)["names"]) == 2
        fresh = OptimizerStateSwapper(d)
        back = fresh.swap_in_tree("gen")
        np.testing.assert_array_equal(back["w"], t2["w"])
        np.testing.assert_array_equal(back["b"], t2["b"])
        fresh.close()
        osw.close()


class TestHostOffloadStructure:
    def test_map_structure_path_traversal(self):
        """master_tree/state_tree rebuild nested structures by PATH
        (no stateful parallel iteration): nested dicts, single-leaf
        subtrees, and mixed depths all round-trip."""
        from deepspeed_tpu.runtime.config import OffloadConfig, \
            OptimizerConfig
        from deepspeed_tpu.runtime.zero.offload import (
            HostOffloadOptimizer)
        master = {"blocks": {"deep": {"w": np.ones((2, 3), np.float32)},
                             "b": np.zeros(4, np.float32)},
                  "wte": np.full((5,), 2.0, np.float32)}
        opt = HostOffloadOptimizer(
            master, OptimizerConfig(type="AdamW", params={"lr": 1e-3}),
            OffloadConfig(device="cpu"), num_threads=1)
        back = opt.master_tree()
        jax.tree.map(np.testing.assert_array_equal, back, master)
        st = opt.state_tree()
        assert int(st["step"]) == 0
        jax.tree.map(lambda m, ref: np.testing.assert_array_equal(
            m, np.zeros_like(ref)), st["m"], master)
        # load_state_tree inverts state_tree
        st["m"]["wte"][:] = 7.0
        opt.load_state_tree(st)
        np.testing.assert_array_equal(
            opt.state_tree()["m"]["wte"], np.full((5,), 7.0))
        opt.close()
