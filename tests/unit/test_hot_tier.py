"""Hot-tier chaos suite (ISSUE 7 tentpole a): peer-replicated in-memory
checkpoints, tier-ordered restore, and deterministic degradation.

Everything here runs at the store/manager layer (plain numpy trees, no
model, no jit) so the matrix is fast and deterministic enough for
tier-1; the real-process kill-a-host-and-resume-from-the-hot-tier runs
ride in tests/unit/test_elastic_agent.py's slow set.

The invariant under test: the common single-host loss restores from
surviving replicas with ZERO persistent-storage reads, and ANY hot-tier
defect (missing replicas, CRC-corrupt replica, poisoned replica_fetch)
degrades to the durable tier instead of failing the resume.
"""

import os

import numpy as np
import pytest

from deepspeed_tpu.utils import fault_injection
from deepspeed_tpu.runtime.checkpoint_engine import hot_tier
from deepspeed_tpu.runtime.checkpoint_engine import manager
from deepspeed_tpu.runtime.checkpoint_engine import serialization as ser
from deepspeed_tpu.runtime.checkpoint_engine.engines import (
    SyncCheckpointEngine)

pytestmark = pytest.mark.chaos

PEERS = ["h0", "h1", "h2", "h3"]


@pytest.fixture(autouse=True)
def _reset_faults():
    fault_injection.reset()
    yield
    fault_injection.reset()


def _tree(step):
    return {"w": np.full((4, 3), float(step), np.float32),
            "b": np.arange(5, dtype=np.int64) + step}


def _payload(step, nprocs=1):
    chunks, index, meta = ser.extract_local_chunks(_tree(step))
    extra = {"index": index, "__tree_meta__": meta,
             "user_extra": {"global_step": step, "nprocs": nprocs}}
    return chunks, extra


def _stores(root, peers=PEERS, replicas=1, **kw):
    return {p: hot_tier.HotTierStore(root=str(root), node=p, peers=peers,
                                     replicas=replicas, **kw)
            for p in peers}


def _durable_generation(save_dir, step):
    """One durable generation via the engine-level save protocol."""
    eng = SyncCheckpointEngine(None)
    tag = f"global_step{step}"
    chunks, extra = _payload(step)
    eng.save((chunks, extra),
             os.path.join(save_dir, tag, "shard-0.npz"),
             on_durable=lambda: manager.publish_latest(save_dir, tag))
    return tag


class TestRingTopology:
    def test_neighbors_k1(self):
        s = hot_tier.HotTierStore(root="/nonexistent-unused", node="h1",
                                  peers=PEERS, replicas=1)
        assert s.ring_neighbors() == ["h2"]

    def test_neighbors_k2_wraps(self):
        s = hot_tier.HotTierStore(root="/nonexistent-unused", node="h3",
                                  peers=PEERS, replicas=2)
        assert s.ring_neighbors() == ["h0", "h1"]

    def test_single_node_has_no_neighbors(self):
        s = hot_tier.HotTierStore(root="/nonexistent-unused", node="h0",
                                  peers=["h0"], replicas=3)
        assert s.ring_neighbors() == []

    def test_replicas_capped_by_ring_size(self):
        s = hot_tier.HotTierStore(root="/nonexistent-unused", node="h0",
                                  peers=["h0", "h1"], replicas=5)
        assert s.ring_neighbors() == ["h1"]


class TestPushRestore:
    def test_roundtrip_from_own_store(self, tmp_path):
        stores = _stores(tmp_path)
        chunks, extra = _payload(3)
        n = stores["h0"].push("global_step3", chunks, extra,
                              shard_name="shard-0.npz")
        assert n == 1                             # one ring replica
        tag, flat, header = stores["h0"].load_best()
        assert tag == "global_step3"
        assert header["extra"]["global_step"] == 3
        np.testing.assert_array_equal(flat["w"], _tree(3)["w"])
        # own-store read: no replica fetch fired
        assert fault_injection.injector.fired("replica_fetch") == 0
        assert fault_injection.injector.fired("replica_push") == 1

    def test_host_loss_restores_from_surviving_replica(self, tmp_path):
        """THE common failure: the writer host dies; its ring neighbor
        holds the replica and the resume never touches storage."""
        stores = _stores(tmp_path)
        chunks, extra = _payload(5)
        stores["h0"].push("global_step5", chunks, extra,
                          shard_name="shard-0.npz")
        hot_tier.purge_node(str(tmp_path), "h0")   # host RAM gone
        tag, flat, header = stores["h1"].load_best()
        assert tag == "global_step5"
        np.testing.assert_array_equal(flat["w"], _tree(5)["w"])
        # the restore read a REPLICA (fired) — and nothing else existed
        assert fault_injection.injector.fired("replica_fetch") >= 1

    def test_non_neighbor_cannot_restore_after_purge(self, tmp_path):
        """K=1: only the next ring neighbor holds the replica; a purge
        of both writer and neighbor loses the generation (that's what
        the durable tier is for)."""
        stores = _stores(tmp_path)
        chunks, extra = _payload(5)
        stores["h0"].push("global_step5", chunks, extra,
                          shard_name="shard-0.npz")
        hot_tier.purge_node(str(tmp_path), "h0")
        hot_tier.purge_node(str(tmp_path), "h1")
        tag, _, _ = stores["h2"].load_best()
        assert tag is None

    def test_k2_survives_double_host_loss(self, tmp_path):
        stores = _stores(tmp_path, replicas=2)
        chunks, extra = _payload(7)
        stores["h0"].push("global_step7", chunks, extra,
                          shard_name="shard-0.npz")
        hot_tier.purge_node(str(tmp_path), "h0")
        hot_tier.purge_node(str(tmp_path), "h1")
        tag, flat, _ = stores["h2"].load_best()
        assert tag == "global_step7"
        np.testing.assert_array_equal(flat["w"], _tree(7)["w"])

    def test_newest_generation_wins(self, tmp_path):
        stores = _stores(tmp_path)
        for step in (1, 2, 10):
            chunks, extra = _payload(step)
            stores["h0"].push(f"global_step{step}", chunks, extra,
                              shard_name="shard-0.npz")
        tag, _, header = stores["h0"].load_best()
        assert tag == "global_step10"              # step order, not lex
        assert header["extra"]["global_step"] == 10

    def test_multi_writer_assembly_across_stores(self, tmp_path):
        """A 2-writer world: each writer pushes ITS shard; a reader
        assembles the generation from shards scattered across stores
        (h1's own + the replica h0 pushed to it)."""
        stores = _stores(tmp_path, peers=["h0", "h1"], replicas=1)
        t0, t1 = _tree(1), {"w": np.full((4, 3), 9.0, np.float32),
                            "b": np.arange(5, dtype=np.int64)}
        # writer 0: rows 0-1 of w; writer 1: rows 2-3 (chunked layout)
        c0 = {"w#0.0": t0["w"][:2], "b#0.0": t0["b"]}
        i0 = {"w": {"shape": [4, 3], "dtype": "float32",
                    "chunks": [{"key": "w#0.0", "start": [0, 0]}]},
              "b": {"shape": [5], "dtype": "int64",
                    "chunks": [{"key": "b#0.0", "start": [0]}]}}
        c1 = {"w#1.0": t1["w"][2:]}
        i1 = {"w": {"shape": [4, 3], "dtype": "float32",
                    "chunks": [{"key": "w#1.0", "start": [2, 0]}]},
              "b": {"shape": [5], "dtype": "int64", "chunks": []}}
        ex = {"__tree_meta__": {},
              "user_extra": {"global_step": 1, "nprocs": 2}}
        stores["h0"].push("global_step1", c0, dict(ex, index=i0),
                          shard_name="shard-0.npz")
        stores["h1"].push("global_step1", c1, dict(ex, index=i1),
                          shard_name="shard-1.npz")
        hot_tier.purge_node(str(tmp_path), "h0")   # writer 0 dies
        tag, flat, _ = stores["h1"].load_best()
        assert tag == "global_step1"
        np.testing.assert_array_equal(flat["w"][:2], t0["w"][:2])
        np.testing.assert_array_equal(flat["w"][2:], t1["w"][2:])


class TestDegradation:
    def test_poisoned_replica_fetch_degrades_to_durable(self, tmp_path):
        """Acceptance variant: replicas CRC-poisoned via the
        replica_fetch fault point — the tiered load degrades to the
        durable tier and still resumes."""
        hot_root = tmp_path / "hot"
        durable = str(tmp_path / "ckpt")
        _durable_generation(durable, step=4)
        stores = _stores(hot_root)
        chunks, extra = _payload(4)
        stores["h0"].push("global_step4", chunks, extra,
                          shard_name="shard-0.npz")
        hot_tier.purge_node(str(hot_root), "h0")   # force replica reads
        fault_injection.arm("replica_fetch", fails=100)
        counters = {}
        tier, tag, flat, header = manager.load_best_tiered(
            durable, hot_store=stores["h1"], counters=counters)
        assert tier == "durable"
        assert tag == "global_step4"
        np.testing.assert_array_equal(flat["w"], _tree(4)["w"])
        assert counters["hot_fallbacks"] == 1
        assert counters["durable_restores"] == 1

    def test_crc_corrupt_replica_degrades(self, tmp_path):
        """Bit-rot in a replica file (not just an injected fetch error)
        is caught by the CRC manifest and degrades identically."""
        hot_root = tmp_path / "hot"
        durable = str(tmp_path / "ckpt")
        _durable_generation(durable, step=6)
        stores = _stores(hot_root)
        chunks, extra = _payload(6)
        stores["h0"].push("global_step6", chunks, extra,
                          shard_name="shard-0.npz")
        hot_tier.purge_node(str(hot_root), "h0")
        replica = os.path.join(str(hot_root), "h1", "global_step6",
                               "from-h0", "shard-0.npz")
        size = os.path.getsize(replica)
        with open(replica, "r+b") as f:
            f.seek(size // 2)
            chunk = f.read(4)
            f.seek(size // 2)
            f.write(bytes(b ^ 0xFF for b in chunk))
        tier, tag, _, _ = manager.load_best_tiered(
            durable, hot_store=stores["h1"])
        assert (tier, tag) == ("durable", "global_step6")

    def test_hot_restore_reads_zero_durable_files(self, tmp_path):
        """The tentpole claim, asserted mechanically: when the hot tier
        serves the restore, the durable loader is NEVER invoked."""
        hot_root = tmp_path / "hot"
        durable = str(tmp_path / "ckpt")
        _durable_generation(durable, step=2)
        stores = _stores(hot_root)
        chunks, extra = _payload(2)
        stores["h0"].push("global_step2", chunks, extra,
                          shard_name="shard-0.npz")
        durable_reads = []

        def loader(tag_dir):
            durable_reads.append(tag_dir)
            return ser.load_state(tag_dir)

        counters = {}
        tier, tag, flat, _ = manager.load_best_tiered(
            durable, hot_store=stores["h0"], loader=loader,
            counters=counters)
        assert tier == "hot" and tag == "global_step2"
        assert durable_reads == []                 # ZERO storage reads
        assert counters["hot_restores"] == 1
        assert counters.get("durable_restores", 0) == 0

    def test_empty_hot_tier_goes_straight_to_durable(self, tmp_path):
        durable = str(tmp_path / "ckpt")
        _durable_generation(durable, step=1)
        store = hot_tier.HotTierStore(root=str(tmp_path / "hot"),
                                      node="h0", peers=PEERS)
        counters = {}
        tier, tag, _, _ = manager.load_best_tiered(
            durable, hot_store=store, counters=counters)
        assert (tier, tag) == ("durable", "global_step1")
        # an EMPTY hot tier is not a fallback (nothing was lost)
        assert counters.get("hot_fallbacks", 0) == 0

    def test_nothing_anywhere_returns_none(self, tmp_path):
        store = hot_tier.HotTierStore(root=str(tmp_path / "hot"),
                                      node="h0", peers=PEERS)
        tier, tag, flat, header = manager.load_best_tiered(
            str(tmp_path / "ckpt"), hot_store=store)
        assert tier is None and tag is None


class TestPushFaults:
    def test_replica_push_failure_is_advisory(self, tmp_path):
        """A failed peer push can never cost the save: the local entry
        still lands, the error is counted, nothing raises."""
        counters = {}
        stores = _stores(tmp_path, counters=counters)
        fault_injection.arm("replica_push", fails=100)
        chunks, extra = _payload(3)
        n = stores["h0"].push("global_step3", chunks, extra,
                              shard_name="shard-0.npz")
        assert n == 0                              # no replica landed
        assert counters["hot_push_errors"] == 1
        # own store still restorable
        tag, _, _ = stores["h0"].load_best()
        assert tag == "global_step3"
        # ...but the ring neighbor holds nothing after the writer dies
        hot_tier.purge_node(str(tmp_path), "h0")
        assert stores["h1"].load_best()[0] is None

    def test_push_async_swallows_advisory_failures(self, tmp_path):
        stores = _stores(tmp_path)
        fault_injection.arm("replica_push", fails=1)
        chunks, extra = _payload(3)
        fut = stores["h0"].push_async("global_step3", chunks, extra,
                                      shard_name="shard-0.npz")
        assert stores["h0"].wait() is True         # no raise
        assert fut.exception() is None
        stores["h0"].shutdown()

    def test_kill_during_push_propagates(self, tmp_path):
        """SimulatedKill models SIGKILL: no advisory swallow."""
        stores = _stores(tmp_path)
        fault_injection.arm("replica_push", kill=True)
        chunks, extra = _payload(3)
        with pytest.raises(fault_injection.SimulatedKill):
            stores["h0"].push("global_step3", chunks, extra,
                              shard_name="shard-0.npz")


class TestRetentionAndCandidates:
    def test_hot_gc_keeps_newest(self, tmp_path):
        stores = _stores(tmp_path, keep_last=2)
        for step in range(1, 6):
            chunks, extra = _payload(step)
            stores["h0"].push(f"global_step{step}", chunks, extra,
                              shard_name="shard-0.npz")
        own = sorted(os.listdir(os.path.join(str(tmp_path), "h0")))
        assert own == ["global_step4", "global_step5"]

    def test_tiered_candidates_order_hot_first(self, tmp_path):
        hot_root = tmp_path / "hot"
        durable = str(tmp_path / "ckpt")
        _durable_generation(durable, step=1)
        _durable_generation(durable, step=2)
        stores = _stores(hot_root)
        chunks, extra = _payload(2)
        stores["h0"].push("global_step2", chunks, extra,
                          shard_name="shard-0.npz")
        cands = manager.load_candidates(durable,
                                        hot_store=stores["h0"])
        assert cands[0] == ("hot", "global_step2")
        assert ("durable", "global_step2") in cands
        assert ("durable", "global_step1") in cands
        assert [t for t, _ in cands] == sorted(
            [t for t, _ in cands], key=lambda t: t != "hot")

    def test_legacy_candidates_shape_unchanged(self, tmp_path):
        durable = str(tmp_path / "ckpt")
        _durable_generation(durable, step=1)
        assert manager.load_candidates(durable) == ["global_step1"]

    def test_stale_hot_generation_never_rolls_back_durable(
            self, tmp_path):
        """The advisory push can lag the durable commit (async pool,
        push failure): a hot tier holding only step 2 after step 3
        durably committed must NOT serve step 2 — that would silently
        roll a committed generation back."""
        hot_root = tmp_path / "hot"
        durable = str(tmp_path / "ckpt")
        _durable_generation(durable, step=2)
        _durable_generation(durable, step=3)     # committed, never pushed
        stores = _stores(hot_root)
        chunks, extra = _payload(2)
        stores["h0"].push("global_step2", chunks, extra,
                          shard_name="shard-0.npz")
        cands = manager.load_candidates(durable, hot_store=stores["h0"])
        assert ("hot", "global_step2") not in cands   # filtered as stale
        counters = {}
        tier, tag, _, header = manager.load_best_tiered(
            durable, hot_store=stores["h0"], counters=counters)
        assert (tier, tag) == ("durable", "global_step3")
        assert header["extra"]["global_step"] == 3
        # a filtered-out stale tier is not a DEGRADATION
        assert counters.get("hot_fallbacks", 0) == 0

    def test_hot_newer_than_durable_latest_is_served(self, tmp_path):
        """The inverse: the durable commit of step 4 never landed but
        the replicas did — the newest trained state wins."""
        hot_root = tmp_path / "hot"
        durable = str(tmp_path / "ckpt")
        _durable_generation(durable, step=3)
        stores = _stores(hot_root)
        chunks, extra = _payload(4)
        stores["h0"].push("global_step4", chunks, extra,
                          shard_name="shard-0.npz")
        tier, tag, _, header = manager.load_best_tiered(
            durable, hot_store=stores["h0"])
        assert (tier, tag) == ("hot", "global_step4")
        assert header["extra"]["global_step"] == 4
