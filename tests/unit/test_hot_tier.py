"""Hot-tier chaos suite (ISSUE 7 tentpole a): peer-replicated in-memory
checkpoints, tier-ordered restore, and deterministic degradation.

Everything here runs at the store/manager layer (plain numpy trees, no
model, no jit) so the matrix is fast and deterministic enough for
tier-1; the real-process kill-a-host-and-resume-from-the-hot-tier runs
ride in tests/unit/test_elastic_agent.py's slow set.

The invariant under test: the common single-host loss restores from
surviving replicas with ZERO persistent-storage reads, and ANY hot-tier
defect (missing replicas, CRC-corrupt replica, poisoned replica_fetch)
degrades to the durable tier instead of failing the resume.
"""

import os
import threading

import numpy as np
import pytest

from deepspeed_tpu.utils import fault_injection
from deepspeed_tpu.runtime.checkpoint_engine import hot_tier
from deepspeed_tpu.runtime.checkpoint_engine import manager
from deepspeed_tpu.runtime.checkpoint_engine import serialization as ser
from deepspeed_tpu.runtime.checkpoint_engine.engines import (
    SyncCheckpointEngine)

pytestmark = pytest.mark.chaos

PEERS = ["h0", "h1", "h2", "h3"]
# two virtual slices: h0/h1 form slice 0, h2/h3 slice 1
SLICES = {"h0": "0", "h1": "0", "h2": "1", "h3": "1"}


@pytest.fixture(autouse=True)
def _reset_faults():
    fault_injection.reset()
    yield
    fault_injection.reset()


def _tree(step):
    return {"w": np.full((4, 3), float(step), np.float32),
            "b": np.arange(5, dtype=np.int64) + step}


def _payload(step, nprocs=1):
    chunks, index, meta = ser.extract_local_chunks(_tree(step))
    extra = {"index": index, "__tree_meta__": meta,
             "user_extra": {"global_step": step, "nprocs": nprocs}}
    return chunks, extra


def _stores(root, peers=PEERS, replicas=1, **kw):
    return {p: hot_tier.HotTierStore(root=str(root), node=p, peers=peers,
                                     replicas=replicas, **kw)
            for p in peers}


def _durable_generation(save_dir, step):
    """One durable generation via the engine-level save protocol."""
    eng = SyncCheckpointEngine(None)
    tag = f"global_step{step}"
    chunks, extra = _payload(step)
    eng.save((chunks, extra),
             os.path.join(save_dir, tag, "shard-0.npz"),
             on_durable=lambda: manager.publish_latest(save_dir, tag))
    return tag


class TestRingTopology:
    def test_neighbors_k1(self):
        s = hot_tier.HotTierStore(root="/nonexistent-unused", node="h1",
                                  peers=PEERS, replicas=1)
        assert s.ring_neighbors() == ["h2"]

    def test_neighbors_k2_wraps(self):
        s = hot_tier.HotTierStore(root="/nonexistent-unused", node="h3",
                                  peers=PEERS, replicas=2)
        assert s.ring_neighbors() == ["h0", "h1"]

    def test_single_node_has_no_neighbors(self):
        s = hot_tier.HotTierStore(root="/nonexistent-unused", node="h0",
                                  peers=["h0"], replicas=3)
        assert s.ring_neighbors() == []

    def test_replicas_capped_by_ring_size(self):
        s = hot_tier.HotTierStore(root="/nonexistent-unused", node="h0",
                                  peers=["h0", "h1"], replicas=5)
        assert s.ring_neighbors() == ["h1"]


class TestPushRestore:
    def test_roundtrip_from_own_store(self, tmp_path):
        stores = _stores(tmp_path)
        chunks, extra = _payload(3)
        n = stores["h0"].push("global_step3", chunks, extra,
                              shard_name="shard-0.npz")
        assert n == 1                             # one ring replica
        tag, flat, header = stores["h0"].load_best()
        assert tag == "global_step3"
        assert header["extra"]["global_step"] == 3
        np.testing.assert_array_equal(flat["w"], _tree(3)["w"])
        # own-store read: no replica fetch fired
        assert fault_injection.injector.fired("replica_fetch") == 0
        assert fault_injection.injector.fired("replica_push") == 1

    def test_host_loss_restores_from_surviving_replica(self, tmp_path):
        """THE common failure: the writer host dies; its ring neighbor
        holds the replica and the resume never touches storage."""
        stores = _stores(tmp_path)
        chunks, extra = _payload(5)
        stores["h0"].push("global_step5", chunks, extra,
                          shard_name="shard-0.npz")
        hot_tier.purge_node(str(tmp_path), "h0")   # host RAM gone
        tag, flat, header = stores["h1"].load_best()
        assert tag == "global_step5"
        np.testing.assert_array_equal(flat["w"], _tree(5)["w"])
        # the restore read a REPLICA (fired) — and nothing else existed
        assert fault_injection.injector.fired("replica_fetch") >= 1

    def test_non_neighbor_cannot_restore_after_purge(self, tmp_path):
        """K=1: only the next ring neighbor holds the replica; a purge
        of both writer and neighbor loses the generation (that's what
        the durable tier is for)."""
        stores = _stores(tmp_path)
        chunks, extra = _payload(5)
        stores["h0"].push("global_step5", chunks, extra,
                          shard_name="shard-0.npz")
        hot_tier.purge_node(str(tmp_path), "h0")
        hot_tier.purge_node(str(tmp_path), "h1")
        tag, _, _ = stores["h2"].load_best()
        assert tag is None

    def test_k2_survives_double_host_loss(self, tmp_path):
        stores = _stores(tmp_path, replicas=2)
        chunks, extra = _payload(7)
        stores["h0"].push("global_step7", chunks, extra,
                          shard_name="shard-0.npz")
        hot_tier.purge_node(str(tmp_path), "h0")
        hot_tier.purge_node(str(tmp_path), "h1")
        tag, flat, _ = stores["h2"].load_best()
        assert tag == "global_step7"
        np.testing.assert_array_equal(flat["w"], _tree(7)["w"])

    def test_newest_generation_wins(self, tmp_path):
        stores = _stores(tmp_path)
        for step in (1, 2, 10):
            chunks, extra = _payload(step)
            stores["h0"].push(f"global_step{step}", chunks, extra,
                              shard_name="shard-0.npz")
        tag, _, header = stores["h0"].load_best()
        assert tag == "global_step10"              # step order, not lex
        assert header["extra"]["global_step"] == 10

    def test_multi_writer_assembly_across_stores(self, tmp_path):
        """A 2-writer world: each writer pushes ITS shard; a reader
        assembles the generation from shards scattered across stores
        (h1's own + the replica h0 pushed to it)."""
        stores = _stores(tmp_path, peers=["h0", "h1"], replicas=1)
        t0, t1 = _tree(1), {"w": np.full((4, 3), 9.0, np.float32),
                            "b": np.arange(5, dtype=np.int64)}
        # writer 0: rows 0-1 of w; writer 1: rows 2-3 (chunked layout)
        c0 = {"w#0.0": t0["w"][:2], "b#0.0": t0["b"]}
        i0 = {"w": {"shape": [4, 3], "dtype": "float32",
                    "chunks": [{"key": "w#0.0", "start": [0, 0]}]},
              "b": {"shape": [5], "dtype": "int64",
                    "chunks": [{"key": "b#0.0", "start": [0]}]}}
        c1 = {"w#1.0": t1["w"][2:]}
        i1 = {"w": {"shape": [4, 3], "dtype": "float32",
                    "chunks": [{"key": "w#1.0", "start": [2, 0]}]},
              "b": {"shape": [5], "dtype": "int64", "chunks": []}}
        ex = {"__tree_meta__": {},
              "user_extra": {"global_step": 1, "nprocs": 2}}
        stores["h0"].push("global_step1", c0, dict(ex, index=i0),
                          shard_name="shard-0.npz")
        stores["h1"].push("global_step1", c1, dict(ex, index=i1),
                          shard_name="shard-1.npz")
        hot_tier.purge_node(str(tmp_path), "h0")   # writer 0 dies
        tag, flat, _ = stores["h1"].load_best()
        assert tag == "global_step1"
        np.testing.assert_array_equal(flat["w"][:2], t0["w"][:2])
        np.testing.assert_array_equal(flat["w"][2:], t1["w"][2:])


class TestDegradation:
    def test_poisoned_replica_fetch_degrades_to_durable(self, tmp_path):
        """Acceptance variant: replicas CRC-poisoned via the
        replica_fetch fault point — the tiered load degrades to the
        durable tier and still resumes."""
        hot_root = tmp_path / "hot"
        durable = str(tmp_path / "ckpt")
        _durable_generation(durable, step=4)
        stores = _stores(hot_root)
        chunks, extra = _payload(4)
        stores["h0"].push("global_step4", chunks, extra,
                          shard_name="shard-0.npz")
        hot_tier.purge_node(str(hot_root), "h0")   # force replica reads
        fault_injection.arm("replica_fetch", fails=100)
        counters = {}
        tier, tag, flat, header = manager.load_best_tiered(
            durable, hot_store=stores["h1"], counters=counters)
        assert tier == "durable"
        assert tag == "global_step4"
        np.testing.assert_array_equal(flat["w"], _tree(4)["w"])
        assert counters["hot_fallbacks"] == 1
        assert counters["durable_restores"] == 1

    def test_crc_corrupt_replica_degrades(self, tmp_path):
        """Bit-rot in a replica file (not just an injected fetch error)
        is caught by the CRC manifest and degrades identically."""
        hot_root = tmp_path / "hot"
        durable = str(tmp_path / "ckpt")
        _durable_generation(durable, step=6)
        stores = _stores(hot_root)
        chunks, extra = _payload(6)
        stores["h0"].push("global_step6", chunks, extra,
                          shard_name="shard-0.npz")
        hot_tier.purge_node(str(hot_root), "h0")
        replica = os.path.join(str(hot_root), "h1", "global_step6",
                               "from-h0", "shard-0.npz")
        size = os.path.getsize(replica)
        with open(replica, "r+b") as f:
            f.seek(size // 2)
            chunk = f.read(4)
            f.seek(size // 2)
            f.write(bytes(b ^ 0xFF for b in chunk))
        tier, tag, _, _ = manager.load_best_tiered(
            durable, hot_store=stores["h1"])
        assert (tier, tag) == ("durable", "global_step6")

    def test_hot_restore_reads_zero_durable_files(self, tmp_path):
        """The tentpole claim, asserted mechanically: when the hot tier
        serves the restore, the durable loader is NEVER invoked."""
        hot_root = tmp_path / "hot"
        durable = str(tmp_path / "ckpt")
        _durable_generation(durable, step=2)
        stores = _stores(hot_root)
        chunks, extra = _payload(2)
        stores["h0"].push("global_step2", chunks, extra,
                          shard_name="shard-0.npz")
        durable_reads = []

        def loader(tag_dir):
            durable_reads.append(tag_dir)
            return ser.load_state(tag_dir)

        counters = {}
        tier, tag, flat, _ = manager.load_best_tiered(
            durable, hot_store=stores["h0"], loader=loader,
            counters=counters)
        assert tier == "hot" and tag == "global_step2"
        assert durable_reads == []                 # ZERO storage reads
        assert counters["hot_restores"] == 1
        assert counters.get("durable_restores", 0) == 0

    def test_empty_hot_tier_goes_straight_to_durable(self, tmp_path):
        durable = str(tmp_path / "ckpt")
        _durable_generation(durable, step=1)
        store = hot_tier.HotTierStore(root=str(tmp_path / "hot"),
                                      node="h0", peers=PEERS)
        counters = {}
        tier, tag, _, _ = manager.load_best_tiered(
            durable, hot_store=store, counters=counters)
        assert (tier, tag) == ("durable", "global_step1")
        # an EMPTY hot tier is not a fallback (nothing was lost)
        assert counters.get("hot_fallbacks", 0) == 0

    def test_nothing_anywhere_returns_none(self, tmp_path):
        store = hot_tier.HotTierStore(root=str(tmp_path / "hot"),
                                      node="h0", peers=PEERS)
        tier, tag, flat, header = manager.load_best_tiered(
            str(tmp_path / "ckpt"), hot_store=store)
        assert tier is None and tag is None


class TestPushFaults:
    def test_replica_push_failure_is_advisory(self, tmp_path):
        """A failed peer push can never cost the save: the local entry
        still lands, the error is counted, nothing raises."""
        counters = {}
        stores = _stores(tmp_path, counters=counters)
        fault_injection.arm("replica_push", fails=100)
        chunks, extra = _payload(3)
        n = stores["h0"].push("global_step3", chunks, extra,
                              shard_name="shard-0.npz")
        assert n == 0                              # no replica landed
        assert counters["hot_push_errors"] == 1
        # own store still restorable
        tag, _, _ = stores["h0"].load_best()
        assert tag == "global_step3"
        # ...but the ring neighbor holds nothing after the writer dies
        hot_tier.purge_node(str(tmp_path), "h0")
        assert stores["h1"].load_best()[0] is None

    def test_push_async_swallows_advisory_failures(self, tmp_path):
        stores = _stores(tmp_path)
        fault_injection.arm("replica_push", fails=1)
        chunks, extra = _payload(3)
        fut = stores["h0"].push_async("global_step3", chunks, extra,
                                      shard_name="shard-0.npz")
        assert stores["h0"].wait() is True         # no raise
        assert fut.exception() is None
        stores["h0"].shutdown()

    def test_kill_during_push_propagates(self, tmp_path):
        """SimulatedKill models SIGKILL: no advisory swallow."""
        stores = _stores(tmp_path)
        fault_injection.arm("replica_push", kill=True)
        chunks, extra = _payload(3)
        with pytest.raises(fault_injection.SimulatedKill):
            stores["h0"].push("global_step3", chunks, extra,
                              shard_name="shard-0.npz")


class TestRetentionAndCandidates:
    def test_hot_gc_keeps_newest(self, tmp_path):
        stores = _stores(tmp_path, keep_last=2)
        for step in range(1, 6):
            chunks, extra = _payload(step)
            stores["h0"].push(f"global_step{step}", chunks, extra,
                              shard_name="shard-0.npz")
        own = sorted(os.listdir(os.path.join(str(tmp_path), "h0")))
        assert own == ["global_step4", "global_step5"]

    def test_tiered_candidates_order_hot_first(self, tmp_path):
        hot_root = tmp_path / "hot"
        durable = str(tmp_path / "ckpt")
        _durable_generation(durable, step=1)
        _durable_generation(durable, step=2)
        stores = _stores(hot_root)
        chunks, extra = _payload(2)
        stores["h0"].push("global_step2", chunks, extra,
                          shard_name="shard-0.npz")
        cands = manager.load_candidates(durable,
                                        hot_store=stores["h0"])
        assert cands[0] == ("hot", "global_step2")
        assert ("durable", "global_step2") in cands
        assert ("durable", "global_step1") in cands
        assert [t for t, _ in cands] == sorted(
            [t for t, _ in cands], key=lambda t: t != "hot")

    def test_legacy_candidates_shape_unchanged(self, tmp_path):
        durable = str(tmp_path / "ckpt")
        _durable_generation(durable, step=1)
        assert manager.load_candidates(durable) == ["global_step1"]

    def test_stale_hot_generation_never_rolls_back_durable(
            self, tmp_path):
        """The advisory push can lag the durable commit (async pool,
        push failure): a hot tier holding only step 2 after step 3
        durably committed must NOT serve step 2 — that would silently
        roll a committed generation back."""
        hot_root = tmp_path / "hot"
        durable = str(tmp_path / "ckpt")
        _durable_generation(durable, step=2)
        _durable_generation(durable, step=3)     # committed, never pushed
        stores = _stores(hot_root)
        chunks, extra = _payload(2)
        stores["h0"].push("global_step2", chunks, extra,
                          shard_name="shard-0.npz")
        cands = manager.load_candidates(durable, hot_store=stores["h0"])
        assert ("hot", "global_step2") not in cands   # filtered as stale
        counters = {}
        tier, tag, _, header = manager.load_best_tiered(
            durable, hot_store=stores["h0"], counters=counters)
        assert (tier, tag) == ("durable", "global_step3")
        assert header["extra"]["global_step"] == 3
        # a filtered-out stale tier is not a DEGRADATION
        assert counters.get("hot_fallbacks", 0) == 0

    def test_hot_newer_than_durable_latest_is_served(self, tmp_path):
        """The inverse: the durable commit of step 4 never landed but
        the replicas did — the newest trained state wins."""
        hot_root = tmp_path / "hot"
        durable = str(tmp_path / "ckpt")
        _durable_generation(durable, step=3)
        stores = _stores(hot_root)
        chunks, extra = _payload(4)
        stores["h0"].push("global_step4", chunks, extra,
                          shard_name="shard-0.npz")
        tier, tag, _, header = manager.load_best_tiered(
            durable, hot_store=stores["h0"])
        assert (tier, tag) == ("hot", "global_step4")
        assert header["extra"]["global_step"] == 4


class TestSlicePlacement:
    """Tentpole (a): slice-aware replica placement — pushes target
    OTHER-slice peers first, with cross-slice provenance burned into
    the receiving directory name."""

    def test_cross_slice_neighbors_first(self, tmp_path):
        for replicas, want in ((1, ["h2"]), (2, ["h2", "h3"]),
                               (3, ["h2", "h3", "h1"])):
            s = hot_tier.HotTierStore(root=str(tmp_path), node="h0",
                                      peers=PEERS, replicas=replicas,
                                      slices=SLICES)
            assert s.ring_neighbors() == want

    def test_without_slice_map_ring_order_unchanged(self, tmp_path):
        s = hot_tier.HotTierStore(root=str(tmp_path), node="h0",
                                  peers=PEERS, replicas=2)
        assert s.ring_neighbors() == ["h1", "h2"]   # PR-7 behavior

    def test_cross_slice_push_lands_with_provenance(self, tmp_path):
        counters = {}
        stores = _stores(tmp_path, slices=SLICES, counters=counters)
        chunks, extra = _payload(3)
        n = stores["h0"].push("global_step3", chunks, extra,
                              shard_name="shard-0.npz")
        assert n == 1
        assert os.path.exists(os.path.join(
            str(tmp_path), "h2", "global_step3", "replica-from-h0",
            "shard-0.npz"))
        assert counters["replica_pushes"] == 1

    def test_slices_parsed_from_env(self, tmp_path, monkeypatch):
        monkeypatch.setenv("DSTPU_HOT_SLICES", "0,0,1,1")
        s = hot_tier.HotTierStore(root=str(tmp_path), node="h3",
                                  peers=PEERS, replicas=1)
        assert s.slice_aware and s.slice == "1"
        assert s.ring_neighbors() == ["h0"]         # other slice first

    def test_slice_loss_kill_fires_at_push_boundary(self, tmp_path):
        """Arming slice_loss with kill models the whole slice dying at
        the save boundary: the (fatal-class) kill propagates out of the
        push entry point instead of being swallowed."""
        stores = _stores(tmp_path, slices=SLICES)
        fault_injection.arm("slice_loss", kill=True)
        chunks, extra = _payload(3)
        with pytest.raises(fault_injection.SimulatedKill):
            stores["h0"].push_async("global_step3", chunks, extra,
                                    shard_name="shard-0.npz")

    def test_slice_loss_never_fires_without_slices(self, tmp_path):
        stores = _stores(tmp_path)                  # no slice map
        fault_injection.arm("slice_loss", kill=True)
        chunks, extra = _payload(3)
        stores["h0"].push_async("global_step3", chunks, extra,
                                shard_name="shard-0.npz")
        assert stores["h0"].wait() is True
        assert fault_injection.injector.fired("slice_loss") == 0
        stores["h0"].shutdown()

    def test_dcn_partition_is_advisory(self, tmp_path, monkeypatch):
        """A DCN partition during the collective push is counted and
        swallowed — the durable save at that barrier still lands (the
        own-store write precedes the exchange and survives)."""
        import jax
        counters = {}
        stores = _stores(tmp_path, slices=SLICES, counters=counters)
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        fault_injection.arm("dcn_partition", fails=1)
        chunks, extra = _payload(3)
        n = stores["h0"].push_collective("global_step3", chunks, extra,
                                         shard_name="shard-0.npz")
        assert n == 0
        assert counters["hot_push_errors"] == 1
        tag, _, _ = stores["h0"].load_best()
        assert tag == "global_step3"


class TestReplicaTier:
    """Tentpole (a)+(b): the cross-slice replica as a first-class
    restore tier — a WHOLE-slice loss restores from the surviving
    slice's replica-from-* shards (or the registered MiCS zero-replica)
    with zero persistent-storage reads."""

    def _lose_slice0(self, hot_root):
        hot_tier.purge_node(str(hot_root), "h0")
        hot_tier.purge_node(str(hot_root), "h1")

    def test_slice_loss_restores_from_replica_tier(self, tmp_path):
        hot_root = tmp_path / "hot"
        durable = str(tmp_path / "ckpt")
        _durable_generation(durable, step=5)
        stores = _stores(hot_root, slices=SLICES)
        chunks, extra = _payload(5)
        stores["h0"].push("global_step5", chunks, extra,
                          shard_name="shard-0.npz")
        self._lose_slice0(hot_root)
        durable_reads = []

        def loader(tag_dir):
            durable_reads.append(tag_dir)
            return ser.load_state(tag_dir)

        counters = {}
        tier, tag, flat, _ = manager.load_best_tiered(
            durable, hot_store=stores["h2"], loader=loader,
            counters=counters)
        assert (tier, tag) == ("replica", "global_step5")
        np.testing.assert_array_equal(flat["w"], _tree(5)["w"])
        assert durable_reads == []                 # ZERO storage reads
        assert counters["replica_restores"] == 1
        assert counters.get("durable_restores", 0) == 0
        assert fault_injection.injector.fired("replica_restore") >= 1

    def test_zero_replica_set_is_a_restore_source(self, tmp_path):
        """The registered MiCS zero-replica restores the surviving
        slice from its OWN subtree even when no cross-slice push ever
        landed."""
        hot_root = tmp_path / "hot"
        counters = {}
        stores = _stores(hot_root, slices=SLICES, counters=counters)
        chunks, index, meta = ser.extract_local_chunks(_tree(8))
        rextra = {"index": index, "__tree_meta__": meta,
                  "user_extra": {"global_step": 8,
                                 "zero_replica": True}}
        assert stores["h2"].push_zero_replica(
            "global_step8", chunks, rextra) is True
        assert counters["replica_pushes"] == 1
        self._lose_slice0(hot_root)
        hot, replica = stores["h2"].tier_tags()
        assert (hot, replica) == ([], ["global_step8"])
        tier, tag, flat, _ = manager.load_best_tiered(
            str(tmp_path / "ckpt"), hot_store=stores["h2"],
            counters=counters)
        assert (tier, tag) == ("replica", "global_step8")
        np.testing.assert_array_equal(flat["w"], _tree(8)["w"])

    def test_poisoned_replica_restore_degrades_to_durable(
            self, tmp_path):
        hot_root = tmp_path / "hot"
        durable = str(tmp_path / "ckpt")
        _durable_generation(durable, step=5)
        stores = _stores(hot_root, slices=SLICES)
        chunks, extra = _payload(5)
        stores["h0"].push("global_step5", chunks, extra,
                          shard_name="shard-0.npz")
        self._lose_slice0(hot_root)
        fault_injection.arm("replica_restore", fails=100)
        counters = {}
        tier, tag, _, _ = manager.load_best_tiered(
            durable, hot_store=stores["h2"], counters=counters)
        assert (tier, tag) == ("durable", "global_step5")
        assert counters["replica_fallbacks"] == 1
        assert counters.get("hot_fallbacks", 0) == 0

    def test_hot_tier_load_never_serves_replica_sources(self, tmp_path):
        """tier='hot' is a strict subset: cross-slice sources are out
        of bounds, so a hot-tier attempt over replica-only shards fails
        down-tier instead of silently crossing tiers."""
        hot_root = tmp_path / "hot"
        stores = _stores(hot_root, slices=SLICES)
        chunks, extra = _payload(5)
        stores["h0"].push("global_step5", chunks, extra,
                          shard_name="shard-0.npz")
        self._lose_slice0(hot_root)
        with pytest.raises(FileNotFoundError):
            stores["h2"].load("global_step5", tier="hot")
        flat, _ = stores["h2"].load("global_step5", tier="replica")
        np.testing.assert_array_equal(flat["w"], _tree(5)["w"])


class TestTieredOrderingProperty:
    """Satellite 3: the tiered-restore ordering property over mixed-
    staleness hot/replica/durable generations — stale in-memory
    generations (older than the durable 'latest') are dropped, newer
    ones kept, and a CRC-invalid replica degrades down-tier exactly
    once."""

    def _mixed(self, tmp_path):
        hot_root = tmp_path / "hot"
        durable = str(tmp_path / "ckpt")
        _durable_generation(durable, step=1)
        _durable_generation(durable, step=5)       # 'latest'
        stores = _stores(hot_root, slices=SLICES)
        for step in (4, 6):                        # h2's own = hot class
            chunks, extra = _payload(step)
            stores["h2"].push(f"global_step{step}", chunks, extra,
                              shard_name="shard-0.npz")
        for step in (3, 7):                        # h0 -> replica-from-h0
            chunks, extra = _payload(step)
            stores["h0"].push(f"global_step{step}", chunks, extra,
                              shard_name="shard-0.npz")
        hot_tier.purge_node(str(hot_root), "h0")
        hot_tier.purge_node(str(hot_root), "h1")
        return durable, stores["h2"]

    def test_candidate_order_and_staleness_floor(self, tmp_path):
        durable, survivor = self._mixed(tmp_path)
        cands = manager.load_candidates(durable, hot_store=survivor)
        assert cands == [("hot", "global_step6"),
                         ("replica", "global_step7"),
                         ("durable", "global_step5"),
                         ("durable", "global_step1")]
        # the property spelled out: stale hot (4) and stale replica (3)
        # dropped; replica newer than 'latest' (7) kept
        assert ("hot", "global_step4") not in cands
        assert ("replica", "global_step3") not in cands

    def test_best_tiered_serves_hot_before_replica(self, tmp_path):
        durable, survivor = self._mixed(tmp_path)
        counters = {}
        tier, tag, _, header = manager.load_best_tiered(
            durable, hot_store=survivor, counters=counters)
        assert (tier, tag) == ("hot", "global_step6")
        assert header["extra"]["global_step"] == 6
        assert counters.get("replica_restores", 0) == 0

    def test_crc_invalid_replica_degrades_exactly_once(self, tmp_path):
        """Corrupt the only replica shard: the replica tier is
        attempted, fails, and counts EXACTLY one replica_fallbacks —
        then the durable tier serves."""
        hot_root = tmp_path / "hot"
        durable = str(tmp_path / "ckpt")
        _durable_generation(durable, step=5)
        stores = _stores(hot_root, slices=SLICES)
        for step in (6, 7):
            chunks, extra = _payload(step)
            stores["h0"].push(f"global_step{step}", chunks, extra,
                              shard_name="shard-0.npz")
        hot_tier.purge_node(str(hot_root), "h0")
        hot_tier.purge_node(str(hot_root), "h1")
        for step in (6, 7):
            replica = os.path.join(
                str(hot_root), "h2", f"global_step{step}",
                "replica-from-h0", "shard-0.npz")
            size = os.path.getsize(replica)
            with open(replica, "r+b") as f:
                f.seek(size // 2)
                chunk = f.read(4)
                f.seek(size // 2)
                f.write(bytes(b ^ 0xFF for b in chunk))
        counters = {}
        tier, tag, _, _ = manager.load_best_tiered(
            durable, hot_store=stores["h2"], counters=counters)
        assert (tier, tag) == ("durable", "global_step5")
        assert counters["replica_fallbacks"] == 1  # once, not per tag
        assert counters.get("hot_fallbacks", 0) == 0


class TestReplicasClamp:
    """Satellite 1: hot_replicas (config int or autotuned winner alike
    — both flow through the constructor) is clamped to world_size - 1
    with a one-time warning."""

    def test_clamped_with_one_warning(self, tmp_path, monkeypatch):
        # the package logger runs propagate=False, so record directly
        msgs = []
        monkeypatch.setattr(
            hot_tier.logger, "warning",
            lambda m, *a, **k: msgs.append(str(m)))
        hot_tier._CLAMP_WARNED[0] = False
        s = hot_tier.HotTierStore(root=str(tmp_path), node="h0",
                                  peers=PEERS, replicas=9)
        assert s.replicas == len(PEERS) - 1
        assert len(s.ring_neighbors()) == len(PEERS) - 1
        assert sum("clamping" in m for m in msgs) == 1
        hot_tier.HotTierStore(root=str(tmp_path), node="h1",
                              peers=PEERS, replicas=9)
        assert sum("clamping" in m for m in msgs) == 1  # still once

    def test_exact_fit_not_warned(self, tmp_path, monkeypatch):
        msgs = []
        monkeypatch.setattr(
            hot_tier.logger, "warning",
            lambda m, *a, **k: msgs.append(str(m)))
        hot_tier._CLAMP_WARNED[0] = False
        s = hot_tier.HotTierStore(root=str(tmp_path), node="h0",
                                  peers=PEERS, replicas=3)
        assert s.replicas == 3
        assert not [m for m in msgs if "clamping" in m]


class TestPushBacklogBound:
    """Satellite 2: the async push backlog is bounded — a newer push of
    the same tag supersedes a queued one, total pending pushes are
    capped, and every drop is a counted advisory hot_push_errors."""

    def test_backlog_capped_drops_oldest(self, tmp_path):
        counters = {}
        stores = _stores(tmp_path, peers=["h0", "h1"],
                         counters=counters, max_inflight_pushes=2,
                         keep_last=10)
        s = stores["h0"]
        gate = threading.Event()
        s._pool.submit(gate.wait)       # occupy the single worker
        try:
            for step in range(1, 6):
                chunks, extra = _payload(step)
                s.push_async(f"global_step{step}", chunks, extra,
                             shard_name="shard-0.npz")
                assert len(s._inflight) <= 2       # the bound holds
        finally:
            gate.set()
        assert counters["hot_push_errors"] == 3    # 3 oldest dropped
        assert s.wait() is True
        # only the surviving newest pushes ever wrote
        own = sorted(os.listdir(os.path.join(str(tmp_path), "h0")))
        assert own == ["global_step4", "global_step5"]
        s.shutdown()

    def test_newer_same_tag_supersedes_queued(self, tmp_path):
        counters = {}
        stores = _stores(tmp_path, peers=["h0", "h1"],
                         counters=counters, max_inflight_pushes=4)
        s = stores["h0"]
        gate = threading.Event()
        s._pool.submit(gate.wait)
        try:
            c1, e1 = _payload(1)
            c2, e2 = _payload(2)
            s.push_async("global_stepX", c1, e1,
                         shard_name="shard-0.npz")
            s.push_async("global_stepX", c2, e2,
                         shard_name="shard-0.npz")
            assert counters["hot_push_errors"] == 1
            assert sum(1 for t, _ in s._inflight
                       if t == "global_stepX") == 1
        finally:
            gate.set()
        assert s.wait() is True
        _, _, header = s.load_best()
        assert header["extra"]["global_step"] == 2  # the NEWER payload
        s.shutdown()

    def test_running_push_is_never_dropped(self, tmp_path):
        """Only queued (cancellable) futures can be dropped — a push
        already executing survives even over the cap."""
        counters = {}
        stores = _stores(tmp_path, peers=["h0", "h1"],
                         counters=counters, max_inflight_pushes=1,
                         keep_last=10)
        s = stores["h0"]
        chunks, extra = _payload(1)
        s.push_async("global_step1", chunks, extra,
                     shard_name="shard-0.npz")
        assert s.wait() is True
        # the push ran (nothing to supersede it) and landed
        assert s.load_best()[0] == "global_step1"
        s.shutdown()
