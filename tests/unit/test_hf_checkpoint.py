"""HF checkpoint ingestion parity: our converted models must reproduce
transformers' logits on the same weights.

Mirrors the reference's HF-model inference tests
(tests/unit/inference/test_inference.py model matrix) — but stronger:
instead of golden strings, exact logit parity vs the torch forward on a
randomly initialized model of each supported family, saved and reloaded
through the real safetensors path (no network; models are constructed
from config classes offline).
"""

import numpy as np
import pytest

import jax.numpy as jnp

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

from deepspeed_tpu.checkpoint.hf import load_pretrained  # noqa: E402

# compile-heavy: excluded from the fast core set (pytest -m 'not slow')
pytestmark = pytest.mark.slow



def _roundtrip(tmp_path, hf_model, inputs, atol=2e-3):
    """Save hf_model, ingest via load_pretrained, compare logits fp32."""
    d = str(tmp_path / "model")
    hf_model.save_pretrained(d, safe_serialization=True)
    hf_model.eval()
    with torch.no_grad():
        ref = hf_model(torch.tensor(inputs)).logits.float().numpy()

    model, params = load_pretrained(d, dtype="float32")
    logits = np.asarray(model.apply(params, jnp.asarray(inputs)),
                        np.float32)
    np.testing.assert_allclose(logits, ref, atol=atol, rtol=1e-3)
    return model, params


@pytest.fixture
def inputs():
    rng = np.random.RandomState(0)
    return rng.randint(0, 200, (2, 24)).astype(np.int32)


class TestHFIngestion:
    def test_gpt2(self, tmp_path, inputs):
        cfg = transformers.GPT2Config(
            vocab_size=512, n_positions=64, n_embd=64, n_layer=2, n_head=4)
        _roundtrip(tmp_path, transformers.GPT2LMHeadModel(cfg), inputs)

    def test_opt(self, tmp_path, inputs):
        cfg = transformers.OPTConfig(
            vocab_size=512, hidden_size=64, num_hidden_layers=2,
            ffn_dim=256, num_attention_heads=4,
            max_position_embeddings=64, do_layer_norm_before=True,
            word_embed_proj_dim=64, activation_function="relu")
        _roundtrip(tmp_path, transformers.OPTForCausalLM(cfg), inputs)

    def test_llama(self, tmp_path, inputs):
        cfg = transformers.LlamaConfig(
            vocab_size=512, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=64,
            attention_bias=False, tie_word_embeddings=False)
        _roundtrip(tmp_path, transformers.LlamaForCausalLM(cfg), inputs)

    def test_llama_attention_bias(self, tmp_path, inputs):
        cfg = transformers.LlamaConfig(
            vocab_size=512, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=64,
            attention_bias=True, tie_word_embeddings=False)
        model = transformers.LlamaForCausalLM(cfg)
        # random (not zero) biases so dropping them would fail the parity
        with torch.no_grad():
            for layer in model.model.layers:
                for m in (layer.self_attn.q_proj, layer.self_attn.k_proj,
                          layer.self_attn.v_proj):
                    m.bias.normal_(std=0.5)
        _roundtrip(tmp_path, model, inputs)

    def test_mistral_sliding_window_parity(self, tmp_path):
        # seq (48) > window (16): the window binds, HF masks beyond it —
        # our converted model must reproduce the windowed logits
        cfg = transformers.MistralConfig(
            vocab_size=512, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=64,
            sliding_window=16, attn_implementation="eager")
        rng = np.random.RandomState(1)
        long_inputs = rng.randint(0, 500, (2, 48)).astype(np.int32)
        model, params = _roundtrip(
            tmp_path, transformers.MistralForCausalLM(cfg), long_inputs)
        assert model.config.sliding_window == 16

    def test_mistral_sliding_window_off(self, tmp_path, inputs):
        cfg = transformers.MistralConfig(
            vocab_size=512, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=64,
            sliding_window=None)
        _roundtrip(tmp_path, transformers.MistralForCausalLM(cfg), inputs)

    def test_qwen2(self, tmp_path, inputs):
        cfg = transformers.Qwen2Config(
            vocab_size=512, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=64,
            tie_word_embeddings=False)
        _roundtrip(tmp_path, transformers.Qwen2ForCausalLM(cfg), inputs)

    def test_phi(self, tmp_path, inputs):
        cfg = transformers.PhiConfig(
            vocab_size=512, hidden_size=64, intermediate_size=256,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=4, max_position_embeddings=64,
            partial_rotary_factor=0.5, hidden_act="gelu_new")
        _roundtrip(tmp_path, transformers.PhiForCausalLM(cfg), inputs)

    def test_falcon(self, tmp_path, inputs):
        cfg = transformers.FalconConfig(
            vocab_size=512, hidden_size=64, num_hidden_layers=2,
            num_attention_heads=4, multi_query=True,
            new_decoder_architecture=False, parallel_attn=True,
            bias=False, alibi=False, tie_word_embeddings=True)
        _roundtrip(tmp_path, transformers.FalconForCausalLM(cfg), inputs)

    def test_falcon_new_arch(self, tmp_path, inputs):
        # 40b/180b layout: grouped qkv de-interleave ((KVH, G+2, hd))
        # + separate ln_attn/ln_mlp per parallel branch
        cfg = transformers.FalconConfig(
            vocab_size=512, hidden_size=64, num_hidden_layers=2,
            num_attention_heads=4, num_kv_heads=2, multi_query=False,
            new_decoder_architecture=True, parallel_attn=True,
            bias=False, alibi=False, tie_word_embeddings=True)
        model = transformers.FalconForCausalLM(cfg)
        # distinct branch norms so tying them would fail the parity
        with torch.no_grad():
            for layer in model.transformer.h:
                layer.ln_attn.weight.normal_(1.0, 0.3)
                layer.ln_mlp.weight.normal_(1.0, 0.3)
        _roundtrip(tmp_path, model, inputs)

    def test_falcon_rw(self, tmp_path, inputs):
        # falcon-rw layout: sequential block, per-head qkv interleave,
        # ALiBi, linear biases
        cfg = transformers.FalconConfig(
            vocab_size=512, hidden_size=64, num_hidden_layers=2,
            num_attention_heads=4, multi_query=False,
            new_decoder_architecture=False, parallel_attn=False,
            bias=True, alibi=True, tie_word_embeddings=True)
        _roundtrip(tmp_path, transformers.FalconForCausalLM(cfg), inputs)

    def test_bloom_alibi(self, tmp_path, inputs):
        cfg = transformers.BloomConfig(
            vocab_size=512, hidden_size=64, n_layer=2, n_head=4)
        _roundtrip(tmp_path, transformers.BloomForCausalLM(cfg), inputs)

    def test_mixtral(self, tmp_path, inputs):
        cfg = transformers.MixtralConfig(
            vocab_size=512, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=64,
            num_local_experts=4, num_experts_per_tok=2,
            tie_word_embeddings=False)
        _roundtrip(tmp_path, transformers.MixtralForCausalLM(cfg), inputs)

    def test_gptj(self, tmp_path, inputs):
        # shared-LN parallel block, interleaved (rotate_every_two)
        # partial rotary, biased fc/lm_head over plain q/k/v/out
        cfg = transformers.GPTJConfig(
            vocab_size=512, n_positions=64, n_embd=64, n_layer=2,
            n_head=4, rotary_dim=8, n_inner=None)
        _roundtrip(tmp_path, transformers.GPTJForCausalLM(cfg), inputs)

    def test_gpt_neo(self, tmp_path, inputs):
        # unscaled scores + alternating global/local attention layers
        # (seq 24 > window 8 so the local mask binds)
        cfg = transformers.GPTNeoConfig(
            vocab_size=512, max_position_embeddings=64, hidden_size=64,
            num_layers=2, num_heads=4, window_size=8,
            attention_types=[[["global", "local"], 1]])
        _roundtrip(tmp_path, transformers.GPTNeoForCausalLM(cfg), inputs)

    def test_gpt_neox(self, tmp_path, inputs):
        # per-head-interleaved fused qkv de-interleave, two-LN parallel
        # residual, biased blocks with a bias-free embed_out
        cfg = transformers.GPTNeoXConfig(
            vocab_size=512, hidden_size=64, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=256,
            max_position_embeddings=64, rotary_pct=0.5,
            use_parallel_residual=True)
        _roundtrip(tmp_path, transformers.GPTNeoXForCausalLM(cfg), inputs)

    def test_gpt_neox_sequential(self, tmp_path, inputs):
        # pythia-style use_parallel_residual=False loads as sequential
        cfg = transformers.GPTNeoXConfig(
            vocab_size=512, hidden_size=64, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=256,
            max_position_embeddings=64, rotary_pct=0.25,
            use_parallel_residual=False)
        _roundtrip(tmp_path, transformers.GPTNeoXForCausalLM(cfg), inputs)

    def test_internlm(self, tmp_path, inputs):
        # InternLM v1 = llama + biased q/k/v/o (its config says
        # bias: true). transformers has no offline InternLM class, so
        # build the equivalent HF llama (attention_bias biases exactly
        # q/k/v/o), save it, and rewrite the dir as an internlm
        # checkpoint: model_type + internlm config keys; weight names
        # are identical (model.layers.N.self_attn...)
        import json
        import os
        cfg = transformers.LlamaConfig(
            vocab_size=512, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=4, max_position_embeddings=64,
            attention_bias=True, tie_word_embeddings=False)
        model = transformers.LlamaForCausalLM(cfg)
        with torch.no_grad():
            for layer in model.model.layers:
                for m in (layer.self_attn.q_proj, layer.self_attn.k_proj,
                          layer.self_attn.v_proj, layer.self_attn.o_proj):
                    m.bias.normal_(std=0.5)
        d = str(tmp_path / "model")
        model.save_pretrained(d, safe_serialization=True)
        model.eval()
        with torch.no_grad():
            ref = model(torch.tensor(inputs)).logits.float().numpy()
        with open(os.path.join(d, "config.json")) as f:
            c = json.load(f)
        c["model_type"] = "internlm"
        c["bias"] = True
        with open(os.path.join(d, "config.json"), "w") as f:
            json.dump(c, f)
        m, params = load_pretrained(d, dtype="float32")
        logits = np.asarray(m.apply(params, jnp.asarray(inputs)),
                            np.float32)
        np.testing.assert_allclose(logits, ref, atol=2e-3, rtol=1e-3)
        from deepspeed_tpu.models.internlm import InternLM
        assert isinstance(m, InternLM)

    def test_serve_real_weights_greedy_parity(self, tmp_path, inputs):
        # end to end: HF dir -> build_hf_engine (v2 paged serving) ->
        # greedy decode must reproduce transformers' greedy continuation
        from deepspeed_tpu.inference import build_hf_engine
        cfg = transformers.GPT2Config(
            vocab_size=512, n_positions=96, n_embd=64, n_layer=2, n_head=4)
        hf_model = transformers.GPT2LMHeadModel(cfg)
        d = str(tmp_path / "model")
        hf_model.save_pretrained(d, safe_serialization=True)
        hf_model.eval()

        prompt = inputs[:1, :16]
        with torch.no_grad():
            ref = hf_model.generate(
                torch.tensor(prompt), max_new_tokens=8, do_sample=False,
                pad_token_id=0)[0, 16:].numpy()

        eng = build_hf_engine(d, dtype="float32")
        rid = eng.put(prompt[0].tolist(), max_new_tokens=8,
                      temperature=0.0)
        while not eng.is_done(rid):
            eng.step()
        got = np.asarray(eng.get(rid))
        np.testing.assert_array_equal(got, ref)

    def test_save_16bit_model_roundtrip_gpt2(self, tmp_path, inputs):
        # train (ZeRO-2) -> save_16bit_model -> transformers loads the
        # exported dir -> logits match the engine's own forward
        # (reference engine.py:3625 save_16bit_model)
        import deepspeed_tpu
        from deepspeed_tpu.models import GPT2, GPT2Config
        from deepspeed_tpu.utils import groups
        groups.reset()
        cfg = GPT2Config(n_layer=2, n_head=4, d_model=64, max_seq_len=64,
                         vocab_size=512, remat=False, dtype="float32")
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=GPT2(cfg),
            config={"train_micro_batch_size_per_gpu": 2,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "steps_per_print": 0,
                    "zero_optimization": {"stage": 2}})
        bsz = engine.config.train_batch_size
        batch = {"input_ids": np.tile(inputs[:1, :32], (bsz, 1))}
        for _ in range(2):
            engine.train_batch(batch)
        d = str(tmp_path / "export")
        engine.save_16bit_model(d, dtype="float32")
        ours = np.asarray(
            engine.model.apply(engine.state["params"],
                               jnp.asarray(inputs)), np.float32)
        hf = transformers.GPT2LMHeadModel.from_pretrained(d)
        hf.eval()
        with torch.no_grad():
            theirs = hf(torch.tensor(inputs)).logits.float().numpy()
        np.testing.assert_allclose(theirs, ours, atol=2e-3, rtol=1e-3)
        groups.reset()

    def test_export_llama_roundtrip(self, tmp_path, inputs):
        # init -> export_hf -> transformers load -> logit parity (the
        # inverse of convert_llama, GQA + untied head)
        import jax
        from deepspeed_tpu.checkpoint.hf_export import export_hf
        from deepspeed_tpu.models import Llama, LlamaConfig
        cfg = LlamaConfig(n_layer=2, n_head=4, n_kv_heads=2, d_model=64,
                          max_seq_len=64, vocab_size=512, remat=False,
                          dtype="float32")
        model = Llama(cfg)
        params = model.init(jax.random.key(3))
        d = str(tmp_path / "export")
        export_hf(model, params, d, dtype="float32")
        ours = np.asarray(model.apply(params, jnp.asarray(inputs)),
                          np.float32)
        hf = transformers.LlamaForCausalLM.from_pretrained(d)
        hf.eval()
        with torch.no_grad():
            theirs = hf(torch.tensor(inputs)).logits.float().numpy()
        np.testing.assert_allclose(theirs, ours, atol=2e-3, rtol=1e-3)
        # and back through our own loader (full circle)
        m2, p2 = load_pretrained(d, dtype="float32")
        again = np.asarray(m2.apply(p2, jnp.asarray(inputs)), np.float32)
        np.testing.assert_allclose(again, ours, atol=1e-4)

    def test_export_gpt_neox_roundtrip(self, tmp_path, inputs):
        # exercises the per-head qkv re-interleave inverse
        import jax
        from deepspeed_tpu.checkpoint.hf_export import export_hf
        from deepspeed_tpu.models import GPTNeoX, GPTNeoXConfig
        cfg = GPTNeoXConfig(n_layer=2, n_head=4, n_kv_heads=4, d_model=64,
                            max_seq_len=64, vocab_size=512, remat=False,
                            rotary_pct=0.5, dtype="float32")
        model = GPTNeoX(cfg)
        params = model.init(jax.random.key(4))
        # distinct non-zero biases so a broken qkv bias re-interleave
        # (e.g. concatenation instead of per-head interleave) fails
        r = np.random.RandomState(7)
        for k in ("bq", "bk", "bv", "bo", "bup", "bdown"):
            params["blocks"][k] = jnp.asarray(
                r.normal(0, 0.5, params["blocks"][k].shape), jnp.float32)
        d = str(tmp_path / "export")
        export_hf(model, params, d, dtype="float32")
        ours = np.asarray(model.apply(params, jnp.asarray(inputs)),
                          np.float32)
        hf = transformers.GPTNeoXForCausalLM.from_pretrained(d)
        hf.eval()
        with torch.no_grad():
            theirs = hf(torch.tensor(inputs)).logits.float().numpy()
        np.testing.assert_allclose(theirs, ours, atol=2e-3, rtol=1e-3)

    def test_unsupported_type_raises(self, tmp_path):
        import json
        import os
        d = tmp_path / "model"
        os.makedirs(d)
        with open(d / "config.json", "w") as f:
            json.dump({"model_type": "t5"}, f)
        with pytest.raises(ValueError, match="unsupported model_type"):
            load_pretrained(str(d))


class TestGPTJNullRotaryDim:
    """HF configs may carry an explicit ``"rotary_dim": null`` — that
    means full-head rotary (same as the key being absent), and must not
    crash the converter with a None / int division."""

    def _convert(self, hf_extra):
        from deepspeed_tpu.checkpoint.hf import convert_gptj
        L, D, H, V, T = 2, 64, 4, 128, 32
        F = 4 * D
        hf = dict({"n_layer": L, "n_embd": D, "n_head": H,
                   "vocab_size": V, "n_positions": T}, **hf_extra)
        r = np.random.RandomState(0)
        sd = {"transformer.wte.weight": r.randn(V, D).astype(np.float32),
              "transformer.ln_f.weight": np.ones(D, np.float32),
              "transformer.ln_f.bias": np.zeros(D, np.float32),
              "lm_head.weight": r.randn(V, D).astype(np.float32),
              "lm_head.bias": np.zeros(V, np.float32)}
        for i in range(L):
            lp = f"transformer.h.{i}."
            for nm in ("q_proj", "k_proj", "v_proj", "out_proj"):
                sd[lp + f"attn.{nm}.weight"] = \
                    r.randn(D, D).astype(np.float32)
            sd[lp + "mlp.fc_in.weight"] = r.randn(F, D).astype(np.float32)
            sd[lp + "mlp.fc_in.bias"] = np.zeros(F, np.float32)
            sd[lp + "mlp.fc_out.weight"] = r.randn(D, F).astype(np.float32)
            sd[lp + "mlp.fc_out.bias"] = np.zeros(D, np.float32)
            sd[lp + "ln_1.weight"] = np.ones(D, np.float32)
            sd[lp + "ln_1.bias"] = np.zeros(D, np.float32)
        return convert_gptj(hf, sd, dtype="float32")

    def test_null_rotary_dim_means_full_head(self):
        cfg_null, _ = self._convert({"rotary_dim": None})
        cfg_abs, _ = self._convert({})
        assert cfg_null.rotary_pct == 1.0
        assert cfg_abs.rotary_pct == 1.0

    def test_explicit_rotary_dim_still_partial(self):
        cfg, _ = self._convert({"rotary_dim": 8})
        assert cfg.rotary_pct == 8 / 16

    def test_zero_rotary_dim_means_no_rotary(self):
        cfg, _ = self._convert({"rotary_dim": 0})
        assert cfg.rotary_pct == 0.0
