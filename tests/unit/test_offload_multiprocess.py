"""Multi-process ZeRO-Offload: a 2-process jax.distributed CPU ring
trains with offload_optimizer and matches the single-process loss
(reference stage_1_and_2.py:1181 — every DP rank cpu-steps its own
partition at any world size).

Processes are real (subprocess + jax.distributed rendezvous on
localhost), mirroring the reference's DistributedExec multi-process
harness (tests/unit/common.py:105)."""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

# multi-process spawn: excluded from the fast core set
pytestmark = pytest.mark.slow

_WORKER = r"""
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count={ndev}"
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coordinator_address={coord!r},
                           num_processes={nproc},
                           process_id={pid})
import numpy as np
import deepspeed_tpu
from deepspeed_tpu.models import GPT2, PRESETS
from deepspeed_tpu.utils import groups

groups.reset()
model = GPT2(PRESETS["tiny"])
engine, _, _, _ = deepspeed_tpu.initialize(
    model=model,
    config={{"train_micro_batch_size_per_gpu": 1,
             "steps_per_print": 0,
             "optimizer": {{"type": "AdamW", "params": {{"lr": 1e-3}}}},
             "bf16": {{"enabled": True}},
             "zero_optimization": {{"stage": 2,
                                    "offload_optimizer":
                                        {{"device": "cpu"}}}}}})
rng = np.random.RandomState(0)
bsz = engine.config.train_batch_size
batch = {{"input_ids": rng.randint(0, 1024, (bsz, 128)).astype(np.int32)}}
losses = [float(engine.train_batch(batch)) for _ in range(4)]
if jax.process_index() == 0:
    print("LOSSES=" + json.dumps(losses))
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_world(nproc):
    coord = f"127.0.0.1:{_free_port()}"
    procs = []
    for pid in range(nproc):
        code = _WORKER.format(repo=REPO, coord=coord, nproc=nproc,
                              pid=pid, ndev=2 // nproc)
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.pop("JAX_PLATFORMS", None)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", code], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = [p.communicate(timeout=600) for p in procs]
    for p, (so, se) in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{se[-3000:]}"
    for so, _ in outs:
        for line in so.splitlines():
            if line.startswith("LOSSES="):
                return json.loads(line[len("LOSSES="):])
    raise AssertionError("no LOSSES line from rank 0")


@pytest.mark.xfail(
    __import__("jax").__version_info__ < (0, 6),
    reason="legacy jaxlib CPU backend cannot compile multiprocess "
           "computations at all ('Multiprocess computations aren't "
           "implemented on the CPU backend' from the first jitted init "
           "with non-addressable out_shardings) — an environment limit, "
           "not an offload bug; passes on driver jax >= 0.9 whose CPU "
           "collectives run cross-process",
    strict=False)
@pytest.mark.slow
def test_two_process_offload_matches_single():
    # same global batch (2 x micro 1 vs 1 x ... both dp=2 over 2 devices;
    # the 2-process run splits the SAME 2-device mesh across processes)
    multi = _run_world(2)
    single = _run_world(1)
    assert len(multi) == 4 and len(single) == 4
    np.testing.assert_allclose(multi, single, rtol=2e-4, atol=2e-4)
    assert multi[-1] < multi[0]          # it actually trains
