"""Compression tests (reference tests/unit/compression/test_compression.py
scaled to the functional design)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import deepspeed_tpu
from deepspeed_tpu.compression import (CompressionManager, init_compression,
                                       redundancy_clean, ops)
from deepspeed_tpu.models import GPT2, GPT2Config


TINY = GPT2Config(n_layer=2, n_head=4, d_model=32, max_seq_len=32,
                  vocab_size=64, remat=False, dtype="float32")


class TestOps:
    def test_quantize_weight_levels(self):
        w = jnp.asarray(np.random.RandomState(0).randn(64, 64),
                        jnp.float32)
        q = ops.quantize_weight(w, bits=4)
        # 4-bit symmetric: at most 16 distinct values per tensor
        assert len(np.unique(np.asarray(q))) <= 16
        # 8-bit is closer to the original than 4-bit
        e8 = np.abs(np.asarray(ops.quantize_weight(w, bits=8)) - w).mean()
        e4 = np.abs(np.asarray(q) - w).mean()
        assert e8 < e4

    def test_quantize_ste_gradient(self):
        """Backward must be identity (straight-through)."""
        w = jnp.asarray(np.random.RandomState(1).randn(32, 32), jnp.float32)
        g = jax.grad(lambda w: jnp.sum(ops.quantize_weight(w, bits=4)))(w)
        np.testing.assert_allclose(np.asarray(g), 1.0)

    def test_sparse_mask_ratio(self):
        w = jnp.asarray(np.random.RandomState(2).randn(40, 40), jnp.float32)
        m = ops.sparse_mask(w, ratio=0.75)
        assert abs(np.asarray(m).mean() - 0.25) < 0.01
        # keeps the largest magnitudes
        kept = np.abs(np.asarray(w))[np.asarray(m)]
        dropped = np.abs(np.asarray(w))[~np.asarray(m)]
        assert kept.min() >= dropped.max() - 1e-6

    def test_row_mask_structure(self):
        w = jnp.asarray(np.random.RandomState(3).randn(16, 8), jnp.float32)
        m = np.asarray(ops.row_mask(w, ratio=0.5, axis=0))
        rows = m.all(axis=1) | (~m).any(axis=1)
        assert rows.all()                       # each row all-true or all-false
        assert m.all(axis=1).sum() == 8         # half the rows kept

    def test_head_mask_structure(self):
        w = jnp.asarray(np.random.RandomState(4).randn(24, 12), jnp.float32)
        m = np.asarray(ops.head_mask(w, ratio=0.5, num_heads=4,
                                     head_axis=0))
        # 4 heads of 6 rows: exactly 2 heads survive, whole
        per_head = m.reshape(4, 6, 12)
        head_on = per_head.all(axis=(1, 2))
        head_off = (~per_head).all(axis=(1, 2))
        assert (head_on | head_off).all() and head_on.sum() == 2

    def test_quantize_activation(self):
        x = jnp.asarray(np.random.RandomState(5).randn(128), jnp.float32)
        q = ops.quantize_activation(x, bits=8)
        assert np.abs(np.asarray(q) - np.asarray(x)).max() < 0.05


CONFIG = {
    "compression_training": {
        "weight_quantization": {
            "shared_parameters": {"enabled": True, "schedule_offset": 0,
                                  "quantization_type": "symmetric"},
            "different_groups": {
                "wq": {"params": {"target_bits": 8},
                       "modules": ["blocks/wqkv", "blocks/wup"]}}},
        "sparse_pruning": {
            "shared_parameters": {"enabled": True, "schedule_offset": 0},
            "different_groups": {
                "sp": {"params": {"dense_ratio": 0.5},
                       "modules": ["blocks/wdown"]}}},
    }
}


class TestManager:
    def test_plan_matches_patterns(self):
        model = GPT2(TINY)
        params = model.init(jax.random.key(0))
        mgr = CompressionManager(CONFIG, example_params=params)
        assert "blocks/wqkv" in mgr.plan
        assert "blocks/wup" in mgr.plan
        assert "blocks/wdown" in mgr.plan
        assert "wte" not in mgr.plan

    def test_transform_applies(self):
        model = GPT2(TINY)
        params = model.init(jax.random.key(0))
        mgr = CompressionManager(CONFIG, example_params=params)
        out = mgr.transform(params)
        # wdown: half zeroed
        frac = (np.asarray(out["blocks"]["wdown"]) == 0).mean()
        assert abs(frac - 0.5) < 0.02
        # untouched tensors identical
        np.testing.assert_array_equal(np.asarray(out["wte"]),
                                      np.asarray(params["wte"]))

    def test_schedule_offset_gates(self):
        cfg = {"compression_training": {"weight_quantization": {
            "shared_parameters": {"enabled": True, "schedule_offset": 10},
            "different_groups": {"g": {"params": {"target_bits": 4},
                                       "modules": ["blocks/wqkv"]}}}}}
        model = GPT2(TINY)
        params = model.init(jax.random.key(0))
        mgr = CompressionManager(cfg, example_params=params)
        before = mgr.transform(params, step=5)
        np.testing.assert_array_equal(
            np.asarray(before["blocks"]["wqkv"]),
            np.asarray(params["blocks"]["wqkv"]))
        after = mgr.transform(params, step=10)
        assert not np.array_equal(np.asarray(after["blocks"]["wqkv"]),
                                  np.asarray(params["blocks"]["wqkv"]))

    def test_wrapped_model_trains(self):
        from deepspeed_tpu.utils import groups
        groups.reset()
        model, mgr = init_compression(GPT2(TINY), CONFIG)
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model,
            config={"train_micro_batch_size_per_gpu": 1,
                    "optimizer": {"type": "AdamW", "params": {"lr": 2e-3}},
                    "steps_per_print": 0})
        data = np.random.RandomState(0).randint(
            0, 64, (engine.config.train_batch_size, 32)).astype(np.int32)
        losses = [float(engine.train_batch({"input_ids": data}))
                  for _ in range(6)]
        assert losses[-1] < losses[0]
        # masters stay dense (masked only in forward)
        master_wdown = np.asarray(
            jax.device_get(engine.state["master"]["blocks"]["wdown"]))
        assert (master_wdown == 0).mean() < 0.1

    def test_redundancy_clean_bakes(self):
        model = GPT2(TINY)
        params = model.init(jax.random.key(0))
        mgr = CompressionManager(CONFIG, example_params=params)
        cleaned = redundancy_clean(params, mgr)
        assert (np.asarray(cleaned["blocks"]["wdown"]) == 0).mean() > 0.4


class TestQuantizeGroupsSemantics:
    def test_group_count_semantics(self):
        """quantize_groups=1 (the default) must be per-tensor quantization,
        NOT a per-element no-op."""
        import jax
        from deepspeed_tpu.compression import CompressionManager
        cfg = {"compression_training": {"weight_quantization": {
            "shared_parameters": {"enabled": True, "schedule_offset": 0,
                                  "quantize_groups": 1},
            "different_groups": {"g": {"params": {"target_bits": 4},
                                       "modules": ["blocks/wqkv"]}}}}}
        model = GPT2(TINY)
        params = model.init(jax.random.key(0))
        mgr = CompressionManager(cfg, example_params=params)
        out = mgr.transform(params)
        q = np.asarray(out["blocks"]["wqkv"])
        assert not np.array_equal(q, np.asarray(params["blocks"]["wqkv"]))
        assert len(np.unique(q)) <= 16
