"""BERT encoder + DeepSpeedTransformerLayer parity.

Mirrors the reference's transformer-kernel parity suite
(tests/unit/ops/transformer/ — fused CUDA encoder vs vendored HF BERT,
forward AND backward): here the fused layer's numerics are pinned
against an INDEPENDENT dense jnp encoder implementing the textbook
post-LN BERT block, and the encoder model trains end to end through the
engine."""

import math

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models.bert import Bert, BertConfig, BERT_TINY
from deepspeed_tpu.ops.transformer.transformer import (
    DeepSpeedTransformerConfig, DeepSpeedTransformerLayer)
from deepspeed_tpu.utils import groups

# compile-heavy: excluded from the fast core set (pytest -m 'not slow')
pytestmark = pytest.mark.slow



def _reference_block(params, x, mask, *, pre_ln, eps):
    """Independent textbook BERT block (post-LN default): written from
    the BERT equations, NOT from the layer under test."""
    D = x.shape[-1]

    def ln(h, s, b):
        h32 = h.astype(jnp.float32)
        mu = h32.mean(-1, keepdims=True)
        var = ((h32 - mu) ** 2).mean(-1, keepdims=True)
        return ((h32 - mu) / jnp.sqrt(var + eps)) * s + b

    h = ln(x, params["ln1_scale"], params["ln1_bias"]) if pre_ln else x
    B, T = x.shape[0], x.shape[1]
    qkv = h @ params["wqkv"] + params["bqkv"]
    H = 4
    hd = D // H
    q, k, v = [qkv[..., i * D:(i + 1) * D].reshape(B, T, H, hd)
               for i in range(3)]
    s = jnp.einsum("bthd,bshd->bhts", q, k) / math.sqrt(hd)
    if mask is not None:
        s = s + jnp.where(mask[:, None, None, :], 0.0, -1e30)
    p = jax.nn.softmax(s, -1)
    attn = jnp.einsum("bhts,bshd->bthd", p, v).reshape(B, T, D)
    attn_out = attn @ params["wo"] + params["bo"]
    if pre_ln:
        x = x + attn_out
        h2 = ln(x, params["ln2_scale"], params["ln2_bias"])
        mlp = jax.nn.gelu(h2 @ params["wi"] + params["bi"]) \
            @ params["wout"] + params["bout"]
        return x + mlp
    x = ln(x + attn_out, params["ln1_scale"], params["ln1_bias"])
    mlp = jax.nn.gelu(x @ params["wi"] + params["bi"]) \
        @ params["wout"] + params["bout"]
    return ln(x + mlp, params["ln2_scale"], params["ln2_bias"])


class TestLayerParity:
    @pytest.mark.parametrize("pre_ln", [False, True])
    def test_forward_and_backward_match_reference(self, pre_ln):
        cfg = DeepSpeedTransformerConfig(
            hidden_size=64, heads=4, pre_layer_norm=pre_ln,
            layer_norm_eps=1e-12, dtype="float32")
        layer = DeepSpeedTransformerLayer(cfg)
        params = layer.init(jax.random.key(0))
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(2, 16, 64), jnp.float32) * 0.5
        mask = jnp.asarray(rng.rand(2, 16) > 0.2)

        got = layer(params, x, mask=mask)
        want = _reference_block(params, x, mask, pre_ln=pre_ln,
                                eps=cfg.layer_norm_eps)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

        def loss_f(p, x):
            return jnp.sum(layer(p, x, mask=mask).astype(jnp.float32)
                           ** 2)

        def loss_r(p, x):
            return jnp.sum(_reference_block(
                p, x, mask, pre_ln=pre_ln,
                eps=cfg.layer_norm_eps).astype(jnp.float32) ** 2)

        gp, gx = jax.grad(loss_f, (0, 1))(params, x)
        rp, rx = jax.grad(loss_r, (0, 1))(params, x)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(rx),
                                   rtol=1e-4, atol=1e-4)
        for key in gp:
            np.testing.assert_allclose(
                np.asarray(gp[key]), np.asarray(rp[key]),
                rtol=1e-4, atol=1e-4, err_msg=key)


class TestBertModel:
    def test_param_count(self):
        m = Bert(BERT_TINY)
        params = m.init(jax.random.key(0))
        n = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
        assert n == BERT_TINY.num_params()

    def test_mask_isolates_padding(self):
        m = Bert(BERT_TINY)
        params = m.init(jax.random.key(0))
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 512, (1, 32)).astype(np.int32)
        mask = np.ones((1, 32), bool)
        mask[0, 20:] = False
        h1 = m.apply(params, jnp.asarray(ids),
                     attention_mask=jnp.asarray(mask))
        ids2 = ids.copy()
        ids2[0, 20:] = 7            # change only masked-out positions
        h2 = m.apply(params, jnp.asarray(ids2),
                     attention_mask=jnp.asarray(mask))
        np.testing.assert_allclose(np.asarray(h1[:, :20]),
                                   np.asarray(h2[:, :20]),
                                   rtol=1e-5, atol=1e-5)

    def test_flash_mask_matches_dense(self):
        # padding masks ride the flash kernel's additive-bias input:
        # the flash encoder must reproduce the dense encoder exactly
        from dataclasses import replace
        dense = Bert(BERT_TINY)
        flash = Bert(replace(BERT_TINY, use_flash_attention=True))
        params = dense.init(jax.random.key(0))
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 512, (2, 48)).astype(np.int32)
        mask = np.ones((2, 48), bool)
        mask[0, 30:] = False
        mask[1, 10:] = False
        h0 = dense.apply(params, jnp.asarray(ids),
                         attention_mask=jnp.asarray(mask))
        h1 = flash.apply(params, jnp.asarray(ids),
                         attention_mask=jnp.asarray(mask))
        np.testing.assert_allclose(np.asarray(h0), np.asarray(h1),
                                   rtol=1e-4, atol=1e-4)
        # and the MLM loss gradient
        batch = {"input_ids": jnp.asarray(ids),
                 "attention_mask": jnp.asarray(mask)}
        g0 = jax.grad(lambda p: dense.loss(p, batch, train=False))(params)
        g1 = jax.grad(lambda p: flash.loss(p, batch, train=False))(params)
        for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-4)

    def test_trains_through_engine(self):
        groups.reset()
        m = Bert(BERT_TINY)
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=m, config={"train_micro_batch_size_per_gpu": 2,
                             "steps_per_print": 0,
                             "optimizer": {"type": "AdamW",
                                           "params": {"lr": 1e-3}},
                             "zero_optimization": {"stage": 2}})
        rng = np.random.RandomState(0)
        bsz = engine.config.train_batch_size
        batch = {"input_ids": rng.randint(1, 512, (bsz, 64))
                 .astype(np.int32)}
        losses = [float(engine.train_batch(batch)) for _ in range(4)]
        assert losses[-1] < losses[0]
