"""Elastic agent: world supervision, membership-change restart, and
checkpoint-resume recovery (reference elasticity/elastic_agent.py:28
DSElasticAgent + bin/ds_elastic)."""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from deepspeed_tpu.elasticity.elastic_agent import (DSElasticAgent,
                                                    WorldFailure)

# compile-heavy: excluded from the fast core set (pytest -m 'not slow')
pytestmark = pytest.mark.slow


def _mock_launch(script_for_host):
    """launch_fn that runs a small python script per host."""
    def launch(hosts):
        procs = []
        for h in hosts:
            procs.append((h, subprocess.Popen(
                [sys.executable, "-c", script_for_host(h, len(hosts))])))
        return procs
    return launch


class TestAgentSupervision:
    def test_clean_world_exits_once(self):
        agent = DSElasticAgent(
            _mock_launch(lambda h, n: "import time; time.sleep(0.1)"),
            ["a", "b", "c"], poll_s=0.05)
        final = agent.run()
        assert final == ["a", "b", "c"]
        assert agent.restart_count == 0

    def test_membership_change_restarts_without_failed_host(self):
        events = []

        def script(host, n):
            # host 'b' fails in the first generation only (n==3)
            if host == "b" and n == 3:
                return "raise SystemExit(1)"
            return "import time; time.sleep(0.2)"

        agent = DSElasticAgent(
            _mock_launch(script), ["a", "b", "c"], poll_s=0.05,
            on_restart=lambda gen, hosts: events.append((gen, hosts)))
        final = agent.run()
        assert final == ["a", "c"]
        assert agent.restart_count == 1
        assert events == [(1, ["a", "c"])]

    def test_restart_budget(self):
        # exactly one host dies per generation; budget of 1 restart is
        # exhausted by the second failure
        def script(h, n):
            dies = {3: "a", 2: "b", 1: "c"}[n]
            if h == dies:
                return "raise SystemExit(1)"
            return "import time; time.sleep(0.2)"

        agent = DSElasticAgent(
            _mock_launch(script), ["a", "b", "c"], poll_s=0.05,
            max_restarts=1)
        with pytest.raises(WorldFailure, match="budget"):
            agent.run()

    def test_elastic_config_gates_world_size(self):
        """A shrunken world outside the admissible chip set aborts instead
        of silently training with an invalid batch configuration."""
        ds_config = {"elasticity": {
            "enabled": True, "max_train_batch_size": 64,
            "micro_batch_sizes": [4], "min_gpus": 1, "max_gpus": 16,
            "version": 0.2, "num_gpus_per_node": 2}}
        # 3 hosts x 2 chips = 6 admissible; 2 hosts x 2 = 4 admissible;
        # after TWO failures 1 host = 2 chips... also admissible; force
        # inadmissibility via min_hosts instead for determinism
        agent = DSElasticAgent(
            _mock_launch(lambda h, n: "raise SystemExit(1)"),
            ["a", "b"], ds_config=ds_config, chips_per_host=3,
            poll_s=0.05, min_hosts=1)
        # world 2*3=6 valid; after one failure 1*3=3 -> not a multiple of
        # num_gpus_per_node=2 and not in valid set -> WorldFailure
        with pytest.raises(WorldFailure, match="admissible"):
            agent.run()


class TestKillAHostResume:
    def test_training_resumes_from_latest_checkpoint(self, tmp_path):
        """The reference recovery model end to end: generation 0 loses a
        worker mid-run; the agent relaunches the survivors, which resume
        from the engine's durable-latest checkpoint and finish."""
        ckpt_dir = tmp_path / "ckpt"
        log = tmp_path / "steps.log"
        worker = tmp_path / "worker.py"
        worker.write_text(textwrap.dedent(f"""
            import os, sys
            os.environ["JAX_PLATFORMS"] = "cpu"
            os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=2").strip()
            sys.path.insert(0, {str(os.getcwd())!r})
            import jax
            jax.config.update("jax_platforms", "cpu")
            try:    # newer-jax spelling; XLA_FLAGS above covers older
                jax.config.update("jax_num_cpu_devices", 2)
            except AttributeError:
                pass
            import numpy as np
            import deepspeed_tpu
            from deepspeed_tpu.models import GPT2, GPT2Config
            from deepspeed_tpu.utils import groups

            gen = int(os.environ.get("ELASTIC_GENERATION", "0"))
            host = os.environ["WORKER_HOST"]
            cfg = GPT2Config(n_layer=1, n_head=2, d_model=32,
                             max_seq_len=16, vocab_size=64, remat=False,
                             dtype="float32")
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=GPT2(cfg),
                config={{"train_micro_batch_size_per_gpu": 2,
                         "steps_per_print": 0,
                         "optimizer": {{"type": "Adam",
                                        "params": {{"lr": 1e-3}}}},
                         "zero_optimization": {{"stage": 0}}}})
            engine.load_checkpoint({str(ckpt_dir)!r})
            rng = np.random.RandomState(0)
            batch = {{"input_ids": rng.randint(
                0, 64, (engine.config.train_batch_size, 16)).astype(
                np.int32)}}
            import time
            while engine.global_step < 5:
                engine.train_batch(batch)
                if host == "h0":     # one writer (shared-FS model)
                    engine.save_checkpoint({str(ckpt_dir)!r})
                with open({str(log)!r}, "a") as f:
                    f.write(f"{{host}} gen={{gen}} "
                            f"step={{engine.global_step}}\\n")
                if host == "h1" and gen == 0 and engine.global_step >= 2:
                    raise SystemExit(1)   # the killed host
                if host == "h0" and gen == 0:
                    time.sleep(1.5)   # slow so the failure interrupts it
        """))

        def launch(hosts):
            procs = []
            for h in hosts:
                env = dict(os.environ)
                env["WORKER_HOST"] = h
                env["ELASTIC_GENERATION"] = str(agent.restart_count)
                procs.append((h, subprocess.Popen(
                    [sys.executable, str(worker)], env=env)))
            return procs

        agent = DSElasticAgent(launch, ["h0", "h1"], poll_s=0.1)
        final = agent.run()
        assert final == ["h0"]
        assert agent.restart_count == 1
        lines = log.read_text().strip().splitlines()
        # generation 1 resumed from a checkpoint (step > 1 on its first
        # logged line) and reached step 5
        gen1 = [ln for ln in lines if "gen=1" in ln]
        assert gen1, lines
        first_resumed = int(gen1[0].split("step=")[1])
        assert first_resumed >= 2, lines   # resumed, not restarted at 1
        assert any("step=5" in ln for ln in gen1)


class TestHungHostResume:
    def test_hung_worker_triggers_restart_from_latest(self, tmp_path):
        """ISSUE 2 tentpole (4): a worker that HANGS (stops completing
        train_batches, so its DSTPU_HEARTBEAT_FILE goes stale) takes the
        SAME recovery path as one that died — the agent kills it,
        relaunches the survivors, and training resumes from the durable
        'latest' checkpoint."""
        ckpt_dir = tmp_path / "ckpt"
        log = tmp_path / "steps.log"
        worker = tmp_path / "worker.py"
        worker.write_text(textwrap.dedent(f"""
            import os, sys, time
            os.environ["JAX_PLATFORMS"] = "cpu"
            sys.path.insert(0, {str(os.getcwd())!r})
            import jax
            jax.config.update("jax_platforms", "cpu")
            import numpy as np
            import deepspeed_tpu
            from deepspeed_tpu.models import GPT2, GPT2Config

            gen = int(os.environ.get("ELASTIC_GENERATION", "0"))
            host = os.environ["WORKER_HOST"]
            cfg = GPT2Config(n_layer=1, n_head=2, d_model=32,
                             max_seq_len=16, vocab_size=64, remat=False,
                             dtype="float32")
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=GPT2(cfg),
                config={{"train_micro_batch_size_per_gpu": 2,
                         "steps_per_print": 0,
                         "optimizer": {{"type": "Adam",
                                        "params": {{"lr": 1e-3}}}},
                         "zero_optimization": {{"stage": 0}}}})
            engine.load_checkpoint({str(ckpt_dir)!r})
            rng = np.random.RandomState(0)
            batch = {{"input_ids": rng.randint(
                0, 64, (engine.config.train_batch_size, 16)).astype(
                np.int32)}}
            while engine.global_step < 4:
                engine.train_batch(batch)   # beats the heartbeat file
                if host == "h0":
                    engine.save_checkpoint({str(ckpt_dir)!r})
                with open({str(log)!r}, "a") as f:
                    f.write(f"{{host}} gen={{gen}} "
                            f"step={{engine.global_step}}\\n")
                if gen == 0 and engine.global_step >= 2:
                    if host == "h1":
                        time.sleep(3600)    # HUNG: alive, never beats
                    break   # h0: clean exit; gen 1 must RESUME from 2
        """))

        def launch(hosts):
            procs = []
            for h in hosts:
                env = dict(os.environ)
                env["WORKER_HOST"] = h
                env["ELASTIC_GENERATION"] = str(agent.restart_count)
                env["DSTPU_HEARTBEAT_FILE"] = agent.heartbeat_path(h)
                procs.append((h, subprocess.Popen(
                    [sys.executable, str(worker)], env=env)))
            return procs

        agent = DSElasticAgent(
            launch, ["h0", "h1"], poll_s=0.2,
            # generous vs. compile time: the FIRST beat lands only after
            # jit compilation; stale detection matters per-beat after
            heartbeat_timeout_s=30,
            heartbeat_dir=str(tmp_path / "hb"))
        final = agent.run()
        assert final == ["h0"]
        assert agent.restart_count == 1
        gen1 = [ln for ln in log.read_text().strip().splitlines()
                if "gen=1" in ln]
        assert gen1
        assert int(gen1[0].split("step=")[1]) >= 2   # resumed
        assert any("step=4" in ln for ln in gen1)


class TestKillHostHotTierResume:
    """ISSUE 7 acceptance: kill one host mid-training (real processes);
    the agent purges the dead host's hot-tier store and resumes the
    surviving world at dp-1 FROM THE HOT TIER — zero reads of the
    durable checkpoint dir, loss curve continuing within tolerance of
    an uninterrupted run. A second variant poisons the replicas
    (CRC-invalid via the replica_fetch fault point): the resume
    degrades to the durable tier and still continues."""

    WORKER = r"""
        import os, sys, time
        os.environ["JAX_PLATFORMS"] = "cpu"
        sys.path.insert(0, {repo!r})
        import jax
        jax.config.update("jax_platforms", "cpu")
        ndev = int(os.environ.get("WORLD_NHOSTS", "1"))
        try:
            jax.config.update("jax_num_cpu_devices", ndev)
        except AttributeError:
            os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={{ndev}}"
                ).strip()
        import numpy as np
        import deepspeed_tpu
        from deepspeed_tpu.models import GPT2, GPT2Config
        import deepspeed_tpu.runtime.checkpoint_engine.serialization \
            as ser

        gen = int(os.environ.get("ELASTIC_GENERATION", "0"))
        host = os.environ["WORKER_HOST"]
        ckpt = {ckpt!r}

        # count every durable shard read (the acceptance assertion)
        durable_reads = []
        _orig_load_file = ser.load_file
        def _counting_load_file(path, *a, **kw):
            if str(path).startswith(ckpt):
                durable_reads.append(str(path))
            return _orig_load_file(path, *a, **kw)
        ser.load_file = _counting_load_file

        cfg = GPT2Config(n_layer=1, n_head=2, d_model=32,
                         max_seq_len=16, vocab_size=64, remat=False,
                         dtype="float32")
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=GPT2(cfg),
            config={{"train_micro_batch_size_per_gpu": 2,
                     "steps_per_print": 0,
                     "optimizer": {{"type": "Adam",
                                    "params": {{"lr": 1e-3}}}},
                     "zero_optimization": {{"stage": 1}}}})
        assert (engine.hot_store is not None) == bool(
            os.environ.get("DSTPU_HOT_TIER_ROOT")), "hot tier auto"
        engine.load_checkpoint(ckpt)
        with open({log!r}, "a") as f:
            f.write(f"{{host}} gen={{gen}} resumed "
                    f"step={{engine.global_step}} "
                    f"tier={{engine.last_restore_tier}} "
                    f"durable_reads={{len(durable_reads)}}\n")
        rng = np.random.RandomState(0)
        batch = {{"input_ids": rng.randint(
            0, 64, (4, 16)).astype(np.int32)}}
        while engine.global_step < 4:
            loss = float(engine.train_batch(batch))
            if host == "h0" or gen > 0:      # single surviving writer
                engine.save_checkpoint(ckpt)
                if engine.hot_store is not None:
                    engine.hot_store.wait()
            with open({log!r}, "a") as f:
                f.write(f"{{host}} gen={{gen}} "
                        f"step={{engine.global_step}} "
                        f"loss={{loss:.6f}}\n")
            if (host == "h0" and gen == 0
                    and engine.global_step >= 2):
                raise SystemExit(1)          # the killed host
            if host == "h0" and gen == 0:
                # slow writer: h1 logs its full (uninterrupted) loss
                # trajectory before h0's death tears the world down —
                # that trajectory is the test's reference curve
                time.sleep(3.0)
    """

    def _run(self, tmp_path, poison=False):
        import textwrap
        ckpt = str(tmp_path / "ckpt")
        hot_root = str(tmp_path / "hot")
        log = tmp_path / "steps.log"
        worker = tmp_path / "worker.py"
        worker.write_text(textwrap.dedent(self.WORKER.format(
            repo=str(os.getcwd()), ckpt=ckpt, log=str(log))))

        def launch(hosts, topology):
            procs = []
            for h in hosts:
                env = dict(os.environ)
                env.update(agent.worker_env(h))
                env["WORKER_HOST"] = h
                env["ELASTIC_GENERATION"] = str(agent.restart_count)
                env["WORLD_NHOSTS"] = str(len(hosts))
                if poison and agent.restart_count > 0:
                    env["DSTPU_FAULT_INJECT"] = "replica_fetch:100"
                procs.append((h, subprocess.Popen(
                    [sys.executable, str(worker)], env=env)))
            return procs

        agent = DSElasticAgent(launch, ["h0", "h1"], poll_s=0.1,
                               hot_root=hot_root)
        final = agent.run()
        assert final == ["h1"]
        assert agent.restart_count == 1
        assert agent.last_failures == {"h0": "dead"}
        # the dead host's store is purged (its RAM died with it)
        assert not os.path.exists(os.path.join(hot_root, "h0"))
        return log.read_text().strip().splitlines()

    def test_resume_at_dp_minus_1_from_hot_tier(self, tmp_path):
        lines = self._run(tmp_path)
        resumed = [ln for ln in lines
                   if "gen=1" in ln and "resumed" in ln]
        assert resumed, lines
        # THE claim: restored from surviving replicas, ZERO durable
        # reads, at the checkpointed step
        assert "tier=hot" in resumed[0], resumed
        assert "durable_reads=0" in resumed[0], resumed
        assert int(resumed[0].split("step=")[1].split()[0]) >= 2
        # and the world finished at dp-1
        gen1 = [ln for ln in lines if "gen=1" in ln]
        assert any("step=4" in ln for ln in gen1)
        # loss curve continues within tolerance of the uninterrupted
        # run: gen-0 h1 (never killed, same seeds, same global batch)
        # IS the uninterrupted trajectory for the overlapping steps
        ref = {ln.split("step=")[1].split()[0]:
               float(ln.split("loss=")[1])
               for ln in lines if ln.startswith("h1 gen=0") and
               "loss=" in ln}
        got = {ln.split("step=")[1].split()[0]:
               float(ln.split("loss=")[1])
               for ln in lines if "gen=1" in ln and "loss=" in ln}
        shared = sorted(set(ref) & set(got))
        assert shared, (ref, got)
        for s in shared:
            np.testing.assert_allclose(got[s], ref[s], rtol=2e-4)

    def test_poisoned_replicas_degrade_to_durable(self, tmp_path):
        lines = self._run(tmp_path, poison=True)
        resumed = [ln for ln in lines
                   if "gen=1" in ln and "resumed" in ln]
        assert resumed, lines
        # replicas CRC-poisoned -> durable tier served the resume
        assert "tier=durable" in resumed[0], resumed
        assert int(resumed[0].split("step=")[1].split()[0]) >= 2
        gen1 = [ln for ln in lines if "gen=1" in ln]
        assert any("step=4" in ln for ln in gen1)


class TestSliceLossReplicaResume:
    """ISSUE 15 acceptance: a two-slice virtual mesh (one real process
    per slice, slice membership via the agent's slices map); every host
    of slice 0 dies mid-training at a save boundary via the armed
    slice_loss point. The agent classifies dead_slice, relaunches the
    surviving slice at data_outer - 1, and the resume is served by the
    cross-slice REPLICA tier with zero durable reads. The poisoned
    variant (replica_restore armed in the relaunch) degrades to the
    durable tier and still converges to the baseline loss curve."""

    WORKER = r"""
        import os, sys, time
        os.environ["JAX_PLATFORMS"] = "cpu"
        sys.path.insert(0, {repo!r})
        import jax
        jax.config.update("jax_platforms", "cpu")
        ndev = int(os.environ.get("WORLD_NHOSTS", "1"))
        try:
            jax.config.update("jax_num_cpu_devices", ndev)
        except AttributeError:
            os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={{ndev}}"
                ).strip()
        import numpy as np
        import deepspeed_tpu
        from deepspeed_tpu.models import GPT2, GPT2Config
        import deepspeed_tpu.runtime.checkpoint_engine.serialization \
            as ser

        gen = int(os.environ.get("ELASTIC_GENERATION", "0"))
        host = os.environ["WORKER_HOST"]
        ckpt = {ckpt!r}

        # count every durable shard read (the acceptance assertion)
        durable_reads = []
        _orig_load_file = ser.load_file
        def _counting_load_file(path, *a, **kw):
            if str(path).startswith(ckpt):
                durable_reads.append(str(path))
            return _orig_load_file(path, *a, **kw)
        ser.load_file = _counting_load_file

        cfg = GPT2Config(n_layer=1, n_head=2, d_model=32,
                         max_seq_len=16, vocab_size=64, remat=False,
                         dtype="float32")
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=GPT2(cfg),
            config={{"train_micro_batch_size_per_gpu": 2,
                     "steps_per_print": 0,
                     "optimizer": {{"type": "Adam",
                                    "params": {{"lr": 1e-3}}}},
                     "zero_optimization": {{"stage": 1}}}})
        if gen == 0:
            # the agent exported DSTPU_HOT_SLICE(S): pushes go
            # cross-slice, so slice 1 holds slice 0's shards
            assert engine.hot_store is not None
            assert engine.hot_store.slice_aware, "slice map not wired"
        engine.load_checkpoint(ckpt)
        with open({log!r}, "a") as f:
            f.write(f"{{host}} gen={{gen}} resumed "
                    f"step={{engine.global_step}} "
                    f"tier={{engine.last_restore_tier}} "
                    f"durable_reads={{len(durable_reads)}}\n")
        rng = np.random.RandomState(0)
        batch = {{"input_ids": rng.randint(
            0, 64, (4, 16)).astype(np.int32)}}
        while engine.global_step < 4:
            loss = float(engine.train_batch(batch))
            if host == "h0" or gen > 0:      # single writer per gen
                # slice 0's armed slice_loss (skip=2, kill) fires at
                # the THIRD save's hot-push boundary — BEFORE the
                # step-3 durable write, so durable latest stays at 2
                # and the step-2 cross-slice replica passes the
                # staleness floor
                engine.save_checkpoint(ckpt)
                if engine.hot_store is not None:
                    engine.hot_store.wait()
            with open({log!r}, "a") as f:
                f.write(f"{{host}} gen={{gen}} "
                        f"step={{engine.global_step}} "
                        f"loss={{loss:.6f}}\n")
            if host == "h0" and gen == 0:
                # slow writer: h1 logs its full (uninterrupted) loss
                # trajectory first — the test's reference curve
                time.sleep(3.0)
    """

    def _run(self, tmp_path, poison=False):
        ckpt = str(tmp_path / "ckpt")
        hot_root = str(tmp_path / "hot")
        log = tmp_path / "steps.log"
        worker = tmp_path / "worker.py"
        worker.write_text(textwrap.dedent(self.WORKER.format(
            repo=str(os.getcwd()), ckpt=ckpt, log=str(log))))

        def launch(hosts, topology):
            procs = []
            for h in hosts:
                env = dict(os.environ)
                env.update(agent.worker_env(h))
                env["WORKER_HOST"] = h
                env["ELASTIC_GENERATION"] = str(agent.restart_count)
                env["WORLD_NHOSTS"] = str(len(hosts))
                if h == "h0" and agent.restart_count == 0:
                    # the whole of slice 0 dies at its 3rd save
                    env["DSTPU_FAULT_INJECT"] = \
                        "slice_loss:1:skip=2:kill"
                if poison and agent.restart_count > 0:
                    env["DSTPU_FAULT_INJECT"] = "replica_restore:100"
                procs.append((h, subprocess.Popen(
                    [sys.executable, str(worker)], env=env)))
            return procs

        agent = DSElasticAgent(launch, ["h0", "h1"], poll_s=0.1,
                               hot_root=hot_root,
                               slices={"h0": "0", "h1": "1"})
        assert agent.topology["do"] == 2         # two-slice mesh
        final = agent.run()
        assert final == ["h1"]
        assert agent.restart_count == 1
        # the WHOLE slice died together -> dead_slice, not dead
        assert agent.last_failures == {"h0": "dead_slice"}
        assert agent.topology["do"] == 1         # data_outer shrank
        # the dead slice's store is purged (its RAM died with it)
        assert not os.path.exists(os.path.join(hot_root, "h0"))
        return log.read_text().strip().splitlines()

    def test_slice_loss_resumes_from_replica_tier(self, tmp_path):
        lines = self._run(tmp_path)
        resumed = [ln for ln in lines
                   if "gen=1" in ln and "resumed" in ln]
        assert resumed, lines
        # THE claim: the surviving slice restored from the cross-slice
        # replica, ZERO durable reads, at the replicated step
        assert "tier=replica" in resumed[0], resumed
        assert "durable_reads=0" in resumed[0], resumed
        assert int(resumed[0].split("step=")[1].split()[0]) >= 2
        gen1 = [ln for ln in lines if "gen=1" in ln]
        assert any("step=4" in ln for ln in gen1)
        # loss curve continues within tolerance of the uninterrupted
        # run (gen-0 h1: never killed, same seeds, same batch)
        ref = {ln.split("step=")[1].split()[0]:
               float(ln.split("loss=")[1])
               for ln in lines if ln.startswith("h1 gen=0") and
               "loss=" in ln}
        got = {ln.split("step=")[1].split()[0]:
               float(ln.split("loss=")[1])
               for ln in lines if "gen=1" in ln and "loss=" in ln}
        shared = sorted(set(ref) & set(got))
        assert shared, (ref, got)
        for s in shared:
            np.testing.assert_allclose(got[s], ref[s], rtol=2e-4)

    def test_poisoned_replica_degrades_to_durable(self, tmp_path):
        lines = self._run(tmp_path, poison=True)
        resumed = [ln for ln in lines
                   if "gen=1" in ln and "resumed" in ln]
        assert resumed, lines
        # replica tier poisoned -> durable served the resume, and the
        # run still converges to the baseline within tolerance
        assert "tier=durable" in resumed[0], resumed
        assert int(resumed[0].split("step=")[1].split()[0]) >= 2
        gen1 = [ln for ln in lines if "gen=1" in ln]
        assert any("step=4" in ln for ln in gen1)
        ref = {ln.split("step=")[1].split()[0]:
               float(ln.split("loss=")[1])
               for ln in lines if ln.startswith("h1 gen=0") and
               "loss=" in ln}
        got = {ln.split("step=")[1].split()[0]:
               float(ln.split("loss=")[1])
               for ln in lines if "gen=1" in ln and "loss=" in ln}
        shared = sorted(set(ref) & set(got))
        assert shared, (ref, got)
        for s in shared:
            np.testing.assert_allclose(got[s], ref[s], rtol=2e-4)


class TestPreemptDrain:
    """ISSUE 15 tentpole (c) acceptance: SIGTERM to the AGENT is
    forwarded to the worker, whose drain handler finishes the in-flight
    step, forces one fresh hot generation + a flight-recorder dump
    whose tail records the preemption, and exits PREEMPTED_EXIT_CODE —
    which the agent classifies 'preempted' and relaunches without
    backoff; the resume is served from the drained hot generation."""

    WORKER = r"""
        import os, sys, time
        os.environ["JAX_PLATFORMS"] = "cpu"
        sys.path.insert(0, {repo!r})
        import jax
        jax.config.update("jax_platforms", "cpu")
        ndev = int(os.environ.get("WORLD_NHOSTS", "1"))
        try:
            jax.config.update("jax_num_cpu_devices", ndev)
        except AttributeError:
            os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={{ndev}}"
                ).strip()
        import numpy as np
        import deepspeed_tpu
        from deepspeed_tpu.models import GPT2, GPT2Config

        gen = int(os.environ.get("ELASTIC_GENERATION", "0"))
        host = os.environ["WORKER_HOST"]
        ckpt = {ckpt!r}
        cfg = GPT2Config(n_layer=1, n_head=2, d_model=32,
                         max_seq_len=16, vocab_size=64, remat=False,
                         dtype="float32")
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=GPT2(cfg),
            config={{"train_micro_batch_size_per_gpu": 2,
                     "steps_per_print": 0,
                     "optimizer": {{"type": "Adam",
                                    "params": {{"lr": 1e-3}}}},
                     "zero_optimization": {{"stage": 1}},
                     "telemetry": {{"enabled": True,
                                    "interval_steps": 1000,
                                    "cluster_agg": False}}}})
        engine.load_checkpoint(ckpt)
        with open({log!r}, "a") as f:
            f.write(f"{{host}} gen={{gen}} resumed "
                    f"step={{engine.global_step}} "
                    f"tier={{engine.last_restore_tier}}\n")
        rng = np.random.RandomState(0)
        batch = {{"input_ids": rng.randint(
            0, 64, (engine.config.train_batch_size, 16)).astype(
            np.int32)}}
        # gen 0 runs until the forwarded SIGTERM drains it (the bound
        # only guards against a lost signal); gen 1 proves the resume
        target = 60 if gen == 0 else engine.global_step + 2
        while engine.global_step < target:
            loss = float(engine.train_batch(batch))
            engine.save_checkpoint(ckpt)
            if engine.hot_store is not None:
                engine.hot_store.wait()
            with open({log!r}, "a") as f:
                f.write(f"{{host}} gen={{gen}} "
                        f"step={{engine.global_step}} "
                        f"loss={{loss:.6f}}\n")
            if gen == 0:
                time.sleep(0.2)      # window for the SIGTERM to land
    """

    def test_sigterm_drains_and_relaunches_without_backoff(
            self, tmp_path):
        import signal
        import threading
        import time as _time
        ckpt = str(tmp_path / "ckpt")
        hot_root = str(tmp_path / "hot")
        fr_root = str(tmp_path / "fr")
        log = tmp_path / "steps.log"
        worker = tmp_path / "worker.py"
        worker.write_text(textwrap.dedent(self.WORKER.format(
            repo=str(os.getcwd()), ckpt=ckpt, log=str(log))))

        def launch(hosts, topology):
            procs = []
            for h in hosts:
                env = dict(os.environ)
                env.update(agent.worker_env(h))
                env["WORKER_HOST"] = h
                env["ELASTIC_GENERATION"] = str(agent.restart_count)
                procs.append((h, subprocess.Popen(
                    [sys.executable, str(worker)], env=env)))
            return procs

        # the corrupt-class backoff is deliberately huge: if the drain
        # exit were misclassified, the elapsed bound below would trip
        agent = DSElasticAgent(
            launch, ["h0"], poll_s=0.1, hot_root=hot_root,
            flightrec_root=fr_root,
            restart_backoff_s={"corrupt_ckpt": 300.0})

        def _fire_sigterm():
            # deliver once the worker has COMPLETED a step (handler
            # installed, a hot generation exists to drain on top of)
            deadline = _time.time() + 120
            while _time.time() < deadline:
                if log.exists() and "gen=0 step=" in log.read_text():
                    os.kill(os.getpid(), signal.SIGTERM)
                    return
                _time.sleep(0.1)

        prev = signal.getsignal(signal.SIGTERM)
        t = threading.Thread(target=_fire_sigterm)
        t0 = _time.time()
        try:
            t.start()
            final = agent.run()
        finally:
            t.join()
            signal.signal(signal.SIGTERM, prev)
        assert final == ["h0"]
        assert agent.restart_count == 1
        assert agent.last_failures == {"h0": "preempted"}
        assert _time.time() - t0 < 300           # no backoff penalty
        # the flight dump's tail records the preemption
        from deepspeed_tpu.monitor import flight_recorder
        dump = flight_recorder.read_dump(fr_root, "h0")
        assert dump is not None, "no flight dump from the drain"
        assert dump["reason"] == "preempted"
        kinds = [e["kind"] for e in dump["events"]]
        assert kinds[-1] == "preempted"
        drained = [e for e in dump["events"]
                   if e["kind"] == "preempted"][-1]
        assert drained["drained"] is True
        # the relaunch resumed from the FRESH drained hot generation
        lines = log.read_text().strip().splitlines()
        resumed = [ln for ln in lines
                   if "gen=1" in ln and "resumed" in ln]
        assert resumed, lines
        assert "tier=hot" in resumed[0], resumed
        resumed_step = int(resumed[0].split("step=")[1].split()[0])
        assert resumed_step == drained["step"], (resumed, drained)
        assert any("gen=1" in ln and "loss=" in ln for ln in lines)
