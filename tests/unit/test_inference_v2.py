"""v2 serving engine tests: blocked allocator, state manager, paged decode
parity with the dense engine, continuous batching. Reference coverage
model: tests/unit/inference/v2/ (kernels + ragged + engine)."""

import numpy as np
import jax
import pytest

import deepspeed_tpu
from deepspeed_tpu.inference.v2 import (BlockedAllocator, DSStateManager,
                                        InferenceEngineV2)
from deepspeed_tpu.models import GPT2, GPT2Config
from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.groups import TopologyConfig

# compile-heavy: excluded from the fast core set (pytest -m 'not slow')
pytestmark = pytest.mark.slow



CFG = GPT2Config(n_layer=2, n_head=4, d_model=64, max_seq_len=128,
                 vocab_size=256, remat=False, dtype="float32")


class TestBlockedAllocator:
    def test_allocate_free_cycle(self):
        a = BlockedAllocator(8)
        assert a.total_blocks == 7
        got = a.allocate(3)
        assert len(set(got)) == 3 and 0 not in got
        assert a.free_blocks == 4
        a.free(got)
        assert a.free_blocks == 7

    def test_exhaustion_raises(self):
        a = BlockedAllocator(4)
        a.allocate(3)
        with pytest.raises(RuntimeError):
            a.allocate(1)

    def test_double_free_raises(self):
        a = BlockedAllocator(4)
        got = a.allocate(2)
        a.free(got[:1])
        with pytest.raises(ValueError):
            a.free(got[:1])
        with pytest.raises(ValueError):
            a.free([0])


class TestStateManager:
    def test_admit_retire_frees_blocks(self):
        m = DSStateManager(num_blocks=9, block_size=4, max_batch=2,
                           max_blocks_per_seq=4)
        slot, seq = m.admit(1, np.arange(5), max_new_tokens=3)
        # 5+3=8 tokens -> 2 blocks
        assert len(seq.blocks) == 2
        assert m.allocator.free_blocks == 6
        m.retire(1)
        assert m.allocator.free_blocks == 8
        assert m.free_slot() == slot

    def test_can_admit_respects_blocks_and_slots(self):
        m = DSStateManager(num_blocks=5, block_size=4, max_batch=1,
                           max_blocks_per_seq=4)
        assert m.can_admit(8, 0)
        m.admit(1, np.arange(8), max_new_tokens=0)
        assert not m.can_admit(1, 0)  # no slot
        m.retire(1)
        assert m.can_admit(16, 0)
        assert not m.can_admit(16, 1)  # 17 tokens -> 5 blocks > 4 free

    def test_decode_batch_layout(self):
        m = DSStateManager(num_blocks=9, block_size=4, max_batch=3,
                           max_blocks_per_seq=2)
        _, seq = m.admit(7, np.arange(6), max_new_tokens=2)
        seq.generated.append(42)
        b = m.decode_batch()
        assert b.active.tolist() == [True, False, False]
        assert b.tokens[0] == 42
        assert b.lengths[0] == 6  # prompt in cache, new token not yet
        assert (b.block_tables[1] == 0).all()


def _v1_greedy(model, params, prompts, n):
    groups.reset()
    eng = deepspeed_tpu.init_inference(
        model, params=params, config={"dtype": "float32",
                                      "prompt_bucket": 16})
    out = eng.generate(prompts, max_new_tokens=n, temperature=0.0)
    groups.reset()
    return out


class TestEngineV2:
    def test_paged_greedy_matches_dense(self):
        model = GPT2(CFG)
        params = model.init(jax.random.key(0))
        prompts = [np.arange(5) % 256, (np.arange(9) * 3) % 256,
                   (np.arange(3) + 100) % 256]
        ref = _v1_greedy(model, params, prompts, 6)
        eng = InferenceEngineV2(model, params=params,
                                config={"dtype": "float32",
                                        "kv_block_size": 8,
                                        "prompt_bucket": 16,
                                        "max_batch_size": 4})
        outs = eng.generate_all(prompts, max_new_tokens=6)
        for i, o in enumerate(outs):
            np.testing.assert_array_equal(o, ref[i])

    def test_continuous_batching_more_requests_than_slots(self):
        model = GPT2(CFG)
        params = model.init(jax.random.key(0))
        prompts = [((np.arange(4) + 11 * i) % 256) for i in range(6)]
        eng = InferenceEngineV2(model, params=params,
                                config={"dtype": "float32",
                                        "kv_block_size": 8,
                                        "prompt_bucket": 8,
                                        "max_batch_size": 2})
        free0 = eng.state_mgr.allocator.free_blocks
        outs = eng.generate_all(prompts, max_new_tokens=5)
        ref = _v1_greedy(model, params, prompts, 5)
        for i, o in enumerate(outs):
            np.testing.assert_array_equal(o, ref[i])
        # all blocks returned to the free list
        assert eng.state_mgr.allocator.free_blocks == free0

    def test_block_boundary_crossing(self):
        """Generation crossing multiple block boundaries stays correct."""
        model = GPT2(CFG)
        params = model.init(jax.random.key(0))
        prompts = [np.arange(6) % 256]
        ref = _v1_greedy(model, params, prompts, 12)
        eng = InferenceEngineV2(model, params=params,
                                config={"dtype": "float32",
                                        "kv_block_size": 4,
                                        "prompt_bucket": 8,
                                        "max_batch_size": 2})
        outs = eng.generate_all(prompts, max_new_tokens=12)
        np.testing.assert_array_equal(outs[0], ref[0])

    def test_eos_retires_early_and_frees(self):
        model = GPT2(CFG)
        params = model.init(jax.random.key(0))
        prompt = np.arange(4) % 256
        ref = _v1_greedy(model, params, [prompt], 1)
        eos = int(ref[0, 0])  # first greedy token
        eng = InferenceEngineV2(model, params=params,
                                config={"dtype": "float32",
                                        "kv_block_size": 8,
                                        "prompt_bucket": 8,
                                        "max_batch_size": 2})
        free0 = eng.state_mgr.allocator.free_blocks
        uid = eng.put(prompt, max_new_tokens=10, eos_token_id=eos)
        while eng.has_work:
            eng.step()
        out = eng.get(uid)
        assert out.tolist() == [eos]
        assert eng.state_mgr.allocator.free_blocks == free0

    def test_tp_paged_matches_single(self):
        model = GPT2(CFG)
        params = model.init(jax.random.key(0))
        prompts = [np.arange(7) % 256]
        groups.reset()
        topo = groups.initialize(TopologyConfig(tensor_parallel_size=4))
        eng = InferenceEngineV2(model, params=params, topology=topo,
                                config={"dtype": "float32",
                                        "kv_block_size": 8,
                                        "prompt_bucket": 8,
                                        "tensor_parallel": 4})
        outs = eng.generate_all(prompts, max_new_tokens=6)
        ref = _v1_greedy(model, params, prompts, 6)
        np.testing.assert_array_equal(outs[0], ref[0])

    def test_ep_sharded_mixtral_matches_single(self):
        """EP x TP serving (reference module_inject/layers.py EP+TP
        inference MoE): mixtral experts sharded over 'expert' and
        heads/FFN over 'tensor' in the v2 decode/prefill programs must
        reproduce the single-device greedy tokens exactly."""
        from deepspeed_tpu.models.mixtral import Mixtral, MixtralConfig
        mcfg = MixtralConfig(n_layer=2, n_head=4, n_kv_heads=2,
                             d_model=64, max_seq_len=128, vocab_size=512,
                             remat=False, num_experts=4, moe_top_k=2,
                             dtype="float32")
        model = Mixtral(mcfg)
        params = model.init(jax.random.key(5))
        prompts = [np.arange(9) % 500, (np.arange(13) + 41) % 500]

        groups.reset()
        single = InferenceEngineV2(model, params=params,
                                   config={"dtype": "float32",
                                           "kv_block_size": 16,
                                           "max_batch_size": 2})
        ref = single.generate_all(prompts, max_new_tokens=6)

        groups.reset()
        eng = InferenceEngineV2(model, params=params,
                                config={"dtype": "float32",
                                        "kv_block_size": 16,
                                        "max_batch_size": 2,
                                        "tensor_parallel": 2,
                                        "expert_parallel": 2})
        outs = eng.generate_all(prompts, max_new_tokens=6)
        for a, b in zip(ref, outs):
            np.testing.assert_array_equal(a, b)

    def test_ep_splitfuse_mixtral_matches_single(self):
        """EP serving through the SplitFuse chunk program: mixtral at
        expert_parallel=2 with chunked prefill must reproduce the
        single-shard greedy tokens — the chunk program's expert FFN
        routes through the ragged EP all_to_all path too (the PR-5
        GSPMD ragged_dot mis-partition fix)."""
        from deepspeed_tpu.models.mixtral import Mixtral, MixtralConfig
        mcfg = MixtralConfig(n_layer=2, n_head=4, n_kv_heads=2,
                             d_model=64, max_seq_len=128, vocab_size=512,
                             remat=False, num_experts=4, moe_top_k=2,
                             dtype="float32")
        model = Mixtral(mcfg)
        params = model.init(jax.random.key(5))
        prompts = [np.arange(20) % 500, (np.arange(7) + 41) % 500]
        base = {"dtype": "float32", "kv_block_size": 16,
                "max_batch_size": 2, "splitfuse_tokens": 16}

        groups.reset()
        single = InferenceEngineV2(model, params=params,
                                   config=dict(base))
        ref = single.generate_all(prompts, max_new_tokens=5)

        groups.reset()
        eng = InferenceEngineV2(model, params=params,
                                config=dict(base, expert_parallel=2))
        outs = eng.generate_all(prompts, max_new_tokens=5)
        for a, b in zip(ref, outs):
            np.testing.assert_array_equal(a, b)


class TestPerRequestSampling:
    def test_mixed_greedy_and_sampled_batch(self):
        """Greedy and sampled requests share one decode program; greedy
        rows must match the all-greedy reference exactly."""
        model = GPT2(CFG)
        params = model.init(jax.random.key(0))
        prompts = [np.arange(5) % 256, (np.arange(7) * 3) % 256]
        ref = _v1_greedy(model, params, [prompts[0]], 6)
        eng = InferenceEngineV2(model, params=params,
                                config={"dtype": "float32",
                                        "kv_block_size": 8,
                                        "prompt_bucket": 16,
                                        "max_batch_size": 2})
        u_greedy = eng.put(prompts[0], max_new_tokens=6)  # default greedy
        u_sampled = eng.put(prompts[1], max_new_tokens=6,
                            temperature=1.0, top_k=50)
        while eng.has_work:
            eng.step()
        out_g = eng.get(u_greedy)
        out_s = eng.get(u_sampled)
        np.testing.assert_array_equal(out_g, ref[0])
        assert out_s.shape == (6,)
        assert np.isfinite(out_s).all()

    def test_sampled_differs_across_requests(self):
        model = GPT2(CFG)
        params = model.init(jax.random.key(0))
        eng = InferenceEngineV2(model, params=params,
                                config={"dtype": "float32",
                                        "kv_block_size": 8,
                                        "prompt_bucket": 8,
                                        "max_batch_size": 4})
        prompt = np.arange(4) % 256
        uids = [eng.put(prompt, max_new_tokens=8, temperature=1.2,
                        top_k=0) for _ in range(3)]
        while eng.has_work:
            eng.step()
        outs = [eng.get(u).tolist() for u in uids]
        # independent rng per step + per slot: all three identical would
        # mean per-slot sampling is broken
        assert len({tuple(o) for o in outs}) > 1, outs


class TestSplitFuse:
    """Dynamic SplitFuse (reference blogs/deepspeed-fastgen §3B): prompts
    stream through fixed-size chunk programs fused with running decodes
    — same outputs as the bucketed-prefill engine, no head-of-line
    blocking, one compiled program for every prompt length."""

    def _engines(self, chunk=16, **kw):
        model = GPT2(CFG)
        params = model.init(jax.random.key(0))
        groups.reset()
        legacy = InferenceEngineV2(
            model, params=params,
            config=dict({"dtype": "float32", "kv_block_size": 8,
                         "prompt_bucket": 16, "max_batch_size": 4}, **kw))
        groups.reset()
        sf = InferenceEngineV2(
            model, params=params,
            config=dict({"dtype": "float32", "kv_block_size": 8,
                         "prompt_bucket": 16, "max_batch_size": 4,
                         "splitfuse_tokens": chunk}, **kw))
        return legacy, sf

    def test_chunked_matches_bucketed_greedy(self):
        # prompts spanning <1 chunk, exactly 1 chunk, and several chunks
        rng = np.random.RandomState(0)
        prompts = [rng.randint(0, 256, (n,)).astype(np.int32)
                   for n in (5, 16, 37, 50)]
        legacy, sf = self._engines(chunk=16)
        want = legacy.generate_all(prompts, max_new_tokens=6)
        got = sf.generate_all(prompts, max_new_tokens=6)
        for w, g in zip(want, got):
            np.testing.assert_array_equal(g, w)

    def test_no_head_of_line_blocking(self):
        """A running decode keeps producing tokens at every scheduler
        step WHILE a long prompt chunk-prefills (the legacy engine
        stalls decodes for the whole bucketed prefill)."""
        legacy, sf = self._engines(chunk=16)
        rng = np.random.RandomState(1)
        a = sf.put(rng.randint(0, 256, (6,)), max_new_tokens=24)
        sf.step()                      # admit + first chunk finishes A's
        while not np.asarray(sf.get(a)).size:
            sf.step()                  # A now decoding
        long_prompt = rng.randint(0, 256, (64,))   # 4 chunks of 16
        b = sf.put(long_prompt, max_new_tokens=4)

        def b_prefilling():
            return (any(r.uid == b for r in sf._pending)
                    or b in sf._prefill_q)

        a_tokens_during_prefill = 0
        chunk_steps = 0
        while b_prefilling():
            out = sf.step()
            chunk_steps += 1
            a_tokens_during_prefill += sum(1 for uid, _ in out if uid == a)
        assert chunk_steps >= 4        # the prompt really streamed
        # A produced decode tokens during EVERY chunk dispatch
        assert a_tokens_during_prefill >= chunk_steps

    def test_splitfuse_single_program(self):
        """All prompt lengths share ONE fused compilation (the legacy
        path compiles one prefill per bucket)."""
        _, sf = self._engines(chunk=16)
        rng = np.random.RandomState(2)
        sf.generate_all([rng.randint(0, 256, (n,))
                         for n in (3, 20, 40)], max_new_tokens=2)
        fused = sf._splitfuse_jit
        assert fused is not None
        # every dispatch reused the same traced program: one compiled
        # signature despite three different prompt lengths
        if callable(getattr(fused, "_cache_size", None)):
            assert fused._cache_size() == 1
        # and the legacy bucketed prefill never ran
        assert sf._prefill_jit is None

    def test_splitfuse_sampled_requests(self):
        # temperature>0 paths through the fused program still work and
        # respect per-request sampling state
        legacy, sf = self._engines(chunk=16)
        rng = np.random.RandomState(3)
        p = rng.randint(0, 256, (20,)).astype(np.int32)
        uid = sf.put(p, max_new_tokens=5, temperature=0.8)
        while not sf.is_done(uid):
            sf.step()
        toks = sf.get(uid)
        assert toks.shape == (5,)
        assert (toks >= 0).all() and (toks < 256).all()
