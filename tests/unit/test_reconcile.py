"""Modeled-vs-measured reconciliation tests (ISSUE 13): the
two-direction planner<->tracer vocabulary lint, drift-report pairing,
the seed-cache -> changed ``calibrate_links`` golden, the reconcile
CLI, the telemetry/flight wiring, and the dp=2 virtual-mesh end-to-end
profiled run."""

import gzip
import inspect
import json
import os
import re

import numpy as np
import pytest

import deepspeed_tpu  # noqa: F401 - compat shims before jax use
import jax

from deepspeed_tpu.autotuning import planner, reconcile
from deepspeed_tpu.autotuning.kernel_cache import KernelCache
from deepspeed_tpu.autotuning.planner import (
    ModelDesc, PodDesc, calibrate_links)
from deepspeed_tpu.profiling import step_trace
from deepspeed_tpu.profiling.step_trace import StepDecomposition


def _model():
    return ModelDesc(params=1 << 20, n_layer=2, d_model=64, n_head=4,
                     max_seq_len=128, name="test")


def _pod(**kw):
    kw.setdefault("n_chips", 8)
    kw.setdefault("hbm_bytes", 1 << 34)
    return PodDesc(**kw)


def _decomp(**kw):
    terms = {k: 0.0 for k in step_trace.DECOMP_TERMS}
    terms.update({"compute": 10.0, "grad_reduce": 2.0,
                  "tp_reduce": 1.0})
    kw.setdefault("terms", terms)
    kw.setdefault("unmodeled", {"copy_layout": 0.5})
    kw.setdefault("total_device_ms", sum(kw["terms"].values()) + 0.5)
    kw.setdefault("coverage_pct", 96.3)
    kw.setdefault("collectives", [
        {"op": "all-reduce", "term": "grad_reduce", "axes": ["data"],
         "leg": "ici", "count_per_step": 2, "total_ms": 4.0,
         "exposed_ms": 2.0, "hidden_ms": 2.0},
        {"op": "all-reduce", "term": "tp_reduce", "axes": ["tensor"],
         "leg": "ici", "count_per_step": 4, "total_ms": 2.0,
         "exposed_ms": 1.0, "hidden_ms": 1.0},
    ])
    kw.setdefault("kernels", {"flash_attention": 3.0})
    return StepDecomposition(**kw)


# ------------------------------------------------------ vocabulary lint
class TestVocabularyLint:
    """The test_planner_lint.py discipline: the planner's ``_score``
    terms and the tracer's decomposition keys may never silently
    diverge — both directions are greped from source, not trusted."""

    def _score_terms_from_source(self):
        src = inspect.getsource(planner._score)
        found = set(re.findall(r'terms\["(\w+)"\]', src))
        found |= set(re.findall(r'terms = \{"(\w+)"', src))
        return found

    def test_score_terms_constant_matches_score_source(self):
        assert self._score_terms_from_source() == \
            set(planner.SCORE_TERMS), (
                "planner.SCORE_TERMS is out of sync with the terms "
                "_score actually emits — update the constant (and the "
                "tracer/reconciler vocabulary with it)")

    def test_every_score_term_maps_to_a_decomposition_key(self):
        assert set(reconcile.TERM_MAP) == set(planner.SCORE_TERMS)
        for term, key in reconcile.TERM_MAP.items():
            assert key in step_trace.DECOMP_TERMS, (
                f"_score term {term!r} maps to {key!r} which the "
                f"tracer never measures")

    def test_every_decomposition_key_maps_back_or_is_unmodeled(self):
        modeled = set(reconcile.TERM_MAP.values())
        for key in step_trace.DECOMP_TERMS:
            assert key in modeled, (
                f"decomposition key {key!r} reaches no _score term and "
                f"is not declared in step_trace.UNMODELED_KEYS")
        assert set(step_trace.UNMODELED_KEYS).isdisjoint(modeled)
        # tuple-level identity keeps ordering honest too
        assert tuple(planner.SCORE_TERMS) == step_trace.DECOMP_TERMS


# -------------------------------------------------------- drift report
class TestDriftReport:
    def test_every_term_gets_a_measured_row(self):
        rep = reconcile.reconcile(
            _decomp(), _model(), _pod(),
            {"data": 2, "tensor": 4}, batch_tokens=16 * 128)
        assert {r["term"] for r in rep.rows} == set(planner.SCORE_TERMS)
        by_term = {r["term"]: r for r in rep.rows}
        assert by_term["compute"]["measured_ms"] == pytest.approx(10.0)
        # an unexercised term pairs 0 modeled against 0 measured
        assert by_term["expert_a2a"]["measured_ms"] == 0.0
        for r in rep.rows:
            assert r["drift_ms"] == pytest.approx(
                r["measured_ms"] - r["modeled_ms"], abs=1e-6)

    def test_rows_ranked_by_absolute_drift(self):
        rep = reconcile.reconcile(
            _decomp(), _model(), _pod(),
            {"data": 2, "tensor": 4}, batch_tokens=16 * 128)
        drifts = [abs(r["drift_ms"]) for r in rep.rows]
        assert drifts == sorted(drifts, reverse=True)
        assert rep.top()["term"] == rep.rows[0]["term"]

    def test_summary_is_telemetry_shaped(self):
        rep = reconcile.reconcile(
            _decomp(), _model(), _pod(),
            {"data": 2, "tensor": 4}, batch_tokens=16 * 128)
        s = rep.summary()
        assert set(s) == {"top_term", "top_term_index", "top_drift_ms",
                          "wall_err_pct", "coverage_pct",
                          "modeled_wall_ms", "measured_wall_ms",
                          "steps"}
        assert planner.SCORE_TERMS[s["top_term_index"]] == s["top_term"]
        assert s["coverage_pct"] == pytest.approx(96.3)

    def test_table_lists_unmodeled_time(self):
        rep = reconcile.reconcile(
            _decomp(), _model(), _pod(),
            {"data": 2, "tensor": 4}, batch_tokens=16 * 128)
        text = rep.table()
        assert "copy_layout" in text and "(unmodeled)" in text
        for term in planner.SCORE_TERMS:
            assert term in text

    def test_to_dict_round_trips_json(self):
        rep = reconcile.reconcile(
            _decomp(), _model(), _pod(),
            {"data": 2, "tensor": 4}, batch_tokens=16 * 128)
        parsed = json.loads(json.dumps(rep.to_dict()))
        assert parsed["mesh"]["tensor"] == 4
        assert len(parsed["rows"]) == len(planner.SCORE_TERMS)


# ------------------------------------------------------------- seeding
class TestSeeding:
    def _report(self, pod):
        d = _decomp()
        rep = reconcile.reconcile(d, _model(), pod,
                                  {"data": 2, "tensor": 4},
                                  batch_tokens=16 * 128)
        rep._model = _model()
        rep._batch_tokens = 16 * 128
        return d, rep

    def test_seed_rows_shape(self):
        d, rep = self._report(_pod(device_kind="TestChip"))
        rows = reconcile.seed_rows(d, rep, device_kind="TestChip")
        ops = {r["op"] for r in rows}
        assert ops == {"comm_link", "op_cost"}
        link = [r for r in rows if r["op"] == "comm_link"]
        # only the ICI leg carried measured time in the fixture
        assert len(link) == 1 and link[0]["params"]["kind"] == "ici"
        assert link[0]["bucket"] == \
            "pp1,do1,dp2,ep1,sp1,tp4,kici"
        assert link[0]["params"]["source"] == "reconcile"
        assert link[0]["params"]["beta_gbps"] > 0
        costs = {r["params"]["op"]: r["params"]["ms_per_step"]
                 for r in rows if r["op"] == "op_cost"}
        assert costs["flash_attention"] == pytest.approx(3.0)
        assert costs["compute_step"] == pytest.approx(10.0)

    def test_seeding_changes_calibrate_links(self, tmp_path):
        """The ISSUE-13 golden: measured comm_link rows round-trip into
        a DIFFERENT calibrate_links result than the nominal fallback —
        the planner now prices meshes from measured numbers."""
        pod = _pod(device_kind="TestChip")
        baseline = calibrate_links(pod, cache=KernelCache())
        d, rep = self._report(pod)
        rows = reconcile.seed_rows(d, rep, device_kind="TestChip")
        path = str(tmp_path / "cache.json")
        assert reconcile.seed_cache(rows, path=path) == len(rows)
        seeded = calibrate_links(pod, cache=KernelCache.load(path))
        assert seeded["ici"] != baseline["ici"], (
            "seeded comm_link row did not change the ICI calibration")
        # beta is the measured-effective one from the seeded row
        row = [r for r in rows if r["op"] == "comm_link"][0]
        assert seeded["ici"][1] == pytest.approx(
            row["params"]["beta_gbps"] * 1e9)

    def test_device_kind_refusal_intact(self, tmp_path):
        """A cache measured on one chip must never calibrate another."""
        pod = _pod(device_kind="TestChip")
        d, rep = self._report(pod)
        rows = reconcile.seed_rows(d, rep, device_kind="TestChip")
        path = str(tmp_path / "cache.json")
        reconcile.seed_cache(rows, path=path)
        other = _pod(device_kind="OtherChip")
        got = calibrate_links(other, cache=KernelCache.load(path))
        assert got == calibrate_links(other, cache=KernelCache())

    def test_pseudo_ops_stay_out_of_the_registry(self):
        """comm_link/op_cost are cache-file-only: REGISTRY and the knob
        table must never learn them (test_autotune asserts REGISTRY ==
        _BUCKETS; this is the same fence from the other side)."""
        from deepspeed_tpu.autotuning.kernel_registry import REGISTRY
        assert "comm_link" not in REGISTRY
        assert "op_cost" not in REGISTRY


# ---------------------------------------------------------------- CLI
def _write_trace(root, events):
    d = os.path.join(root, "plugins", "profile", "t")
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, "host.trace.json.gz")
    with gzip.open(path, "wt") as f:
        json.dump({"traceEvents": events}, f)
    return path


def _canned_events():
    meta = [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/device:TPU:0 (Core 0)"}},
        {"ph": "M", "pid": 1, "tid": 10, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
    ]

    def ev(name, ts, dur):
        return {"ph": "X", "pid": 1, "tid": 10, "name": name,
                "ts": ts, "dur": dur, "args": {}}

    return meta + [
        ev("fusion.1", 0, 8000),
        ev("all-reduce.2", 8100, 1000),
        ev("custom-call.3", 9200, 500),
    ]


class TestReconcileCLI:
    def test_drift_table_and_json(self, tmp_path, capsys):
        from deepspeed_tpu.profiling import reconcile as cli
        _write_trace(str(tmp_path), _canned_events())
        rc = cli.main([str(tmp_path), "--mesh", "dp=2,tp=4",
                       "--steps", "1"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "grad_reduce" in out and "modeled_ms" in out
        rc = cli.main([str(tmp_path), "--mesh", "dp=2,tp=4", "--json"])
        assert rc == 0
        parsed = json.loads(capsys.readouterr().out)
        assert parsed["decomposition"]["schema"] == \
            step_trace.SCHEMA_VERSION
        terms = {r["term"] for r in parsed["drift"]["rows"]}
        assert terms == set(planner.SCORE_TERMS)

    def test_seed_cache_flag_round_trips(self, tmp_path, capsys):
        from deepspeed_tpu.profiling import reconcile as cli
        _write_trace(str(tmp_path), _canned_events())
        cache = str(tmp_path / "cache.json")
        rc = cli.main([str(tmp_path), "--mesh", "dp=2", "--seed-cache",
                       "--cache", cache])
        assert rc == 0
        assert "seeded" in capsys.readouterr().out
        loaded = KernelCache.load(cache)
        ops = {e.get("op") for e in loaded.entries.values()}
        assert "comm_link" in ops

    def test_missing_trace_exits_2(self, tmp_path, capsys):
        from deepspeed_tpu.profiling import reconcile as cli
        assert cli.main([str(tmp_path / "empty")]) == 2


# ---------------------------------------------------- telemetry wiring
class TestTelemetryWiring:
    def _collector(self, monitor=None):
        from deepspeed_tpu.monitor.telemetry import TelemetryCollector
        from deepspeed_tpu.runtime.config import TelemetryConfig
        cfg = TelemetryConfig(enabled=True, interval_steps=2,
                              cluster_agg=False)
        return TelemetryCollector(cfg, monitor=monitor, n_devices=2)

    def test_profiler_stop_fires_on_trace(self, tmp_path, monkeypatch):
        from deepspeed_tpu.monitor.telemetry import ProfilerControl
        calls = []
        monkeypatch.setattr(jax.profiler, "start_trace",
                            lambda d: None)
        monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
        monkeypatch.setenv("DSTPU_PROFILE_STEPS", "2:4")
        pc = ProfilerControl(
            logdir=str(tmp_path),
            on_trace=lambda d, n, s: calls.append((d, n, s)))
        for step in range(6):
            pc.on_step(step)
        assert calls == [(os.path.join(str(tmp_path), "xprof"), 2, 4)]

    def test_on_trace_failure_never_raises(self, tmp_path, monkeypatch):
        from deepspeed_tpu.monitor.telemetry import ProfilerControl

        def boom(*a):
            raise RuntimeError("parser exploded")

        monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)
        monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
        monkeypatch.setenv("DSTPU_PROFILE_STEPS", "0:1")
        pc = ProfilerControl(logdir=str(tmp_path), on_trace=boom)
        for step in range(3):
            pc.on_step(step)        # must not raise
        assert pc.range is None

    def test_reconcile_summary_reaches_snapshot_and_events(self):
        summary = {"top_term": "compute", "top_term_index": 0,
                   "top_drift_ms": 5.0, "wall_err_pct": 12.5,
                   "coverage_pct": 97.0, "modeled_wall_ms": 40.0,
                   "measured_wall_ms": 45.0, "steps": 2}

        class _Mon:
            enabled = True
            events = []

            def write_events(self, evs):
                self.events.extend(evs)

        mon = _Mon()
        tel = self._collector(monitor=mon)
        try:
            tel.set_reconcile(lambda d, n: summary)
            tel._on_trace_ready("/nowhere/xprof", 2, 4)
            tel.drain()
            assert tel.last["reconcile"] == summary
            # events park until the next main-thread flush
            assert not any(t.startswith("Train/Reconcile/")
                           for t, _, _ in mon.events)
            for step in range(5, 7):
                tel.on_step(step, 0.01)
            by_tag = {t: v for t, v, _ in mon.events}
            assert by_tag["Train/Reconcile/wall_err_pct"] == 12.5
            assert by_tag["Train/Reconcile/top_drift_term"] == 0
            assert by_tag["Train/Reconcile/coverage_pct"] == 97.0
            # snapshot carries reconcile across later flushes
            assert tel.snapshot()["reconcile"] == summary
            # flight: both an event and the sticky crash context
            kinds = [e["kind"] for e in tel.flight.events()]
            assert "reconcile" in kinds
            assert tel.flight.context()["reconcile"] == summary
        finally:
            tel.close()

    def test_reconcile_none_warns_once_no_event(self, monkeypatch):
        from deepspeed_tpu.monitor import telemetry as tmod
        warns = []
        monkeypatch.setattr(tmod.logger, "warning",
                            lambda msg, *a, **k: warns.append(str(msg)))
        tel = self._collector()
        try:
            tel.set_reconcile(lambda d, n: None)
            tel._on_trace_ready("/nowhere", 1, 1)
            tel.drain()
            tel._on_trace_ready("/nowhere", 1, 2)
            tel.drain()
            assert len([w for w in warns
                        if "no step decomposition" in w]) == 1
            assert "reconcile" not in tel.last
            assert tel._pending_reconcile_events is None
        finally:
            tel.close()

    def test_flight_dump_context_only_when_set(self, tmp_path):
        from deepspeed_tpu.monitor.flight_recorder import FlightRecorder
        rec = FlightRecorder(node="ctx")
        rec.set_root(str(tmp_path))
        rec.record("step", step=1)
        with open(rec.dump("interval")) as f:
            assert "context" not in json.load(f)
        rec.set_context("reconcile", {"top_term": "compute"})
        with open(rec.dump("crash")) as f:
            dump = json.load(f)
        assert dump["context"]["reconcile"]["top_term"] == "compute"


# ------------------------------------------------- end-to-end (dp=2 mesh)
def _tiny_engine(telemetry=None, tp=1):
    from deepspeed_tpu.models.gpt2 import GPT2, GPT2_TINY
    from deepspeed_tpu.utils import groups
    from deepspeed_tpu.utils.groups import TopologyConfig
    topo = None
    if tp > 1:
        topo = groups.initialize(TopologyConfig(tensor_parallel_size=tp))
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "steps_per_print": 0,
    }
    if telemetry is not None:
        config["telemetry"] = telemetry
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2(GPT2_TINY), config=config,
        **({"topology": topo} if topo is not None else {}))
    rng = np.random.RandomState(0)
    batch = {"input_ids": rng.randint(
        0, 1024, (engine.config.train_batch_size, 128)).astype(np.int32)}
    return engine, batch


class TestEndToEnd:
    def test_profiled_dp2_run_reconciles(self, tmp_path, monkeypatch):
        """The ISSUE-13 acceptance path on the dp=2 virtual mesh: a
        step-ranged capture feeds the parser automatically, the
        decomposition covers >90% of measured device time, and the
        drift report pairs every _score term with a measured value."""
        monkeypatch.setenv("DSTPU_PROFILE_STEPS", "1:3")
        engine, batch = _tiny_engine(
            telemetry={"enabled": True, "interval_steps": 2,
                       "cluster_agg": False,
                       "flightrec_dir": str(tmp_path)},
            tp=4)
        try:
            mesh = dict(engine.mesh.shape)
            assert mesh.get("data") == 2 and mesh.get("tensor") == 4
            for _ in range(5):
                engine.train_batch(batch)
            engine.telemetry.drain()

            snap = engine.telemetry_report()
            assert "reconcile" in snap, (
                "profiled run produced no reconcile summary "
                "(trace->parser wiring broke)")
            summary = snap["reconcile"]
            assert summary["coverage_pct"] > 90.0
            assert summary["measured_wall_ms"] > 0

            rep = engine.reconcile_report()
            assert rep is not None
            dec = rep["decomposition"]
            assert dec["cpu_fallback"] is True    # tier-1 runs on CPU
            assert dec["terms"]["compute"] > 0
            drift = rep["drift"]
            terms = {r["term"] for r in drift["rows"]}
            assert terms == set(planner.SCORE_TERMS)
            # flight recorder saw the profile + reconcile events
            kinds = [e["kind"] for e in engine.telemetry.flight.events()]
            assert "profile_start" in kinds
            assert "profile_stop" in kinds
            assert "reconcile" in kinds
        finally:
            engine.telemetry.close()

    def test_tracing_off_leaves_snapshot_unchanged(self, monkeypatch):
        """Byte-identity guard: without DSTPU_PROFILE_STEPS the
        snapshot carries no reconcile key and the flight context stays
        empty — telemetry output is exactly the pre-PR shape."""
        monkeypatch.delenv("DSTPU_PROFILE_STEPS", raising=False)
        engine, batch = _tiny_engine(
            telemetry={"enabled": True, "interval_steps": 2,
                       "cluster_agg": False})
        try:
            for _ in range(4):
                engine.train_batch(batch)
            engine.telemetry.drain()
            snap = engine.telemetry_report()
            assert "reconcile" not in snap
            assert engine.reconcile_report() is None
            assert engine.telemetry.flight.context() == {}
        finally:
            engine.telemetry.close()
