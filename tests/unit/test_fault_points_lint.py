"""Fault-point lint (ISSUE 7 satellite): the named injection points in
utils/fault_injection.py are only worth anything while (a) production
code actually fires them and (b) some chaos test actually arms them.
Both halves rot silently under refactors — a renamed fire() site or a
deleted test leaves a point that LOOKS chaos-covered but never is. This
lint pins both halves to the KNOWN_POINTS registry."""

import os
import re

import pytest

from deepspeed_tpu.utils import fault_injection

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
PKG = os.path.join(REPO, "deepspeed_tpu")
TESTS = os.path.dirname(os.path.abspath(__file__))


def _py_files(root):
    for dirpath, _, names in os.walk(root):
        if "__pycache__" in dirpath:
            continue
        for n in names:
            if n.endswith(".py"):
                yield os.path.join(dirpath, n)


def _read(path):
    with open(path, encoding="utf-8") as f:
        return f.read()


def test_registry_is_complete():
    """Every fire('<literal>') in the package names a registered point —
    a new injection point must be added to KNOWN_POINTS (where the lint
    can see it) before it ships."""
    fired = set()
    for path in _py_files(PKG):
        for m in re.finditer(r"""fire\(\s*["']([a-z_]+)["']\s*\)""",
                             _read(path)):
            fired.add(m.group(1))
    unregistered = fired - set(fault_injection.KNOWN_POINTS)
    assert not unregistered, (
        f"injection points fired in production code but missing from "
        f"fault_injection.KNOWN_POINTS: {sorted(unregistered)}")


def test_every_registered_point_is_fired_in_production_code():
    blob = "\n".join(_read(p) for p in _py_files(PKG))
    dead = [p for p in fault_injection.KNOWN_POINTS
            if not re.search(r"""fire\(\s*["']%s["']\s*\)""" % p, blob)]
    assert not dead, (
        f"KNOWN_POINTS entries no production code fires (stale "
        f"registry or lost fire() site): {dead}")


def test_every_registered_point_is_armed_by_a_chaos_test():
    """Each point must appear, by name, in at least one test file that
    arms faults (fault_injection.arm(...) or the DSTPU_FAULT_INJECT
    env) — so a deleted/renamed chaos test cannot silently strand an
    injection point with zero coverage."""
    arming_blobs = []
    for path in _py_files(TESTS):
        if os.path.basename(path) == os.path.basename(__file__):
            continue
        text = _read(path)
        if "fault_injection.arm" in text or "DSTPU_FAULT_INJECT" in text:
            arming_blobs.append(text)
    assert arming_blobs, "no arming test files found at all"
    blob = "\n".join(arming_blobs)
    unarmed = [p for p in fault_injection.KNOWN_POINTS
               if f'"{p}"' not in blob and f"'{p}'" not in blob]
    assert not unarmed, (
        f"registered injection points no chaos test arms: {unarmed} — "
        f"add an arm()/DSTPU_FAULT_INJECT test before shipping the "
        f"point")


@pytest.mark.chaos
def test_new_points_exist_and_fire():
    """The ISSUE-7 points are registered and behave like every other
    point (countdown, budget, kill)."""
    for p in ("replica_push", "replica_fetch", "host_loss", "reshape"):
        assert p in fault_injection.KNOWN_POINTS
    fault_injection.reset()
    try:
        fault_injection.arm("reshape", fails=1, skip=1)
        fault_injection.fire("reshape")              # skipped
        with pytest.raises(fault_injection.FaultError):
            fault_injection.fire("reshape")
        fault_injection.fire("reshape")              # healed
        assert fault_injection.injector.hits("reshape") == 1
    finally:
        fault_injection.reset()


# --------------------------------------------------------- blast radius

def test_every_point_declares_a_blast_radius():
    """Satellite lint (ISSUE 15): every KNOWN_POINTS entry carries a
    blast-radius class, and nothing stale lingers in the map — a new
    injection point must DECLARE whether its failure is advisory,
    retryable, or fatal before it ships."""
    assert set(fault_injection.BLAST_RADIUS) == \
        set(fault_injection.KNOWN_POINTS)
    assert set(fault_injection.BLAST_RADIUS.values()) <= \
        {"advisory", "retryable", "fatal"}


@pytest.mark.chaos
def test_advisory_points_never_propagate_to_the_save_path():
    """The blast-radius contract, enforced behaviorally: arm EVERY
    advisory point with an unlimited failure budget and drive the
    push + tiered-load paths — nothing may raise, pushes report
    failure through counters, and loads degrade down-tier."""
    import numpy as np

    from deepspeed_tpu.runtime.checkpoint_engine import hot_tier
    from deepspeed_tpu.runtime.checkpoint_engine import manager
    from deepspeed_tpu.runtime.checkpoint_engine import \
        serialization as ser
    from deepspeed_tpu.runtime.checkpoint_engine.engines import \
        SyncCheckpointEngine

    advisory = sorted(p for p, c in fault_injection.BLAST_RADIUS.items()
                      if c == "advisory")
    assert advisory == ["dcn_partition", "replica_fetch",
                        "replica_push", "replica_restore",
                        "router_overload"]
    # router_overload is serving-plane: its never-kills-a-replica /
    # never-fails-admitted-work contract is pinned behaviorally in
    # test_router.py (TestRouterOverload.test_router_overload_point_is
    # _advisory); the checkpoint drive
    # below covers the storage-plane advisory points
    advisory = [p for p in advisory if p != "router_overload"]
    peers = ["h0", "h1", "h2", "h3"]
    slices = {"h0": "0", "h1": "0", "h2": "1", "h3": "1"}
    tree = {"w": np.arange(4, dtype=np.float32)}
    chunks, index, meta = ser.extract_local_chunks(tree)
    extra = {"index": index, "__tree_meta__": meta,
             "user_extra": {"global_step": 1, "nprocs": 1}}

    import tempfile
    for point in advisory:
        fault_injection.reset()
        with tempfile.TemporaryDirectory() as td:
            hot_root = os.path.join(td, "hot")
            durable = os.path.join(td, "ckpt")
            eng = SyncCheckpointEngine(None)
            eng.save((chunks, extra),
                     os.path.join(durable, "global_step1",
                                  "shard-0.npz"),
                     on_durable=lambda: manager.publish_latest(
                         durable, "global_step1"))
            counters = {}
            stores = {h: hot_tier.HotTierStore(
                root=hot_root, node=h, peers=peers, replicas=1,
                slices=slices, counters=counters) for h in peers}
            # a clean cross-slice generation to poison on the way back
            stores["h0"].push("global_step1", chunks, extra,
                              shard_name="shard-0.npz")
            stores["h2"].push_zero_replica("global_step1", chunks, extra)
            fault_injection.arm(point, fails=100)
            # every push entry point swallows the armed failure
            stores["h0"].push("global_step1", chunks, extra,
                              shard_name="shard-0.npz")
            stores["h0"].push_async("global_step1", chunks, extra,
                                    shard_name="shard-0.npz")
            assert stores["h0"].wait() is True
            stores["h2"].push_zero_replica("global_step1", chunks, extra)
            if point == "dcn_partition":
                # only this branch may reach the collective impl: with
                # a patched 2-process world, any OTHER armed point
                # would let a real ring_exchange_bytes run single-proc
                import jax
                real = jax.process_count
                jax.process_count = lambda: 2
                try:
                    assert stores["h0"].push_collective(
                        "global_step1", chunks, extra,
                        shard_name="shard-0.npz") == 0
                finally:
                    jax.process_count = real
            # the tiered load degrades down-tier instead of raising
            hot_tier.purge_node(hot_root, "h0")
            hot_tier.purge_node(hot_root, "h1")
            tier, tag, flat, _ = manager.load_best_tiered(
                durable, hot_store=stores["h2"], counters=counters)
            assert tag == "global_step1"
            np.testing.assert_array_equal(flat["w"], tree["w"])
            if point in ("replica_fetch", "replica_restore"):
                assert tier == "durable", point
            fault_injection.reset()
        stores["h0"].shutdown()


def test_serving_points_declare_expected_blast_radius():
    """ISSUE-17 serving plane: the router owns retryable failures
    (re-route / health machine), replica_death propagates to it
    (fatal), and overload shedding is a service decision that may never
    take a replica down (advisory)."""
    br = fault_injection.BLAST_RADIUS
    assert br["serve_dispatch"] == "retryable"
    assert br["serve_step"] == "retryable"
    # ISSUE-19: a verify-dispatch failure mid-speculation is owned by
    # the same replica health machine as serve_step — never fatal
    assert br["serve_verify"] == "retryable"
    assert br["replica_death"] == "fatal"
    assert br["router_overload"] == "advisory"
    # ISSUE-20: both halves of the KV handoff fire BEFORE any state
    # moves, so the router's retry-next-round policy owns them — a
    # stream or import failure must never kill either replica
    assert br["kv_stream"] == "retryable"
    assert br["kv_import"] == "retryable"


@pytest.mark.chaos
def test_fatal_point_does_propagate():
    """Counter-example pinning the other side of the contract: a
    fatal-class point (slice_loss at the push boundary) propagates out
    of the entry point instead of being swallowed."""
    import numpy as np

    from deepspeed_tpu.runtime.checkpoint_engine import hot_tier
    from deepspeed_tpu.runtime.checkpoint_engine import \
        serialization as ser

    assert fault_injection.BLAST_RADIUS["slice_loss"] == "fatal"
    tree = {"w": np.arange(4, dtype=np.float32)}
    chunks, index, meta = ser.extract_local_chunks(tree)
    extra = {"index": index, "__tree_meta__": meta,
             "user_extra": {"global_step": 1, "nprocs": 1}}
    import tempfile
    fault_injection.reset()
    try:
        with tempfile.TemporaryDirectory() as td:
            s = hot_tier.HotTierStore(
                root=td, node="h0", peers=["h0", "h1"], replicas=1,
                slices={"h0": "0", "h1": "1"})
            fault_injection.arm("slice_loss", fails=1)
            with pytest.raises(fault_injection.FaultError):
                s.push_async("global_step1", chunks, extra,
                             shard_name="shard-0.npz")
            s.shutdown()
    finally:
        fault_injection.reset()
