"""Fault-point lint (ISSUE 7 satellite): the named injection points in
utils/fault_injection.py are only worth anything while (a) production
code actually fires them and (b) some chaos test actually arms them.
Both halves rot silently under refactors — a renamed fire() site or a
deleted test leaves a point that LOOKS chaos-covered but never is. This
lint pins both halves to the KNOWN_POINTS registry."""

import os
import re

import pytest

from deepspeed_tpu.utils import fault_injection

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
PKG = os.path.join(REPO, "deepspeed_tpu")
TESTS = os.path.dirname(os.path.abspath(__file__))


def _py_files(root):
    for dirpath, _, names in os.walk(root):
        if "__pycache__" in dirpath:
            continue
        for n in names:
            if n.endswith(".py"):
                yield os.path.join(dirpath, n)


def _read(path):
    with open(path, encoding="utf-8") as f:
        return f.read()


def test_registry_is_complete():
    """Every fire('<literal>') in the package names a registered point —
    a new injection point must be added to KNOWN_POINTS (where the lint
    can see it) before it ships."""
    fired = set()
    for path in _py_files(PKG):
        for m in re.finditer(r"""fire\(\s*["']([a-z_]+)["']\s*\)""",
                             _read(path)):
            fired.add(m.group(1))
    unregistered = fired - set(fault_injection.KNOWN_POINTS)
    assert not unregistered, (
        f"injection points fired in production code but missing from "
        f"fault_injection.KNOWN_POINTS: {sorted(unregistered)}")


def test_every_registered_point_is_fired_in_production_code():
    blob = "\n".join(_read(p) for p in _py_files(PKG))
    dead = [p for p in fault_injection.KNOWN_POINTS
            if not re.search(r"""fire\(\s*["']%s["']\s*\)""" % p, blob)]
    assert not dead, (
        f"KNOWN_POINTS entries no production code fires (stale "
        f"registry or lost fire() site): {dead}")


def test_every_registered_point_is_armed_by_a_chaos_test():
    """Each point must appear, by name, in at least one test file that
    arms faults (fault_injection.arm(...) or the DSTPU_FAULT_INJECT
    env) — so a deleted/renamed chaos test cannot silently strand an
    injection point with zero coverage."""
    arming_blobs = []
    for path in _py_files(TESTS):
        if os.path.basename(path) == os.path.basename(__file__):
            continue
        text = _read(path)
        if "fault_injection.arm" in text or "DSTPU_FAULT_INJECT" in text:
            arming_blobs.append(text)
    assert arming_blobs, "no arming test files found at all"
    blob = "\n".join(arming_blobs)
    unarmed = [p for p in fault_injection.KNOWN_POINTS
               if f'"{p}"' not in blob and f"'{p}'" not in blob]
    assert not unarmed, (
        f"registered injection points no chaos test arms: {unarmed} — "
        f"add an arm()/DSTPU_FAULT_INJECT test before shipping the "
        f"point")


@pytest.mark.chaos
def test_new_points_exist_and_fire():
    """The ISSUE-7 points are registered and behave like every other
    point (countdown, budget, kill)."""
    for p in ("replica_push", "replica_fetch", "host_loss", "reshape"):
        assert p in fault_injection.KNOWN_POINTS
    fault_injection.reset()
    try:
        fault_injection.arm("reshape", fails=1, skip=1)
        fault_injection.fire("reshape")              # skipped
        with pytest.raises(fault_injection.FaultError):
            fault_injection.fire("reshape")
        fault_injection.fire("reshape")              # healed
        assert fault_injection.injector.hits("reshape") == 1
    finally:
        fault_injection.reset()
