"""MoE: gating math, layer numerics, expert parallelism, engine e2e.

Mirrors the reference's tests/unit/moe/test_moe.py strategy (EP groups,
top-k gating correctness) on the virtual 8-device CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.moe import MoE, TopKGate, top1gating, top2gating
from deepspeed_tpu.models import GPT2MoE, GPT2MoEConfig
from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.groups import TopologyConfig


def _logits(S=64, E=8, seed=0):
    return jax.random.normal(jax.random.key(seed), (S, E), jnp.float32)


class TestGating:
    def test_top1_shapes_and_capacity(self):
        S, E = 64, 8
        l_aux, combine, dispatch, counts = top1gating(
            _logits(S, E), capacity_factor=1.0, min_capacity=4)
        C = S // E
        assert combine.shape == (S, E, C)
        assert dispatch.shape == (S, E, C)
        # each token goes to at most one (expert, slot)
        assert np.all(np.sum(np.asarray(dispatch), axis=(1, 2)) <= 1)
        # each (expert, slot) holds at most one token
        assert np.all(np.sum(np.asarray(dispatch), axis=0) <= 1)
        assert float(l_aux) > 0

    def test_top1_combine_weights_match_softmax(self):
        S, E = 32, 4
        logits = _logits(S, E, seed=1)
        _, combine, dispatch, _ = top1gating(logits, capacity_factor=4.0)
        gates = jax.nn.softmax(logits, axis=-1)
        kept = np.asarray(jnp.sum(combine, axis=(1, 2)))
        routed = np.asarray(jnp.sum(dispatch, axis=(1, 2))) > 0
        expect = np.asarray(jnp.max(gates, axis=-1))
        np.testing.assert_allclose(kept[routed], expect[routed], rtol=1e-5)

    def test_top1_drops_overflow(self):
        # all tokens prefer expert 0 -> only C survive
        S, E = 32, 4
        logits = jnp.zeros((S, E)).at[:, 0].set(10.0)
        _, _, dispatch, _ = top1gating(logits, capacity_factor=1.0,
                                       min_capacity=4)
        assert int(jnp.sum(dispatch)) == S // E

    def test_top1_no_drop_tokens(self):
        S, E = 32, 4
        logits = jnp.zeros((S, E)).at[:, 0].set(10.0)
        _, _, dispatch, _ = top1gating(logits, capacity_factor=1.0,
                                       drop_tokens=False)
        assert int(jnp.sum(dispatch)) == S

    def test_top2_two_experts_per_token(self):
        S, E = 64, 8
        _, combine, dispatch, _ = top2gating(
            _logits(S, E), capacity_factor=4.0, rng=jax.random.key(2))
        per_token = np.sum(np.asarray(dispatch), axis=(1, 2))
        assert np.all(per_token <= 2)
        assert np.mean(per_token) > 1.5  # ample capacity: most keep both
        # normalized pair weights sum to ~1 where both kept
        sums = np.asarray(jnp.sum(combine, axis=(1, 2)))
        np.testing.assert_allclose(sums[per_token == 2], 1.0, atol=1e-5)

    def test_gate_object_dispatches_k(self):
        g1 = TopKGate(k=1)
        g2 = TopKGate(k=2, top2_2nd_expert_sampling=False)
        out1 = g1(_logits())
        out2 = g2(_logits())
        assert len(out1) == 4 and len(out2) == 4
        with pytest.raises(ValueError):
            TopKGate(k=3)


@pytest.mark.slow
class TestMoELayer:
    def test_forward_and_identity_expert(self):
        """With ample capacity and experts = identity-ish maps, the layer
        output equals the gate-weighted expert output."""
        M, E = 16, 4
        moe = MoE(hidden_size=M, ffn_hidden_size=M, num_experts=E, k=1,
                  capacity_factor=8.0, dtype=jnp.float32,
                  activation=lambda x: x)
        params = moe.init(jax.random.key(0))
        x = jax.random.normal(jax.random.key(1), (8, M), jnp.float32)
        y, l_aux, counts = moe.apply(params, x, train=False)
        assert y.shape == x.shape
        assert float(l_aux) > 0
        assert int(jnp.sum(counts)) == 8
        # hand-computed: every token routed (capacity ample)
        logits = x @ params["gate_w"]
        top = jnp.argmax(logits, -1)
        gates = jax.nn.softmax(logits, -1)
        w = jnp.take_along_axis(gates, top[:, None], -1)[:, 0]
        expect = jax.vmap(
            lambda xi, e, wi: wi * ((xi @ params["wi"][e] + params["bi"][e])
                                    @ params["wo"][e] + params["bo"][e]))(
            x, top, w)
        np.testing.assert_allclose(np.asarray(y), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)

    def test_expert_parallel_matches_single(self):
        """EP=4 sharded forward == unsharded forward (same params)."""
        M, E = 16, 8
        moe = MoE(hidden_size=M, ffn_hidden_size=32, num_experts=E, k=1,
                  capacity_factor=2.0, dtype=jnp.float32)
        params = moe.init(jax.random.key(0))
        x = jax.random.normal(jax.random.key(1), (16, M), jnp.float32)
        y_ref, _, _ = jax.jit(
            lambda p, x: moe.apply(p, x, train=False))(params, x)

        groups.reset()
        topo = groups.initialize(TopologyConfig(expert_parallel_size=4))
        specs = moe.partition_specs()
        sh = jax.tree.map(
            lambda s: jax.sharding.NamedSharding(topo.mesh, s), specs,
            is_leaf=lambda s: isinstance(s, jax.sharding.PartitionSpec))
        with jax.set_mesh(topo.mesh):
            params_sh = jax.device_put(params, sh)
            y_ep, _, _ = jax.jit(
                lambda p, x: moe.apply(p, x, train=False))(params_sh, x)
        np.testing.assert_allclose(np.asarray(y_ref), np.asarray(y_ep),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.slow
class TestGPT2MoEEngine:
    def _cfg(self, **kw):
        return GPT2MoEConfig(n_layer=2, n_head=2, d_model=32, max_seq_len=16,
                             vocab_size=128, remat=False, dtype="float32",
                             num_experts=4, **kw)

    def test_param_count(self):
        cfg = self._cfg()
        model = GPT2MoE(cfg)
        params = model.init(jax.random.key(0))
        n = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
        assert n == cfg.num_params()

    @pytest.mark.parametrize("zero_stage", [0, 2])
    def test_train_decreases_loss_ep(self, zero_stage):
        import deepspeed_tpu
        groups.reset()
        topo = groups.initialize(TopologyConfig(expert_parallel_size=2))
        cfg = self._cfg(moe_top_k=2)
        model = GPT2MoE(cfg)
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, topology=topo,
            config={"train_micro_batch_size_per_gpu": 1,
                    "gradient_accumulation_steps": 1,
                    "steps_per_print": 0,
                    "optimizer": {"type": "Adam", "params": {"lr": 3e-3}},
                    "zero_optimization": {"stage": zero_stage}})
        rng = np.random.RandomState(0)
        batch = {"input_ids": rng.randint(
            0, cfg.vocab_size,
            (engine.config.train_batch_size, cfg.max_seq_len)).astype(
            np.int32)}
        losses = [float(engine.train_batch(batch)) for _ in range(10)]
        assert losses[-1] < losses[0] * 0.9, losses

    def test_expert_shardings_applied(self):
        import deepspeed_tpu
        groups.reset()
        topo = groups.initialize(TopologyConfig(expert_parallel_size=4))
        model = GPT2MoE(self._cfg())
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, topology=topo,
            config={"train_micro_batch_size_per_gpu": 1,
                    "steps_per_print": 0,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 1}})
        wi = engine.state["params"]["blocks"]["moe"]["wi"]
        spec = wi.sharding.spec
        assert "expert" in jax.tree.leaves(tuple(spec))
        # ZeRO-1 master of expert weights partitioned over 'data' only
        mwi = engine.state["master"]["blocks"]["moe"]["wi"]
        flat = jax.tree.leaves(tuple(mwi.sharding.spec))
        assert "data" in flat and "expert" in flat


class TestRaggedMoE:
    def _params_and_x(self, k=1):
        from deepspeed_tpu.moe.layer import MoE
        moe = MoE(hidden_size=32, ffn_hidden_size=64, num_experts=4, k=k,
                  capacity_factor=8.0, eval_capacity_factor=8.0,
                  dtype=jnp.float32, backend="ragged")
        params = moe.init(jax.random.key(0))
        x = jnp.asarray(np.random.RandomState(0).randn(16, 32), jnp.float32)
        return moe, params, x

    def test_matches_dense_when_no_drops(self):
        """With capacity large enough that nothing drops, dropless ragged
        and dense dispatch compute the same function (top-1, eval mode)."""
        from deepspeed_tpu.moe.layer import MoE
        moe_r, params, x = self._params_and_x(k=1)
        moe_d = MoE(hidden_size=32, ffn_hidden_size=64, num_experts=4, k=1,
                    capacity_factor=8.0, eval_capacity_factor=8.0,
                    dtype=jnp.float32, backend="dense")
        y_r, aux_r, _ = moe_r.apply(params, x, train=False)
        y_d, aux_d, _ = moe_d.apply(params, x, train=False)
        np.testing.assert_allclose(np.asarray(y_r), np.asarray(y_d),
                                   rtol=2e-4, atol=2e-4)

    def test_dropless_property(self):
        """Every token gets expert output even when dense capacity would
        drop (all tokens routed to one expert, capacity tiny)."""
        from deepspeed_tpu.moe.sharded_moe import moe_layer_ragged
        rs = np.random.RandomState(1)
        M, F, E, S = 16, 32, 4, 12
        gate_w = np.zeros((M, E), np.float32)
        gate_w[:, 2] = 1.0  # all tokens -> expert 2
        wi = jnp.asarray(rs.randn(E, M, F), jnp.float32)
        bi = jnp.zeros((E, F), jnp.float32)
        wo = jnp.asarray(rs.randn(E, F, M), jnp.float32)
        bo = jnp.zeros((E, M), jnp.float32)
        x = jnp.asarray(np.abs(rs.randn(S, M)) + 0.5, jnp.float32)
        y, aux, counts = moe_layer_ragged(x, jnp.asarray(gate_w), wi, bi,
                                          wo, bo, k=1)
        assert int(np.asarray(counts)[2]) == S
        # no token got zeroed (dense with capacity 4 would drop 8 of 12)
        norms = np.linalg.norm(np.asarray(y), axis=-1)
        assert (norms > 1e-3).all()

    def test_top2_ragged(self):
        moe, params, x = self._params_and_x(k=2)
        y, aux, counts = moe.apply(params, x, train=False)
        assert y.shape == x.shape
        assert float(aux) > 0
        assert np.isfinite(np.asarray(y)).all()

    def test_grad_flows(self):
        moe, params, x = self._params_and_x()
        g = jax.grad(lambda p: jnp.sum(
            moe.apply(p, x, train=False)[0] ** 2))(params)
        assert float(jnp.abs(g["wi"]).max()) > 0


class TestRaggedMoEValidation:
    def test_noisy_gate_rejected(self):
        from deepspeed_tpu.moe.layer import MoE
        with pytest.raises(ValueError, match="ragged"):
            MoE(hidden_size=8, num_experts=2, backend="ragged",
                noisy_gate_policy="RSample")

    def test_k4_allowed_ragged(self):
        from deepspeed_tpu.moe.layer import MoE
        moe = MoE(hidden_size=16, ffn_hidden_size=32, num_experts=8, k=4,
                  dtype=jnp.float32, backend="ragged")
        params = moe.init(jax.random.key(0))
        x = jnp.asarray(np.random.RandomState(0).randn(6, 16), jnp.float32)
        y, aux, counts = moe.apply(params, x, train=False)
        assert y.shape == x.shape
        # counts reflect ALL k dispatches
        assert int(np.asarray(counts).sum()) == 6 * 4


@pytest.mark.slow
class TestGPT2MoERagged:
    def test_ragged_backend_trains_top2(self):
        from deepspeed_tpu.models import GPT2MoE, GPT2MoEConfig
        from deepspeed_tpu.utils import groups
        groups.reset()
        cfg = GPT2MoEConfig(n_layer=2, n_head=2, d_model=32, max_seq_len=32,
                            vocab_size=64, num_experts=4, moe_top_k=2,
                            moe_backend="ragged", remat=False,
                            dtype="float32")
        model = GPT2MoE(cfg)
        assert not model._requires_train_rng()  # deterministic routing
        import deepspeed_tpu
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model,
            config={"train_micro_batch_size_per_gpu": 1,
                    "optimizer": {"type": "AdamW", "params": {"lr": 2e-3}},
                    "steps_per_print": 0})
        data = np.zeros((engine.config.train_batch_size, 16), np.int32)
        l0 = float(engine.train_batch({"input_ids": data}))
        l1 = float(engine.train_batch({"input_ids": data}))
        assert l1 < l0


@pytest.mark.slow
class TestRaggedEP:
    """Expert-parallel dropless MoE (moe_layer_ragged_ep): shard_map +
    all_to_all + per-shard ragged_dot (reference cutlass moe_gemm composed
    with _AllToAll dispatch)."""

    def _params(self, M=32, F=64, E=8, seed=0):
        rng = np.random.RandomState(seed)
        return (jnp.asarray(rng.randn(M, E) * 0.1, jnp.float32),
                jnp.asarray(rng.randn(E, M, F) * 0.1, jnp.float32),
                jnp.asarray(rng.randn(E, F) * 0.1, jnp.float32),
                jnp.asarray(rng.randn(E, F, M) * 0.1, jnp.float32),
                jnp.asarray(rng.randn(E, M) * 0.1, jnp.float32))

    @pytest.mark.parametrize("k", [1, 2])
    def test_matches_single_shard_ragged(self, k):
        from deepspeed_tpu.moe.sharded_moe import (moe_layer_ragged,
                                                   moe_layer_ragged_ep)
        gate_w, wi, bi, wo, bo = self._params()
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(64, 32) * 0.3, jnp.float32)
        y_ref, aux_ref, cnt_ref = moe_layer_ragged(
            x, gate_w, wi, bi, wo, bo, k=k)
        groups.reset()
        topo = groups.initialize(TopologyConfig(data_parallel_size=2,
                                                expert_parallel_size=4))
        with jax.set_mesh(topo.mesh):
            y, aux, cnt = jax.jit(
                lambda *a: __import__("deepspeed_tpu").moe.sharded_moe
                .moe_layer_ragged_ep(*a, k=k))(x, gate_w, wi, bi, wo, bo)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                                   rtol=2e-4, atol=2e-4)
        np.testing.assert_array_equal(np.asarray(cnt),
                                      np.asarray(cnt_ref))
        # aux is formed from psum'd GLOBAL statistics, so it must equal
        # the single-shard loss (not a mean of per-shard losses)
        np.testing.assert_allclose(np.asarray(aux), np.asarray(aux_ref),
                                   rtol=1e-5, atol=1e-6)

    def test_dropless_vs_dense_dispatch_no_drops(self):
        """With ample capacity the dense dispatch drops nothing; dropless
        must then match it token for token (k=1: identical combine)."""
        from deepspeed_tpu.moe.sharded_moe import (moe_layer, TopKGate,
                                                   moe_layer_ragged_ep)
        gate_w, wi, bi, wo, bo = self._params()
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(64, 32) * 0.3, jnp.float32)
        groups.reset()
        topo = groups.initialize(TopologyConfig(expert_parallel_size=4))
        gate = TopKGate(k=1, capacity_factor=8.0,
                        eval_capacity_factor=8.0)   # no drops possible
        with jax.set_mesh(topo.mesh):
            y_dense, _, _ = jax.jit(
                lambda *a: moe_layer(*a, gate, train=False))(
                x, gate_w, wi, bi, wo, bo)
            y_rag, _, _ = jax.jit(
                lambda *a: moe_layer_ragged_ep(*a, k=1))(
                x, gate_w, wi, bi, wo, bo)
        np.testing.assert_allclose(np.asarray(y_rag), np.asarray(y_dense),
                                   rtol=2e-4, atol=2e-4)

    def test_moe_module_ragged_under_ep_mesh(self):
        """MoE(backend='ragged') trains under an expert-parallel mesh."""
        groups.reset()
        topo = groups.initialize(TopologyConfig(expert_parallel_size=4))
        moe = MoE(hidden_size=32, ffn_hidden_size=64, num_experts=8, k=2,
                  dtype=jnp.float32, backend="ragged")
        params = moe.init(jax.random.key(0))
        rng = np.random.RandomState(3)
        x = jnp.asarray(rng.randn(4, 16, 32) * 0.3, jnp.float32)
        with jax.set_mesh(topo.mesh):
            from jax.sharding import PartitionSpec as PS
            params = jax.device_put(params, jax.tree.map(
                lambda s: jax.sharding.NamedSharding(topo.mesh, s),
                moe.partition_specs(),
                is_leaf=lambda s: isinstance(s, PS)))
            y, l_aux, counts = jax.jit(
                lambda p, x: moe.apply(p, x, train=False))(params, x)
        assert y.shape == x.shape
        assert float(jnp.sum(counts)) == 4 * 16 * 2  # k=2, dropless


class TestSwigluEP:
    """Expert-parallel SwiGLU MoE (moe_swiglu_ragged_ep) — the mixtral
    serving FFN. Exists because GSPMD silently mis-partitions
    lax.ragged_dot over expert-sharded weights (off-shard experts' rows
    come back garbage), so EP must be an explicit shard_map exchange.
    Fast tier: this guards the ep_sharded_mixtral serving path."""

    def _params(self, M=16, F=32, E=4, seed=0):
        rng = np.random.RandomState(seed)
        return (jnp.asarray(rng.randn(M, E) * 0.1, jnp.float32),
                jnp.asarray(rng.randn(E, M, F) * 0.1, jnp.float32),
                jnp.asarray(rng.randn(E, M, F) * 0.1, jnp.float32),
                jnp.asarray(rng.randn(E, F, M) * 0.1, jnp.float32))

    def _dense(self, x, gate_w, w1, w3, w2, k=2):
        logits = x.astype(jnp.float32) @ gate_w
        probs = jax.nn.softmax(logits, axis=-1)
        weights, experts = jax.lax.top_k(probs, k)
        weights = weights / jnp.sum(weights, axis=-1, keepdims=True)
        y = jnp.zeros_like(x)
        for e in range(gate_w.shape[-1]):
            o = (jax.nn.silu(x @ w1[e]) * (x @ w3[e])) @ w2[e]
            w = jnp.sum(jnp.where(experts == e, weights, 0.0), axis=-1)
            y = y + o * w[:, None]
        return y

    @pytest.mark.parametrize("odd_tokens", [False, True])
    def test_matches_dense_reference(self, odd_tokens):
        from deepspeed_tpu.moe.sharded_moe import moe_swiglu_ragged_ep
        gate_w, w1, w3, w2 = self._params()
        rng = np.random.RandomState(1)
        S = 15 if odd_tokens else 16    # odd: the pad-to-divisible path
        x = jnp.asarray(rng.randn(S, 16) * 0.3, jnp.float32)
        ref = self._dense(x, gate_w, w1, w3, w2)
        groups.reset()
        topo = groups.initialize(TopologyConfig(expert_parallel_size=2,
                                                tensor_parallel_size=2))
        with jax.set_mesh(topo.mesh):
            y = jax.jit(lambda *a: moe_swiglu_ragged_ep(*a, k=2))(
                x, gate_w, w1, w3, w2)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)
