import numpy as np
import pytest

from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.groups import TopologyConfig


def test_default_topology_all_data():
    topo = groups.initialize()
    assert topo.world_size == 8
    assert topo.get_data_parallel_world_size() == 8
    assert topo.get_model_parallel_world_size() == 1


def test_mixed_topology():
    topo = groups.initialize(TopologyConfig(tensor_parallel_size=2,
                                            seq_parallel_size=2), force=True)
    assert topo.get_data_parallel_world_size() == 2
    assert topo.get_sequence_parallel_world_size() == 2
    assert topo.get_model_parallel_world_size() == 2
    assert topo.mesh.shape["data"] == 2


def test_expert_carved_from_dp():
    # reference utils/groups.py:113 — ep_size divides dp world
    topo = groups.initialize(TopologyConfig(expert_parallel_size=4), force=True)
    assert topo.get_expert_parallel_world_size() == 4
    assert topo.get_expert_data_parallel_world_size() == 2
    # non-expert params still see the full 8-way dp group
    assert topo.get_data_parallel_world_size() == 8


def test_invalid_topology_raises():
    with pytest.raises(ValueError):
        groups.initialize(TopologyConfig(tensor_parallel_size=3), force=True)


def test_batch_sharding_layout():
    topo = groups.initialize(TopologyConfig(seq_parallel_size=2), force=True)
    sh = topo.batch_sharding(seq_dim=1)
    spec = sh.spec
    assert spec[0] == ("data_outer", "data", "expert")
    assert spec[1] == "seq"
