"""Capture a jax.profiler trace of the headline training step.

Usage: python benchmarks/profile_step.py [outdir]
Then aggregate with benchmarks/trace_summary.py.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax

import deepspeed_tpu
from deepspeed_tpu.models import GPT2, PRESETS
from deepspeed_tpu.utils import groups


def main():
    outdir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/dstpu_trace"
    preset = os.environ.get("BENCH_PRESET", "350M")
    seq_len = int(os.environ.get("BENCH_SEQ", "1024"))
    micro = int(os.environ.get("BENCH_MICRO_BS", "24"))

    cfg = PRESETS[preset]
    from dataclasses import replace
    cfg = replace(cfg, max_seq_len=seq_len,
                  use_flash_attention=os.environ.get("BENCH_FLASH", "1") == "1",
                  flash_block_q=int(os.environ.get("BENCH_FLASH_BQ", "1024")),
                  flash_block_k=int(os.environ.get("BENCH_FLASH_BK", "1024")),
                  flash_block_h=int(os.environ.get("BENCH_FLASH_BH", "1")),
                  remat=True,
                  remat_policy=os.environ.get("BENCH_REMAT_POLICY",
                                              "save_flash"),
                  loss_chunk=int(os.environ.get("BENCH_LOSS_CHUNK", "256")))
    model = GPT2(cfg)
    groups.reset()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={
            "train_micro_batch_size_per_gpu": micro,
            "gradient_accumulation_steps": 1,
            "steps_per_print": 0,
            "optimizer": {"type": "AdamW",
                          "params": {"lr": 2e-4, "weight_decay": 0.01}},
            "gradient_clipping": 1.0,
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 2},
        })

    bsz = engine.config.train_batch_size
    rng = np.random.RandomState(0)
    batch = {"input_ids": rng.randint(0, cfg.vocab_size, (bsz, seq_len))
             .astype(np.int32)}

    for _ in range(3):
        engine.train_batch(batch)
    float(np.asarray(engine.state["step"]))

    with jax.profiler.trace(outdir):
        for _ in range(3):
            engine.train_batch(batch)
        float(np.asarray(engine.state["step"]))
    print("trace written to", outdir)


if __name__ == "__main__":
    main()
