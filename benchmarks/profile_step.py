"""Capture a jax.profiler trace of the headline training step.

Usage: python benchmarks/profile_step.py [outdir]
Then aggregate with benchmarks/trace_summary.py.
Honors the same BENCH_* env knobs as bench.py (benchmarks/bench_engine.py).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

from bench_engine import build_bench_engine  # noqa: E402


def main():
    outdir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/dstpu_trace"
    import jax
    engine, batch = build_bench_engine()

    for _ in range(3):
        engine.train_batch(batch)
    float(np.asarray(engine.state["step"]))

    with jax.profiler.trace(outdir):
        for _ in range(3):
            engine.train_batch(batch)
        float(np.asarray(engine.state["step"]))
    print(f"trace written to {outdir}")

    # immediate step anatomy (the trace_summary/reconcile CLIs go
    # deeper; this is the at-a-glance readout)
    from deepspeed_tpu.profiling import step_trace  # noqa: E402
    d = step_trace.decompose_dir(outdir, steps=3, mesh=engine.mesh)
    if d is not None:
        print(f"step decomposition ({d.total_device_ms:.1f} ms/step, "
              f"coverage {d.coverage_pct:.1f}%):")
        for term, ms in sorted(d.terms.items(), key=lambda kv: -kv[1]):
            if ms > 0:
                print(f"  {term:>14}: {ms:.2f} ms")


if __name__ == "__main__":
    main()
