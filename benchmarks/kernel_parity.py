"""On-chip Pallas kernel parity gate.

CI exercises every Pallas kernel in interpreter mode (tests/conftest.py
provisions a CPU mesh); this module is the real-Mosaic counterpart: tiny
shapes, compiled for the actual TPU, asserted against the dense
references — so every driver ``bench.py`` run also validates that
interpreter numerics and Mosaic numerics agree (a divergence would
otherwise ship silently). The TPU substitute for the reference's
per-kernel GPU CI (tests/unit/ops/).

``run()`` returns a dict enumerating EVERY shipped kernel path with its
status ("ok" or the failure string), so the bench JSON's
``kernels_parity`` field names each gate individually: the flash core +
its transposed-operand and q-major-backward variants, the bias family
(ALiBi, learned pair bias incl. d_bias cotangents, sliding window), the
evoformer fold, the SplitFuse fused chunk program, the paged/
block-sparse/quant/fused-CE kernels, the layout-owning MLP matmul, and
every cached autotune winner (tuned-vs-reference rows, so a stale or
wrong winner cache fails numerically instead of silently).

Budget: a few seconds of device time; tens of seconds of compiles.
Tolerances are bf16-scale — on TPU both the kernels and the dense
references run their dots on the MXU in bf16.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["run"]

_TOL = dict(rtol=2e-2, atol=2e-2)


def _close(a, b, what, tol=_TOL):
    a = np.asarray(a, np.float32)
    b = np.asarray(b, np.float32)
    np.testing.assert_allclose(a, b, err_msg=what, **tol)


def _flash(rng):
    from deepspeed_tpu.ops.pallas.flash_attention import (
        attention_reference, flash_attention)
    B, H, T, d = 2, 4, 256, 64
    ks = jax.random.split(rng, 4)
    q, k, v = (jax.random.normal(ks[i], (B, H, T, d), jnp.bfloat16)
               for i in range(3))
    do = jax.random.normal(ks[3], (B, H, T, d), jnp.bfloat16)

    def fl(q, k, v):
        return flash_attention(q, k, v, causal=True, heads_major=True,
                               block_q=128, block_k=128, interpret=False)

    def ref(q, k, v):
        return attention_reference(
            q.swapaxes(1, 2), k.swapaxes(1, 2), v.swapaxes(1, 2),
            causal=True).swapaxes(1, 2)

    # elementwise forward parity (outputs are O(1) post-softmax values),
    # then elementwise cotangent parity through each backward
    of, pull_f = jax.vjp(fl, q, k, v)
    orf, pull_r = jax.vjp(ref, q, k, v)
    _close(of, orf, "flash fwd")
    for a, b, n in zip(pull_f(do), pull_r(do), "qkv"):
        _close(a, b, f"flash d{n}", dict(rtol=5e-2, atol=5e-2))


def _flash_t(rng, qmajor):
    """Transposed-operand (qkv_t) path — the training bench path — and
    its q-major fused backward variant."""
    from deepspeed_tpu.ops.pallas.flash_attention import (
        attention_reference, flash_attention)
    B, H, T, d = 2, 4, 256, 64
    ks = jax.random.split(rng, 4)
    q, k, v = (jax.random.normal(ks[i], (B, H, d, T), jnp.bfloat16)
               for i in range(3))
    do = jax.random.normal(ks[3], (B, H, T, d), jnp.bfloat16)

    def fl(q, k, v):
        return flash_attention(q, k, v, causal=True, qkv_t=True,
                               block_q=128, block_k=128,
                               bwd_qmajor=qmajor, interpret=False)

    def ref(q, k, v):
        qt, kt, vt = (x.transpose(0, 3, 1, 2) for x in (q, k, v))
        return attention_reference(qt, kt, vt, causal=True) \
            .transpose(0, 2, 1, 3)                 # (B, H, T, d)

    of, pull_f = jax.vjp(fl, q, k, v)
    orf, pull_r = jax.vjp(ref, q, k, v)
    tag = "qmajor" if qmajor else "qkv_t"
    _close(of, orf, f"flash[{tag}] fwd")
    for a, b, n in zip(pull_f(do), pull_r(do), "qkv"):
        _close(a, b, f"flash[{tag}] d{n}", dict(rtol=5e-2, atol=5e-2))


def _flash_alibi(rng):
    from deepspeed_tpu.ops.pallas.flash_attention import (
        attention_reference, flash_attention)
    from deepspeed_tpu.ops.pallas.paged_attention import alibi_slopes
    B, H, T, d = 2, 6, 128, 64                    # non-power-of-two heads
    ks = jax.random.split(rng, 3)
    q, k, v = (jax.random.normal(ks[i], (B, T, H, d), jnp.bfloat16)
               for i in range(3))
    sl = alibi_slopes(H)
    ab = jnp.asarray(sl, jnp.float32)[None, :, None, None] \
        * jnp.arange(T, dtype=jnp.float32)[None, None, None, :]
    gf = jax.grad(lambda *a: jnp.sum(flash_attention(
        *a, alibi=sl, block_q=128, block_k=128, interpret=False)
        .astype(jnp.float32) ** 2), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda *a: jnp.sum(attention_reference(
        *a, bias=ab).astype(jnp.float32) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(gf, gr, "qkv"):
        _close(a, b, f"flash+alibi d{n}", dict(rtol=5e-2, atol=5e-2))


def _flash_pair_bias(rng):
    """Learned pair bias: forward parity AND the in-kernel d_bias
    accumulation (the evoformer-training cotangent)."""
    from deepspeed_tpu.ops.pallas.flash_attention import (
        attention_reference, flash_attention)
    B, H, T, d = 2, 4, 128, 64
    ks = jax.random.split(rng, 4)
    q, k, v = (jax.random.normal(ks[i], (B, T, H, d), jnp.bfloat16)
               for i in range(3))
    bias = jax.random.normal(ks[3], (B, H, T, T), jnp.float32) * 0.3

    def loss_f(b):
        return jnp.sum(flash_attention(
            q, k, v, bias=b, bias_grad=True, causal=True, block_q=128,
            block_k=128, interpret=False).astype(jnp.float32) ** 2)

    def loss_r(b):
        return jnp.sum(attention_reference(
            q, k, v, bias=b, causal=True).astype(jnp.float32) ** 2)

    _close(flash_attention(q, k, v, bias=bias, causal=True, block_q=128,
                           block_k=128, interpret=False),
           attention_reference(q, k, v, bias=bias, causal=True),
           "flash pair-bias fwd")
    _close(jax.grad(loss_f)(bias), jax.grad(loss_r)(bias),
           "flash d_bias", dict(rtol=5e-2, atol=5e-2))


def _flash_window(rng):
    from deepspeed_tpu.ops.pallas.flash_attention import (
        attention_reference, flash_attention, NEG_INF)
    B, H, T, d = 2, 4, 256, 64
    ks = jax.random.split(rng, 3)
    q, k, v = (jax.random.normal(ks[i], (B, T, H, d), jnp.bfloat16)
               for i in range(3))
    win = 100
    o = flash_attention(q, k, v, causal=True, window=win, block_q=128,
                        block_k=128, interpret=False)
    pos = jnp.arange(T)
    wmask = (pos[:, None] - pos[None, :] < win)
    bias = jnp.where(wmask, 0.0, NEG_INF)[None, None]
    ref = attention_reference(q, k, v, causal=True, bias=bias)
    _close(o, ref, "flash sliding-window")


def _evoformer(rng):
    """The evoformer fold adapter over the bias-capable flash kernel vs
    its chunked-XLA twin, incl. the pair-bias gradient."""
    from deepspeed_tpu.ops.evoformer_attn import evoformer_attention
    B, S, N, H, d = 1, 2, 64, 2, 32
    ks = jax.random.split(rng, 6)
    q, k, v = (jax.random.normal(ks[i], (B, S, N, H, d), jnp.bfloat16)
               for i in range(3))
    b1 = jax.random.normal(ks[3], (B, S, 1, 1, N), jnp.float32)
    b2 = jax.random.normal(ks[4], (B, 1, H, N, N), jnp.float32) * 0.3

    def f(impl):
        def g(b2_):
            return jnp.sum(evoformer_attention(
                q, k, v, biases=(b1, b2_), impl=impl)
                .astype(jnp.float32) ** 2)
        return g

    _close(evoformer_attention(q, k, v, biases=(b1, b2), impl="kernel"),
           evoformer_attention(q, k, v, biases=(b1, b2), impl="xla"),
           "evoformer fold fwd")
    _close(jax.grad(f("kernel"))(b2), jax.grad(f("xla"))(b2),
           "evoformer d_bias2", dict(rtol=5e-2, atol=5e-2))


def _splitfuse(rng):
    """The Dynamic SplitFuse fused chunk program (chunked prefill +
    running decode in one compiled dispatch) vs the bucketed-prefill
    engine — greedy outputs must be identical."""
    from deepspeed_tpu.inference.v2 import InferenceEngineV2
    from deepspeed_tpu.models import GPT2, GPT2Config
    from deepspeed_tpu.utils import groups
    cfg = GPT2Config(n_layer=2, n_head=4, d_model=64, max_seq_len=128,
                     vocab_size=256, remat=False, dtype="float32")
    model = GPT2(cfg)
    params = model.init(jax.random.key(0))
    base = {"dtype": "float32", "kv_block_size": 8, "prompt_bucket": 16,
            "max_batch_size": 4}
    groups.reset()
    legacy = InferenceEngineV2(model, params=params, config=dict(base))
    groups.reset()
    sf = InferenceEngineV2(model, params=params,
                           config=dict(base, splitfuse_tokens=16))
    rs = np.random.RandomState(0)
    prompts = [rs.randint(0, 256, (n,)).astype(np.int32)
               for n in (5, 16, 37)]
    want = legacy.generate_all(prompts, max_new_tokens=4)
    got = sf.generate_all(prompts, max_new_tokens=4)
    for w, g in zip(want, got):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w),
                                      err_msg="splitfuse fused program")
    groups.reset()


def _speculative(rng):
    """Draft-model speculative decoding vs plain decode: greedy
    spec-on output must be byte-identical to spec-off for BOTH model
    families (the verify program rides each family's own
    apply_paged_verify), and a mid-speculation cancel() must leave the
    target and draft allocators with zero leaked blocks."""
    from deepspeed_tpu.inference.v2 import InferenceEngineV2
    from deepspeed_tpu.models import GPT2, GPT2Config, Llama, LlamaConfig
    from deepspeed_tpu.utils import groups
    base = {"dtype": "float32", "kv_block_size": 8, "prompt_bucket": 16,
            "max_batch_size": 4}
    rs = np.random.RandomState(0)
    prompts = [rs.randint(1, 255, (n,)).astype(np.int32)
               for n in (5, 11, 16)]
    families = (
        ("gpt2",
         GPT2(GPT2Config(n_layer=2, n_head=4, d_model=64,
                         max_seq_len=128, vocab_size=256, remat=False,
                         dtype="float32")),
         GPT2(GPT2Config(n_layer=1, n_head=2, d_model=32,
                         max_seq_len=128, vocab_size=256, remat=False,
                         dtype="float32"))),
        ("llama",
         Llama(LlamaConfig(n_layer=2, n_head=4, n_kv_heads=2,
                           d_model=64, max_seq_len=128, vocab_size=256,
                           remat=False, dtype="float32")),
         Llama(LlamaConfig(n_layer=1, n_head=2, n_kv_heads=1,
                           d_model=32, max_seq_len=128, vocab_size=256,
                           remat=False, dtype="float32"))),
    )
    for name, model, draft in families:
        params = model.init(jax.random.key(0))
        dparams = draft.init(jax.random.key(1))
        groups.reset()
        plain = InferenceEngineV2(model, params=params,
                                  config=dict(base))
        want = plain.generate_all(prompts, max_new_tokens=10)
        groups.reset()
        spec = InferenceEngineV2(
            model, params=params,
            config=dict(base, spec_draft=True, spec_k=4),
            draft_model=draft, draft_params=dparams)
        got = spec.generate_all(prompts, max_new_tokens=10)
        for w, g in zip(want, got):
            np.testing.assert_array_equal(
                np.asarray(g), np.asarray(w),
                err_msg=f"speculative decode ({name})")
        tel = spec.telemetry.percentiles()
        assert tel.get("spec_rounds", 0) > 0, \
            f"speculation never engaged ({name})"
        # mid-speculation cancel: step until the sequence is actively
        # speculating, withdraw it, and audit both pools
        uid = spec.put(prompts[1], max_new_tokens=32)
        while True:
            spec.step()
            seq = spec.state_mgr._seqs.get(uid)
            if seq is not None and seq.draft_blocks:
                break
        assert spec.cancel(uid) is True
        alloc = spec.state_mgr.allocator
        assert alloc.free_blocks == alloc.total_blocks, \
            f"leaked target blocks after mid-spec cancel ({name})"
        da = spec.state_mgr.draft_allocator
        assert da.free_blocks == da.total_blocks, \
            f"leaked draft blocks after mid-spec cancel ({name})"
    groups.reset()


def _kv_handoff(rng):
    """Disaggregated prefill/decode handoff vs colocated decode: run
    prefill on engine P with the decode hold engaged, stream the KV
    blocks + descriptor through the wire format into engine D, and the
    completed greedy output must be byte-identical to a colocated
    reference — for BOTH model families (gpt2 rides the bucketed
    prefill path, llama/GQA rides the split-fuse chunked path). The
    re-export from D before it decodes proves the scatter placed every
    block payload byte-exactly; pool audits prove both sides close
    their accounting."""
    from deepspeed_tpu.inference.v2 import InferenceEngineV2, kv_transfer
    from deepspeed_tpu.models import GPT2, GPT2Config, Llama, LlamaConfig
    from deepspeed_tpu.utils import groups
    base = {"dtype": "float32", "kv_block_size": 8, "prompt_bucket": 16,
            "max_batch_size": 4}
    rs = np.random.RandomState(2)
    prompt = rs.randint(1, 255, (21,)).astype(np.int32)
    families = (
        ("gpt2", {},
         GPT2(GPT2Config(n_layer=2, n_head=4, d_model=64,
                         max_seq_len=128, vocab_size=256, remat=False,
                         dtype="float32"))),
        ("llama", {"splitfuse_tokens": 16},
         Llama(LlamaConfig(n_layer=2, n_head=4, n_kv_heads=2,
                           d_model=64, max_seq_len=128, vocab_size=256,
                           remat=False, dtype="float32"))),
    )
    for name, extra, model in families:
        params = model.init(jax.random.key(0))
        groups.reset()
        ref = InferenceEngineV2(model, params=params,
                                config=dict(base, **extra))
        want = ref.generate_all([prompt], max_new_tokens=8)[0]
        groups.reset()
        P = InferenceEngineV2(model, params=params,
                              config=dict(base, **extra))
        groups.reset()
        D = InferenceEngineV2(model, params=params,
                              config=dict(base, **extra))
        uid = P.put(prompt, max_new_tokens=8)
        P.hold_decode(uid)
        while True:
            P.step()
            seq = P.state_mgr._seqs.get(uid)
            if seq is not None and seq.generated:
                break
        state, _ = P.export_handoff(uid)
        payload = kv_transfer.export_sequence(P, uid)
        kv_transfer.import_sequence(D, payload)
        P.release_handoff(uid)
        alloc = P.state_mgr.allocator
        assert alloc.free_blocks == alloc.total_blocks, \
            f"prefill side leaked blocks after handoff ({name})"
        # round-trip proof: what D would export is byte-identical to
        # what P exported — the scatter landed every payload exactly
        state2, kv2 = D.export_handoff(uid)
        assert state2 == state, f"handoff state drifted ({name})"
        _, flat = kv_transfer.unpack_handoff(payload)
        from deepspeed_tpu.runtime.checkpoint_engine.serialization \
            import flatten_state
        flat2, _meta = flatten_state(kv2)
        for key, arr in flat.items():
            np.testing.assert_array_equal(
                np.asarray(flat2[key]), np.asarray(arr),
                err_msg=f"KV block payload {key} not byte-identical "
                        f"after import ({name})")
        while not D.is_done(uid):
            D.step()
        got = D.get(uid)
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(want),
            err_msg=f"disaggregated output != colocated ({name})")
        da = D.state_mgr.allocator
        assert da.free_blocks == da.total_blocks, \
            f"decode side leaked blocks after completion ({name})"
    groups.reset()


def _mlp_matmul(rng):
    from deepspeed_tpu.ops.pallas.mlp_matmul import _ref_proj, mlp_matmul
    B, T, K, M = 2, 256, 512, 256
    ks = jax.random.split(rng, 3)
    for x_t, out_t in ((True, False), (False, True)):
        x = jax.random.normal(ks[0], (B, K, T) if x_t else (B, T, K),
                              jnp.bfloat16)
        w = jax.random.normal(ks[1], (K, M), jnp.bfloat16)
        kw = dict(x_t=x_t, out_t=out_t, interpret=False)
        y = mlp_matmul(x, w, **kw)
        _close(y, _ref_proj(x, w, x_t, out_t), f"mlp fwd x_t={x_t}")
        dy = jax.random.normal(ks[2], y.shape, jnp.bfloat16)

        def f(x, w):
            return jnp.sum(mlp_matmul(x, w, **kw).astype(jnp.float32)
                           * dy.astype(jnp.float32))

        def fr(x, w):
            return jnp.sum(_ref_proj(x, w, x_t, out_t).astype(jnp.float32)
                           * dy.astype(jnp.float32))

        for a, b, n in zip(jax.grad(f, (0, 1))(x, w),
                           jax.grad(fr, (0, 1))(x, w), ("dx", "dw")):
            _close(a, b, f"mlp {n} x_t={x_t}",
                   dict(rtol=5e-2, atol=5e-1 if n == "dw" else 5e-2))


def _paged(rng):
    from deepspeed_tpu.ops.pallas.paged_attention import (
        paged_decode_attention, paged_decode_attention_reference)
    B, H, d = 4, 8, 64
    NB, BS, MB = 16, 16, 4
    ks = jax.random.split(rng, 4)
    q = jax.random.normal(ks[0], (B, H, d), jnp.bfloat16)
    kc = jax.random.normal(ks[1], (NB, H, BS, d), jnp.bfloat16)
    vc = jax.random.normal(ks[2], (NB, H, BS, d), jnp.bfloat16)
    tables = jax.random.randint(ks[3], (B, MB), 0, NB, jnp.int32)
    lengths = jnp.asarray([5, 63, 17, 30], jnp.int32)
    out = jax.jit(lambda *a: paged_decode_attention(*a, interpret=False))(
        q, kc, vc, tables, lengths)
    ref = jax.jit(paged_decode_attention_reference)(
        q, kc, vc, tables, lengths)
    _close(out, ref, "paged decode")


def _paged_chunk(rng):
    """The SplitFuse chunked-prefill paged kernel vs the dense-gather
    reference on real Mosaic: a GQA chunk straddling block boundaries
    mid-sequence, plus a sliding-window case."""
    from deepspeed_tpu.ops.pallas.paged_attention import (
        paged_chunk_attention, paged_chunk_attention_reference)
    C, H, KVH, d = 32, 8, 4, 64
    NB, BS, MB = 12, 32, 4
    ks = jax.random.split(rng, 4)
    q = jax.random.normal(ks[0], (C, H, d), jnp.bfloat16)
    kc = jax.random.normal(ks[1], (NB, KVH, BS, d), jnp.bfloat16)
    vc = jax.random.normal(ks[2], (NB, KVH, BS, d), jnp.bfloat16)
    table = jax.random.randint(ks[3], (MB,), 0, NB, jnp.int32)
    for start, true_len, window in ((45, 32, 0), (70, 20, 48)):
        out = jax.jit(lambda *a: paged_chunk_attention(
            *a, window=window, block_c=16, interpret=False))(
            q, kc, vc, table, jnp.int32(start), jnp.int32(true_len))
        ref = jax.jit(lambda *a: paged_chunk_attention_reference(
            *a, window=window))(
            q, kc, vc, table, jnp.int32(start), jnp.int32(true_len))
        _close(out[:true_len], ref[:true_len],
               f"paged chunk w={window}")


def _paged_tuned(rng, op):
    """Tuned-winner gate for the serving autotune ops: whatever config
    dispatch resolves for this chip's decode-shape bucket (cached
    winner or the cold-cache default) must reproduce the dense
    reference — the same winner-re-proving contract as the
    autotune_winners gate, but exercised for the engine's own ops even
    when the cache is cold."""
    from deepspeed_tpu.autotuning import kernel_dispatch, kernel_registry
    spec = kernel_registry.REGISTRY[op]
    bucket = {"paged_decode": "B8,MB8,BS32,kh4,g2,d64",
              "paged_chunk": "C32,MB8,BS32,kh4,g2,d64"}[op]
    b = kernel_registry.parse_bucket(bucket)
    params = kernel_dispatch.resolve(op, bucket, "bfloat16",
                                     spec["defaults"](b))
    spec["parity"](b, "bfloat16", params)


def _block_sparse(rng):
    from deepspeed_tpu.ops.pallas.block_sparse_attention import (
        block_sparse_attention)
    from deepspeed_tpu.ops.sparse_attention import FixedSparsityConfig
    B, H, T, d = 2, 4, 256, 64
    blk = 64
    layout = FixedSparsityConfig(
        num_heads=H, block=blk).make_layout(T)
    ks = jax.random.split(rng, 3)
    q, k, v = (jax.random.normal(ks[i], (B, T, H, d), jnp.bfloat16)
               for i in range(3))
    out = jax.jit(lambda q, k, v: block_sparse_attention(
        q, k, v, layout, blk, causal=True, interpret=False))(q, k, v)
    # dense reference with the same layout mask
    lay = np.asarray(jax.device_get(layout))
    if lay.ndim == 2:
        lay = np.broadcast_to(lay[None], (H,) + lay.shape)
    mask = np.kron(lay, np.ones((blk, blk), bool))[:, :T, :T]
    mask = np.tril(np.ones((T, T), bool))[None] & mask.astype(bool)
    s = jnp.einsum("bthd,bshd->bhts", q, k,
                   preferred_element_type=jnp.float32) / np.sqrt(d)
    s = jnp.where(jnp.asarray(mask)[None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    ref = jnp.einsum("bhts,bshd->bthd", p, v)
    _close(out, ref, "block-sparse fwd")


def _fused_ce(rng):
    from deepspeed_tpu.ops.pallas.fused_ce import unembed_logits_stats
    N, D, V = 256, 128, 1000     # V deliberately not a block multiple
    ks = jax.random.split(rng, 3)
    h = jax.random.normal(ks[0], (N, D), jnp.bfloat16)
    w = jax.random.normal(ks[1], (V, D), jnp.bfloat16)
    t = jax.random.randint(ks[2], (N,), 0, V, jnp.int32)
    logits, logz, gold = unembed_logits_stats(h, w, t, block_m=128,
                                              block_n=256,
                                              interpret=False)
    ref = jnp.einsum("nd,vd->nv", h, w,
                     preferred_element_type=jnp.float32)
    _close(logits, ref.astype(jnp.bfloat16), "fused-ce logits")
    _close(logz, jax.nn.logsumexp(ref, axis=-1), "fused-ce logz")
    _close(gold, jnp.take_along_axis(ref, t[:, None], axis=1)[:, 0],
           "fused-ce gold")


def _ring_block(rng):
    """The carry-state blockwise flash step (ring attention's chunk-pair
    kernel): two chained pairs (diagonal-causal + full) with carried
    (m, l, acc) state vs one dense softmax over the concatenated kv —
    proving the ring's state algebra on real Mosaic, plus the per-pair
    backward path via the fused bwd kernel with a GLOBAL lse."""
    from deepspeed_tpu.ops.pallas.flash_attention import (
        flash_block_bwd, flash_block_finalize, flash_block_fwd,
        flash_block_state)
    G, T, d = 4, 128, 64
    ks = jax.random.split(rng, 5)
    q, k1, v1, k2, v2 = (jax.random.normal(k, (G, T, d), jnp.bfloat16)
                         for k in ks)
    st = flash_block_state(G, T, d)
    st = flash_block_fwd(q, k1, v1, st, causal=True, block_q=64,
                         block_k=64, interpret=False)
    st = flash_block_fwd(q, k2, v2, st, causal=False, block_q=64,
                         block_k=64, interpret=False)
    o, lse = flash_block_finalize(st)

    kc = jnp.concatenate([k1, k2], axis=1)
    vc = jnp.concatenate([v1, v2], axis=1)
    s = jnp.einsum("gtd,gsd->gts", q, kc,
                   preferred_element_type=jnp.float32)
    mask = jnp.concatenate([jnp.tril(jnp.ones((T, T), jnp.bool_)),
                            jnp.ones((T, T), jnp.bool_)], axis=1)
    s = jnp.where(mask[None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    ref = jnp.einsum("gts,gsd->gtd", p, vc.astype(jnp.float32))
    _close(o, ref, "ring_block chained fwd")
    _close(lse, jax.nn.logsumexp(s, axis=-1), "ring_block lse")

    # pair backward from the global lse/o (the ring bwd recompute) vs
    # the dense vjp restricted to pair 1's kv
    do = jax.random.normal(ks[0], (G, T, d), jnp.bfloat16)
    ob = o.astype(jnp.bfloat16)
    dq1, dk1, dv1 = flash_block_bwd(q, k1, v1, ob, lse, do, causal=True,
                                    block_q=64, block_k=64,
                                    interpret=False)

    # dense pair-1 contribution with the global lse fixed, in the
    # analytic ds = p * (dp - delta) form the flash backward computes
    pa = jnp.exp(jnp.where(
        jnp.tril(jnp.ones((T, T), jnp.bool_))[None],
        jnp.einsum("gtd,gsd->gts", q.astype(jnp.float32),
                   k1.astype(jnp.float32)), -1e30) - lse[..., None])
    dof = do.astype(jnp.float32)
    delta = jnp.sum(dof * ref, axis=-1)
    dvr = jnp.einsum("gts,gtd->gsd", pa, dof)
    dpr = jnp.einsum("gtd,gsd->gts", dof, v1.astype(jnp.float32))
    dsr = pa * (dpr - delta[..., None])
    dkr = jnp.einsum("gts,gtd->gsd", dsr, q.astype(jnp.float32))
    dqr = jnp.einsum("gts,gsd->gtd", dsr, k1.astype(jnp.float32))
    for a, b, n in ((dq1, dqr, "dq"), (dk1, dkr, "dk"), (dv1, dvr, "dv")):
        _close(a, b, f"ring_block pair {n}", dict(rtol=5e-2, atol=5e-2))


def _moe_grouped(rng):
    """The dropless-MoE grouped-GEMM kernel vs lax.ragged_dot on real
    Mosaic: uneven groups incl. an empty one, fwd + all four grads
    through the fused SwiGLU chain, plus the plain grouped product."""
    from deepspeed_tpu.ops.pallas.grouped_matmul import (grouped_matmul,
                                                         grouped_swiglu)
    S, K, F, E = 256, 128, 256, 4
    ks = jax.random.split(rng, 4)
    x = jax.random.normal(ks[0], (S, K), jnp.bfloat16) * 0.3
    w1 = jax.random.normal(ks[1], (E, K, F), jnp.bfloat16) * 0.1
    w3 = jax.random.normal(ks[2], (E, K, F), jnp.bfloat16) * 0.1
    w2 = jax.random.normal(ks[3], (E, F, K), jnp.bfloat16) * 0.1
    gs = jnp.asarray([100, 0, 37, 119], jnp.int32)

    got = jax.jit(lambda x, w: grouped_matmul(
        x, w, gs, block_m=64, interpret=False))(x, w1)
    _close(got, jax.lax.ragged_dot(x, w1, gs), "moe grouped fwd")

    def lk(x, w1, w3, w2):
        return jnp.sum(grouped_swiglu(x, w1, w3, w2, gs, block_m=64,
                                      interpret=False)
                       .astype(jnp.float32) ** 2)

    def lr(x, w1, w3, w2):
        g = jax.lax.ragged_dot(x, w1, gs)
        u = jax.lax.ragged_dot(x, w3, gs)
        return jnp.sum(jax.lax.ragged_dot(jax.nn.silu(g) * u, w2, gs)
                       .astype(jnp.float32) ** 2)

    ga = jax.grad(lk, (0, 1, 2, 3))(x, w1, w3, w2)
    gr = jax.grad(lr, (0, 1, 2, 3))(x, w1, w3, w2)
    for a, b, n in zip(ga, gr, ("dx", "dw1", "dw3", "dw2")):
        _close(a, b, f"moe grouped swiglu {n}",
               dict(rtol=5e-2, atol=5e-1 if n != "dx" else 5e-2))


def _moe_grouped_tuned(rng):
    """Tuned-winner gate for the MoE grouped op: whatever dispatch
    resolves for this chip's bucket (cached winner or the cold-cache
    ragged default) must reproduce the ragged_dot reference — fwd and
    grads (the registry parity)."""
    from deepspeed_tpu.autotuning import kernel_dispatch, kernel_registry
    spec = kernel_registry.REGISTRY["moe_grouped_mm"]
    bucket = "S512,E8,M128,F256"
    b = kernel_registry.parse_bucket(bucket)
    params = kernel_dispatch.resolve("moe_grouped_mm", bucket, "bfloat16",
                                     spec["defaults"](b))
    spec["parity"](b, "bfloat16", params)


def _tuned_winners(rng):
    """Tuned-vs-reference parity for every cached autotune winner on
    THIS chip: a stale or wrong cache entry (edited file, toolchain
    bump that changed kernel numerics, foreign shapes) fails here
    numerically instead of silently steering the training step. Raises
    with a per-entry breakdown on any failure."""
    from deepspeed_tpu.autotuning import KernelCache, kernel_dispatch
    from deepspeed_tpu.autotuning import kernel_registry
    cache = KernelCache.load(kernel_dispatch.cache_path())
    entries = cache.for_device(kernel_dispatch.device_kind())
    if not entries:
        return                       # "ok": nothing cached, nothing stale
    failures = []
    for key, e in sorted(entries.items()):
        op = e.get("op")
        spec = kernel_registry.REGISTRY.get(op)
        if spec is None:
            failures.append(f"{key}: unknown op {op!r}")
            continue
        try:
            spec["parity"](kernel_registry.parse_bucket(e["bucket"]),
                           e["dtype"], e["params"])
        except Exception as ex:  # noqa: BLE001 — collect all entries
            failures.append(f"{key}: {type(ex).__name__}: {ex}"[:200])
    if failures:
        raise AssertionError(
            f"{len(failures)}/{len(entries)} cached winners failed "
            f"parity: " + "; ".join(failures))


def _quant(rng):
    from deepspeed_tpu.ops.pallas.quantization import (
        dequantize_blockwise, quantize_blockwise)
    x = jax.random.normal(rng, (512, 256), jnp.float32) * 3.0
    qp, sp, meta = quantize_blockwise(x, use_pallas=True, interpret=False)
    qr, sr, _ = quantize_blockwise(x, use_pallas=False)
    _close(qp, qr, "int8 quantize codes", dict(rtol=0, atol=1))
    yp = dequantize_blockwise(qp, sp, meta, use_pallas=True,
                              interpret=False)
    # roundtrip error bound is s/2 = blockwise absmax/254 (~0.055 for
    # |x| up to ~14 here)
    _close(yp, x, "int8 roundtrip", dict(rtol=0, atol=0.08))


def _mlp_wq(rng, bits):
    """Fused weight-only dequant projection kernel (W8A16/W4A16 serving
    FFN, ops/pallas/mlp_matmul.wq_matmul): kernel output vs the
    dequantize-then-einsum reference, every layout orientation. The
    reference uses the SAME quantized codes, so the gate isolates the
    kernel's epilogue arithmetic from quantization error itself."""
    import numpy as np
    from deepspeed_tpu.ops.int8_weights import quantize_leaf
    from deepspeed_tpu.ops.pallas.mlp_matmul import wq_matmul
    ks = jax.random.split(rng, 2)
    B, T, D, F = 2, 128, 128, 256
    x = jax.random.normal(ks[0], (B, T, D), jnp.bfloat16)
    w = np.asarray(jax.random.normal(ks[1], (D, F), jnp.float32)) * 0.1
    qw = quantize_leaf(w, bits=bits)
    wf = qw.dequant(jnp.float32)
    for x_t, out_t in ((False, False), (False, True), (True, False),
                       (True, True)):
        xi = jnp.swapaxes(x, -1, -2) if x_t else x
        got = wq_matmul(xi, qw, x_t=x_t, out_t=out_t, interpret=None)
        ref = jnp.einsum("btd,df->bft" if out_t else "btd,df->btf",
                         x.astype(jnp.float32), wf).astype(x.dtype)
        _close(got, ref, f"mlp_wq{bits} x_t={x_t} out_t={out_t}",
               dict(rtol=5e-2, atol=5e-2))


def _moe_grouped_wq8(rng):
    """Fused weight-only dequant grouped-SwiGLU chain (quantized expert
    FFN serving, grouped_matmul.grouped_swiglu_wq): kernel vs the
    dequantize-then-ragged_dot reference over uneven groups."""
    import numpy as np
    from deepspeed_tpu.ops.int8_weights import quantize_leaf
    from deepspeed_tpu.ops.pallas.grouped_matmul import grouped_swiglu_wq
    ks = jax.random.split(rng, 4)
    S, E, M, F = 512, 8, 128, 256
    x = jax.random.normal(ks[0], (S, M), jnp.bfloat16) * 0.3
    mk = lambda k, sh: np.asarray(
        jax.random.normal(k, sh, jnp.float32)) * 0.1
    q1 = quantize_leaf(mk(ks[1], (E, M, F)), bits=8)
    q3 = quantize_leaf(mk(ks[2], (E, M, F)), bits=8)
    q2 = quantize_leaf(mk(ks[3], (E, F, M)), bits=8)
    sizes = jnp.asarray(np.bincount(np.arange(S) * 7919 % E,
                                    minlength=E), jnp.int32)
    got = grouped_swiglu_wq(x, q1, q3, q2, sizes, interpret=None)
    xf = x.astype(jnp.float32)
    g = jax.lax.ragged_dot(xf, q1.dequant(jnp.float32), sizes)
    u = jax.lax.ragged_dot(xf, q3.dequant(jnp.float32), sizes)
    h = (g * jax.nn.sigmoid(g)) * u
    ref = jax.lax.ragged_dot(h, q2.dequant(jnp.float32), sizes).astype(
        x.dtype)
    _close(got, ref, "moe_grouped_wq8", dict(rtol=5e-2, atol=5e-2))


def _int8_tuned(rng, op):
    """Tuned-winner gate for the W8A8 compute levers: whatever dispatch
    resolves for this chip's bucket (cached winner or the cold-cache
    {int8: 0} exact default) must pass the registry parity — so an int8
    winner that drifted past the gate fails here, and can never have
    been cached in the first place (search runs parity before
    caching)."""
    from deepspeed_tpu.autotuning import kernel_dispatch, kernel_registry
    spec = kernel_registry.REGISTRY[op]
    bucket = ("T512,D128,F512" if op == "mlp_int8"
              else "S512,E8,M128,F256")
    b = kernel_registry.parse_bucket(bucket)
    params = kernel_dispatch.resolve(op, bucket, "bfloat16",
                                     spec["defaults"](b))
    spec["parity"](b, "bfloat16", params)


# every shipped kernel path, gated individually (acceptance: the bench
# JSON's kernels_parity enumerates each)
_GATES = (
    ("flash", _flash),
    ("flash_qkv_t", lambda r: _flash_t(r, qmajor=False)),
    ("flash_bwd_qmajor", lambda r: _flash_t(r, qmajor=True)),
    ("flash_alibi", _flash_alibi),
    ("flash_pair_bias", _flash_pair_bias),
    ("flash_window", _flash_window),
    ("evoformer", _evoformer),
    ("splitfuse", _splitfuse),
    # draft-model speculation: spec-on greedy byte-identity (gpt2 +
    # llama) and the mid-speculation cancel() zero-leak audit
    ("speculative", _speculative),
    # disaggregated prefill/decode: P->D KV-block handoff byte-identity
    # vs colocated (gpt2 + llama/GQA) + both-side pool-closure audits
    ("kv_handoff", _kv_handoff),
    ("mlp_matmul", _mlp_matmul),
    ("paged", _paged),
    # the SplitFuse chunked-prefill paged kernel + the tuned-winner
    # gates for the two serving autotune ops (cached winner — or the
    # cold-cache default — vs the dense reference)
    ("paged_chunk", _paged_chunk),
    ("paged_decode_tuned", lambda r: _paged_tuned(r, "paged_decode")),
    ("paged_chunk_tuned", lambda r: _paged_tuned(r, "paged_chunk")),
    ("block_sparse", _block_sparse),
    ("quant", _quant),
    ("fused_ce", _fused_ce),
    # the dropless-MoE grouped-GEMM kernel (fused SwiGLU chain + plain
    # grouped product, fwd + grads) and its tuned-winner re-prove
    ("moe_grouped", _moe_grouped),
    ("moe_grouped_tuned", _moe_grouped_tuned),
    # fused weight-only dequant serving kernels (W8A16/W4A16 FFN +
    # quantized expert chain) and the W8A8 compute levers' tuned-winner
    # re-prove (cold default {int8: 0} is the exact fp program)
    ("mlp_wq8", lambda r: _mlp_wq(r, 8)),
    ("mlp_wq4", lambda r: _mlp_wq(r, 4)),
    ("moe_grouped_wq8", _moe_grouped_wq8),
    ("mlp_int8_tuned", lambda r: _int8_tuned(r, "mlp_int8")),
    ("moe_grouped_int8_tuned",
     lambda r: _int8_tuned(r, "moe_grouped_int8")),
    # the ring-attention carry-state blockwise flash step (chunk-pair
    # chaining + pair backward from the global lse)
    ("ring_block", _ring_block),
    # every cached autotune winner re-proved against the dense
    # references (ok when the cache is empty)
    ("autotune_winners", _tuned_winners),
)


def run(seed=0):
    """Run every kernel parity gate on the default backend. Returns
    {gate_name: "ok" | "FAILED: ..."} — failures are isolated so one
    broken path never hides the status of the rest."""
    rng = jax.random.key(seed)
    rngs = jax.random.split(rng, len(_GATES))
    out = {}
    for (name, fn), r in zip(_GATES, rngs):
        try:
            fn(r)
            out[name] = "ok"
        except Exception as e:
            out[name] = f"FAILED: {type(e).__name__}: {e}"[:300]
    return out


if __name__ == "__main__":
    res = run()
    print({"kernels_parity": res,
           "all_ok": all(v == "ok" for v in res.values())})
