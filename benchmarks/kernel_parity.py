"""On-chip Pallas kernel parity gate.

CI exercises every Pallas kernel in interpreter mode (tests/conftest.py
provisions a CPU mesh); this module is the real-Mosaic counterpart: tiny
shapes, compiled for the actual TPU, asserted against the dense
references — so every driver ``bench.py`` run also validates that
interpreter numerics and Mosaic numerics agree (a divergence would
otherwise ship silently). The TPU substitute for the reference's
per-kernel GPU CI (tests/unit/ops/).

Budget: well under a second of device time; a few seconds of compiles.
Tolerances are bf16-scale — on TPU both the kernels and the dense
references run their dots on the MXU in bf16.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["run"]

_TOL = dict(rtol=2e-2, atol=2e-2)


def _close(a, b, what, tol=_TOL):
    a = np.asarray(a, np.float32)
    b = np.asarray(b, np.float32)
    np.testing.assert_allclose(a, b, err_msg=what, **tol)


def _flash(rng):
    from deepspeed_tpu.ops.pallas.flash_attention import (
        attention_reference, flash_attention)
    B, H, T, d = 2, 4, 256, 64
    ks = jax.random.split(rng, 4)
    q, k, v = (jax.random.normal(ks[i], (B, H, T, d), jnp.bfloat16)
               for i in range(3))
    do = jax.random.normal(ks[3], (B, H, T, d), jnp.bfloat16)

    def fl(q, k, v):
        return flash_attention(q, k, v, causal=True, heads_major=True,
                               block_q=128, block_k=128, interpret=False)

    def ref(q, k, v):
        return attention_reference(
            q.swapaxes(1, 2), k.swapaxes(1, 2), v.swapaxes(1, 2),
            causal=True).swapaxes(1, 2)

    # elementwise forward parity (outputs are O(1) post-softmax values),
    # then elementwise cotangent parity through each backward
    of, pull_f = jax.vjp(fl, q, k, v)
    orf, pull_r = jax.vjp(ref, q, k, v)
    _close(of, orf, "flash fwd")
    for a, b, n in zip(pull_f(do), pull_r(do), "qkv"):
        _close(a, b, f"flash d{n}", dict(rtol=5e-2, atol=5e-2))


def _paged(rng):
    from deepspeed_tpu.ops.pallas.paged_attention import (
        paged_decode_attention, paged_decode_attention_reference)
    B, H, d = 4, 8, 64
    NB, BS, MB = 16, 16, 4
    ks = jax.random.split(rng, 4)
    q = jax.random.normal(ks[0], (B, H, d), jnp.bfloat16)
    kc = jax.random.normal(ks[1], (NB, H, BS, d), jnp.bfloat16)
    vc = jax.random.normal(ks[2], (NB, H, BS, d), jnp.bfloat16)
    tables = jax.random.randint(ks[3], (B, MB), 0, NB, jnp.int32)
    lengths = jnp.asarray([5, 63, 17, 30], jnp.int32)
    out = jax.jit(lambda *a: paged_decode_attention(*a, interpret=False))(
        q, kc, vc, tables, lengths)
    ref = jax.jit(paged_decode_attention_reference)(
        q, kc, vc, tables, lengths)
    _close(out, ref, "paged decode")


def _block_sparse(rng):
    from deepspeed_tpu.ops.pallas.block_sparse_attention import (
        block_sparse_attention)
    from deepspeed_tpu.ops.sparse_attention import FixedSparsityConfig
    B, H, T, d = 2, 4, 256, 64
    blk = 64
    layout = FixedSparsityConfig(
        num_heads=H, block=blk).make_layout(T)
    ks = jax.random.split(rng, 3)
    q, k, v = (jax.random.normal(ks[i], (B, T, H, d), jnp.bfloat16)
               for i in range(3))
    out = jax.jit(lambda q, k, v: block_sparse_attention(
        q, k, v, layout, blk, causal=True, interpret=False))(q, k, v)
    # dense reference with the same layout mask
    lay = np.asarray(jax.device_get(layout))
    if lay.ndim == 2:
        lay = np.broadcast_to(lay[None], (H,) + lay.shape)
    mask = np.kron(lay, np.ones((blk, blk), bool))[:, :T, :T]
    mask = np.tril(np.ones((T, T), bool))[None] & mask.astype(bool)
    s = jnp.einsum("bthd,bshd->bhts", q, k,
                   preferred_element_type=jnp.float32) / np.sqrt(d)
    s = jnp.where(jnp.asarray(mask)[None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    ref = jnp.einsum("bhts,bshd->bthd", p, v)
    _close(out, ref, "block-sparse fwd")


def _fused_ce(rng):
    from deepspeed_tpu.ops.pallas.fused_ce import unembed_logits_stats
    N, D, V = 256, 128, 1000     # V deliberately not a block multiple
    ks = jax.random.split(rng, 3)
    h = jax.random.normal(ks[0], (N, D), jnp.bfloat16)
    w = jax.random.normal(ks[1], (V, D), jnp.bfloat16)
    t = jax.random.randint(ks[2], (N,), 0, V, jnp.int32)
    logits, logz, gold = unembed_logits_stats(h, w, t, block_m=128,
                                              block_n=256,
                                              interpret=False)
    ref = jnp.einsum("nd,vd->nv", h, w,
                     preferred_element_type=jnp.float32)
    _close(logits, ref.astype(jnp.bfloat16), "fused-ce logits")
    _close(logz, jax.nn.logsumexp(ref, axis=-1), "fused-ce logz")
    _close(gold, jnp.take_along_axis(ref, t[:, None], axis=1)[:, 0],
           "fused-ce gold")


def _quant(rng):
    from deepspeed_tpu.ops.pallas.quantization import (
        dequantize_blockwise, quantize_blockwise)
    x = jax.random.normal(rng, (512, 256), jnp.float32) * 3.0
    qp, sp, meta = quantize_blockwise(x, use_pallas=True, interpret=False)
    qr, sr, _ = quantize_blockwise(x, use_pallas=False)
    _close(qp, qr, "int8 quantize codes", dict(rtol=0, atol=1))
    yp = dequantize_blockwise(qp, sp, meta, use_pallas=True,
                              interpret=False)
    # roundtrip error bound is s/2 = blockwise absmax/254 (~0.055 for
    # |x| up to ~14 here)
    _close(yp, x, "int8 roundtrip", dict(rtol=0, atol=0.08))


def run(seed=0):
    """Run all kernel parity checks on the default backend. Returns
    'ok' or raises with the failing kernel named."""
    rng = jax.random.key(seed)
    rngs = jax.random.split(rng, 5)
    _flash(rngs[0])
    _paged(rngs[1])
    _block_sparse(rngs[2])
    _quant(rngs[3])
    _fused_ce(rngs[4])
    return "ok"


if __name__ == "__main__":
    print({"kernels_parity": run()})
