"""2-host virtual-mesh telemetry probe: the cluster-aggregation +
straggler-delta numbers for ``bench.py`` ``extras.telemetry``.

Spawns N single-process workers (default 2) that each train a tiny
engine on the CPU backend with telemetry armed and the fs cluster
transport ringed through a shared tmp dir (``DSTPU_TELEM_DIR`` +
``DSTPU_TELEM_NODE``/``DSTPU_TELEM_PEERS`` — the same virtual-host
idiom the elastic-agent tests use with ``DSTPU_HOT_*``). Host 1 runs a
genuinely heavier per-step workload (larger micro batch), so the
aggregation has a REAL straggler to find — no injected sleeps in the
production path. The ring's first node gathers at its final flush
(with a wait so the peers' files land) and prints the pod aggregate.

Standalone:  python benchmarks/telemetry_probe.py [--hosts 2]
             [--steps 6] [--straggle-factor 4]
prints one JSON object; bench.py embeds it as
``extras.telemetry.cluster``.
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile


def worker(args):
    """One virtual host: tiny engine, telemetry on, fs cluster ring."""
    import numpy as np

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from __graft_entry__ import _provision
    _provision(1)
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2, GPT2_TINY

    micro = args.micro
    model = GPT2(GPT2_TINY)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": micro,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "steps_per_print": 0,
        "telemetry": {"enabled": True, "interval_steps": args.steps,
                      "cluster_agg": True},
    })
    rng = np.random.RandomState(0)
    batch = {"input_ids": rng.randint(
        0, 1024, (engine.config.train_batch_size, 128)).astype(np.int32)}
    for _ in range(args.warmup):
        engine.train_batch(batch)
    # restart the interval window post-warmup so compile time never
    # poses as a straggler
    engine.telemetry.reset_window()
    for _ in range(args.steps):
        engine.train_batch(batch)
    # the flush at step == interval ran; ring node 0 re-gathers with a
    # wait so every peer's final metrics are in
    tel = engine.telemetry
    tel.drain()
    out = dict(tel.snapshot())
    if tel.cluster is not None and tel.cluster.is_root:
        last = out.get("cluster")
        metrics = {"node": tel.cluster.node,
                   "step": out.get("step", args.steps),
                   "mean_step_ms": out.get("mean_step_ms")}
        from deepspeed_tpu.monitor.telemetry import aggregate_cluster
        got = tel.cluster.gather(metrics, wait_s=20.0)
        agg = aggregate_cluster(got, order=tel.cluster.peers) or last
        out["cluster"] = agg
    print("TELEM_PROBE " + json.dumps(out))
    return 0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--hosts", type=int, default=2)
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--micro", type=int, default=2)
    # the straggler's micro batch = micro * straggle-factor: a real
    # workload skew, measured end to end
    ap.add_argument("--straggle-factor", type=int, default=4)
    ap.add_argument("--worker", action="store_true")
    args = ap.parse_args()
    if args.worker:
        return worker(args)

    hosts = [f"h{i}" for i in range(args.hosts)]
    with tempfile.TemporaryDirectory(prefix="dstpu_telem_probe_") as d:
        procs = []
        for i, h in enumerate(hosts):
            env = dict(os.environ)
            env.pop("XLA_FLAGS", None)
            env.update({
                "JAX_PLATFORMS": "cpu",
                "DSTPU_TELEM_DIR": d,
                "DSTPU_TELEM_NODE": h,
                "DSTPU_TELEM_PEERS": ",".join(hosts),
            })
            micro = args.micro * (args.straggle_factor
                                  if i == len(hosts) - 1 else 1)
            procs.append(subprocess.Popen(
                [sys.executable, os.path.abspath(__file__), "--worker",
                 "--steps", str(args.steps), "--warmup",
                 str(args.warmup), "--micro", str(micro)],
                env=env, stdout=subprocess.PIPE, text=True))
        outs = [p.communicate(timeout=600)[0] for p in procs]
        for p in procs:
            if p.returncode != 0:
                raise SystemExit(f"probe worker failed rc={p.returncode}")
        root = next(
            (json.loads(line[len("TELEM_PROBE "):])
             for out in outs for line in out.splitlines()
             if line.startswith("TELEM_PROBE ")
             and json.loads(line[len("TELEM_PROBE "):]).get("cluster")),
            None)
    report = {"hosts": len(hosts),
              "cluster": (root or {}).get("cluster"),
              "root_snapshot": {k: v for k, v in (root or {}).items()
                                if k != "cluster"}}
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
