"""Collective-communication microbenchmark.

Counterpart of reference ``bin/ds_bench`` + ``benchmarks/communication``
(all_reduce/all_gather/all_to_all sweeps): times each collective over the
current mesh's data axes across a size sweep and prints algorithmic
bandwidth. Run on any topology:

    python benchmarks/comm_bench.py [--sizes-mb 1 16 64] [--trials 10]

On a single chip the numbers are loopback; on a pod they measure ICI/DCN.
"""

import argparse
import time

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import numpy as np
import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from deepspeed_tpu import comm as dist
from deepspeed_tpu.utils import groups


def _timeit(fn, x, trials):
    out = fn(x)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(trials):
        out = fn(x)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / trials


def bench(sizes_mb, trials=10, axis="data"):
    topo = groups.get_topology()
    mesh = topo.mesh
    W = mesh.shape[axis]
    results = []

    def make(op_name, body, out_specs):
        return op_name, jax.jit(lambda x: shard_map(
            body, mesh=mesh, in_specs=P(axis),
            out_specs=out_specs, check_vma=False)(x))

    ops = [
        make("all_reduce", lambda x: dist.all_reduce(x, axis), P(axis)),
        make("all_gather",
             lambda x: dist.all_gather(x, axis), P(None, axis)),
        make("reduce_scatter",
             lambda x: dist.reduce_scatter(x.reshape(W, -1), axis),
             P(axis)),
        make("all_to_all",
             lambda x: dist.all_to_all(x.reshape(W, -1), axis, 0, 0),
             P(axis)),
        make("quantized_reduce_scatter",
             lambda x: dist.quantized_reduce_scatter(x.reshape(-1), axis),
             P(axis)),
    ]
    for mb in sizes_mb:
        n = int(mb * 1e6 / 4)
        n = max(W * 2048, n // (W * 2048) * (W * 2048))
        x = jnp.asarray(np.random.RandomState(0).randn(W, n // W),
                        jnp.float32)
        for name, fn in ops:
            try:
                dt = _timeit(fn, x, trials)
                # algorithmic bandwidth: bytes moved per rank ~ 2(W-1)/W
                # x payload for ring allreduce; report payload/s (simple,
                # comparable across ops like the reference does)
                gbps = x.nbytes / dt / 1e9
                results.append((name, mb, dt * 1e3, gbps))
                print(f"{name:28s} {mb:6.1f}MB  {dt * 1e3:8.3f}ms "
                      f"{gbps:8.2f} GB/s")
            except Exception as e:  # noqa: BLE001
                print(f"{name:28s} {mb:6.1f}MB  FAIL {e}")
    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes-mb", type=float, nargs="+",
                    default=[1, 16, 64])
    ap.add_argument("--trials", type=int, default=10)
    ap.add_argument("--axis", default="data")
    args = ap.parse_args()
    dist.init_distributed()
    groups.initialize()
    print(f"mesh: {dict(groups.get_mesh().shape)}")
    bench(args.sizes_mb, args.trials, args.axis)


if __name__ == "__main__":
    main()
