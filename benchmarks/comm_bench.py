"""Collective-communication microbenchmark.

Counterpart of reference ``bin/ds_bench`` + ``benchmarks/communication``
(all_reduce/all_gather/all_to_all sweeps): times each collective over the
current mesh's data axes across a size sweep and prints both payload
bandwidth and the ALGORITHMIC bus bandwidth (the ``2(W-1)/W`` ring factor
for all-reduce, ``(W-1)/W`` for gather/scatter ops — the number NCCL
tables and the reference's busbw column report). Run on any topology:

    python benchmarks/comm_bench.py [--sizes-mb 1 16 64] [--trials 10]
                                    [--axis data] [--json]

``--json`` prints one machine-readable line to stdout (the driver
archives it) and moves the human table to stderr.

The overlap probe (--overlap-mb) times a collective issued concurrently
with an independent matmul chain inside one jitted program and reports
how much of the collective's wall time the chain hides — the
latency-hiding-scheduler acceptance number. On a single chip the
collectives are loopback; on a pod they measure ICI/DCN.
"""

import argparse
import json
import math
import sys
import time

import os

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import numpy as np
import jax
import jax.numpy as jnp

from deepspeed_tpu import comm as dist       # noqa: F401 (installs the
from deepspeed_tpu.utils import groups       # older-jax shard_map shim)

from jax import shard_map  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

# algorithmic bus-bandwidth factor vs raw payload/time: a ring all-reduce
# moves 2(W-1)/W x payload per rank; gather/scatter/alltoall move
# (W-1)/W. (The factor the old comment named but the code never applied.)
_BUS_FACTOR = {
    "all_reduce": lambda w: 2 * (w - 1) / w,
    "all_gather": lambda w: (w - 1) / w,
    "reduce_scatter": lambda w: (w - 1) / w,
    "all_to_all": lambda w: (w - 1) / w,
    "quantized_reduce_scatter": lambda w: (w - 1) / w,
    # neighbor exchange (ring shift): every rank wires its full payload
    # exactly once — the ring-attention KV rotation primitive, so this
    # row is the bandwidth bound on hiding one rotation under one ring
    # step's compute
    "ppermute": lambda w: 1.0,
    # the same exchange over the 'pipe' axis: the pipeline executors'
    # per-tick activation rotation (runtime/pipe/spmd.py) — the
    # bandwidth bound on hiding one stage handoff under one tick's
    # block compute (--pipe N carves the axis on flat meshes)
    "ppermute_pipe": lambda w: 1.0,
    # hierarchical expert dispatch (moe_swiglu_ragged_ep's staged
    # exchange): ICI-local all_to_all over the inner axis, then ONE
    # cross-slice hop over data_outer — vs the flat single-hop
    # all_to_all row above over the same combined shard grid. The int8
    # variant applies the qgZ clamp to the DCN leg (the MoE
    # dcn_quantize numerics; wire stays fp32 in this emulation, so the
    # row measures the clamp's compute cost, not a byte saving).
    "all_to_all_flat": lambda w: (w - 1) / w,
    "all_to_all_2stage": lambda w: (w - 1) / w,
    "all_to_all_2stage_int8": lambda w: (w - 1) / w,
}


def _timeit(fn, x, trials):
    out = fn(x)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(trials):
        out = fn(x)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / trials


def _wire_bytes(name, x):
    """Bytes a rank actually puts on the wire per call: fp32 payload for
    the plain ops; int8 + one fp32 scale per 2048-block for the
    quantized reduce-scatter."""
    if name == "quantized_reduce_scatter":
        n = int(x.size)
        return n + 4 * (-(-n // 2048))
    return x.nbytes


def bench(sizes_mb, trials=10, axis="data", outer_axis="data_outer",
          out=sys.stdout):
    topo = groups.get_topology()
    mesh = topo.mesh
    W = mesh.shape[axis]
    Wo = dict(mesh.shape).get(outer_axis, 1)
    results = []

    def make(op_name, body, out_specs, in_specs=None):
        return op_name, jax.jit(lambda x: shard_map(
            body, mesh=mesh, in_specs=in_specs or P(axis),
            out_specs=out_specs, check_vma=False)(x))

    def two_stage(quantize):
        """The MoE staged dispatch: buckets keyed (inner rank, outer
        slice), exchanged over the inner (ICI) axis then over
        data_outer (DCN) — read against the flat single-hop
        all_to_all_flat row over the same combined shard grid."""
        def body(x):
            xb = x.reshape(W, Wo, -1)
            xb = dist.all_to_all(xb, axis, 0, 0)
            if quantize:
                from deepspeed_tpu.comm.quantized import \
                    dcn_precision_clamp
                xb = dcn_precision_clamp(xb)
            return dist.all_to_all(xb, outer_axis, 1, 1)
        return body

    ops = [
        make("all_reduce", lambda x: dist.all_reduce(x, axis), P(axis)),
        make("all_gather",
             lambda x: dist.all_gather(x, axis), P(None, axis)),
        make("reduce_scatter",
             lambda x: dist.reduce_scatter(x.reshape(W, -1), axis),
             P(axis)),
        make("all_to_all",
             lambda x: dist.all_to_all(x.reshape(W, -1), axis, 0, 0),
             P(axis)),
        make("quantized_reduce_scatter",
             lambda x: dist.quantized_reduce_scatter(x.reshape(-1), axis),
             P(axis)),
        make("ppermute",
             lambda x: dist.send_forward(x, axis), P(axis)),
    ]
    # entries: (name, jitted fn, combined-grid shard count) — the hier
    # pair exchanges over the (outer x inner) grid, so its payload
    # reshapes to W*Wo rows and its busbw factor uses the combined size
    ops = [(n, f, W) for n, f in ops]
    Wp = dict(mesh.shape).get("pipe", 1)
    if Wp > 1:
        # the pipe-axis neighbor exchange measured over ITS OWN axis
        # (payload sharded P('pipe'), W_pipe shards)
        ops.append((
            "ppermute_pipe",
            jax.jit(lambda x: shard_map(
                lambda x: dist.send_forward(x, "pipe"), mesh=mesh,
                in_specs=P("pipe"), out_specs=P("pipe"),
                check_vma=False)(x)),
            Wp))
    else:
        results.append({"op": "ppermute_pipe",
                        "skipped": "pipe axis is 1 on this mesh (use "
                                   "--pipe to carve one)"})
    if Wo > 1:
        hier = P((outer_axis, axis))
        ops += [
            ("all_to_all_flat",
             jax.jit(lambda x: shard_map(
                 lambda x: dist.all_to_all(
                     x.reshape(W * Wo, -1), (outer_axis, axis), 0, 0),
                 mesh=mesh, in_specs=hier, out_specs=hier,
                 check_vma=False)(x)), W * Wo),
            ("all_to_all_2stage",
             jax.jit(lambda x: shard_map(
                 two_stage(False), mesh=mesh, in_specs=hier,
                 out_specs=hier, check_vma=False)(x)), W * Wo),
            ("all_to_all_2stage_int8",
             jax.jit(lambda x: shard_map(
                 two_stage(True), mesh=mesh, in_specs=hier,
                 out_specs=hier, check_vma=False)(x)), W * Wo),
        ]
    else:
        results.append({"op": "all_to_all_2stage",
                        "skipped": f"{outer_axis} axis is 1 on this "
                                   f"mesh (use --outer to carve one)"})
    for mb in sizes_mb:
        n = int(mb * 1e6 / 4)
        # every row's reshape must divide: the quantized row needs
        # W*2048 | n, the hierarchical rows need (W*Wo)^2 | n (local
        # chunk n/(W*Wo) re-bucketed into W x Wo) — non-power-of-two
        # worlds (6 devices, --outer 3) break the naive W*Wo*2048 round
        # every reshaped row layout must divide, incl. the pipe row's
        # (Wp, -1) view — fold Wp in or non-dividing pipe sizes (e.g.
        # --pipe 3 on 6 devices) error out of every measurement
        blk = math.lcm(W * 2048, (W * Wo) ** 2,
                       dict(mesh.shape).get("pipe", 1))
        n = max(blk, n // blk * blk)
        x = jnp.asarray(np.random.RandomState(0).randn(W, n // W),
                        jnp.float32)
        for name, fn, wtot in ops:
            try:
                xi = x.reshape(wtot, -1) if wtot != W else x
                dt = _timeit(fn, xi, trials)
                wire = _wire_bytes(name, xi)
                gbps = wire / dt / 1e9
                busbw = gbps * _BUS_FACTOR[name](wtot)
                results.append({
                    "op": name, "mb": mb, "ms": round(dt * 1e3, 3),
                    "gbps": round(gbps, 3), "busbw_gbps": round(busbw, 3),
                })
                print(f"{name:28s} {mb:6.1f}MB  {dt * 1e3:8.3f}ms "
                      f"{gbps:8.2f} GB/s  bus {busbw:8.2f} GB/s",
                      file=out)
            except Exception as e:  # noqa: BLE001
                results.append({"op": name, "mb": mb,
                                "error": f"{type(e).__name__}: {e}"[:200]})
                print(f"{name:28s} {mb:6.1f}MB  FAIL {e}", file=out)
    return results


def _fit_alpha_beta(rows, shards):
    """(alpha_s, beta_Bps) from a payload sweep of one op: two-point fit
    of ``t = alpha + bytes/beta`` on the smallest and largest measured
    payloads (per-shard bytes — the wire a single link carries)."""
    pts = sorted((r["mb"] * 1e6 / max(1, shards), r["ms"] * 1e-3)
                 for r in rows if "ms" in r and r["ms"] > 0)
    if not pts:
        return None
    (b0, t0), (b1, t1) = pts[0], pts[-1]
    if b1 > b0 and t1 > t0:
        beta = (b1 - b0) / (t1 - t0)
        alpha = max(0.0, t0 - b0 / beta)
    else:
        beta = b1 / t1
        alpha = 0.0
    return alpha, beta


def cache_rows(results, mesh=None, axis="data", outer_axis="data_outer"):
    """Winner-cache entry rows distilled from a bench() sweep: one
    ``comm_link`` pseudo-op row per link class, in the exact shape
    ``autotuning.kernel_cache.seed_entries`` ingests. The ICI row fits
    alpha-beta from the ppermute sweep (neighbor exchange — the purest
    single-link measure); the DCN row from the hierarchical
    all_to_all_flat sweep when --outer carved a cross-slice axis; the
    'dcn_int8' row (dtype int8) from the qgZ-clamped staged sweep
    (all_to_all_2stage_int8 — alpha-beta over LOGICAL payload bytes, so
    the codec cost and any wire saving land in the coefficients; the
    planner's ``_score`` prices dcn_quantize'd legs with it).
    ``comm_link`` rows live in the cache file only — never in the op
    REGISTRY — so dispatch ignores them; the planner's
    ``calibrate_links`` is their sole reader."""
    from deepspeed_tpu.ops.pallas._common import topo_signature
    from deepspeed_tpu.autotuning.kernel_dispatch import device_kind
    mesh = mesh if mesh is not None else groups.get_mesh()
    shape = dict(mesh.shape)
    W = shape.get(axis, 1)
    Wo = shape.get(outer_axis, 1)
    topo = topo_signature(mesh)
    by_op = {}
    for r in results:
        by_op.setdefault(r.get("op"), []).append(r)
    rows = []
    for kind, op_name, dtype, shards in (
            ("ici", "ppermute", "float32", W),
            ("dcn", "all_to_all_flat", "float32", W * Wo),
            ("dcn_int8", "all_to_all_2stage_int8", "int8", W * Wo)):
        fit = _fit_alpha_beta(by_op.get(op_name, []), shards)
        if fit is None:
            continue
        alpha, beta = fit
        best = max((r for r in by_op[op_name] if "busbw_gbps" in r),
                   key=lambda r: r["mb"], default=None)
        rows.append({
            "device_kind": device_kind(), "op": "comm_link",
            "bucket": f"{topo},k{kind}", "dtype": dtype,
            "params": {
                "kind": kind,
                "alpha_us": round(alpha * 1e6, 3),
                "beta_gbps": round(beta / 1e9, 3),
                "busbw_gbps": (best or {}).get("busbw_gbps"),
                "source": op_name,
            },
            "measured_ms": (best or {}).get("ms"),
        })
    return rows


def overlap_probe(mb=16, trials=10, axis="data", chain=16, dim=1024,
                  out=sys.stdout):
    """Hidden-vs-exposed collective time: time (a) a matmul chain alone,
    (b) an all-reduce alone, (c) one jitted program running both on
    INDEPENDENT data. With a working latency-hiding schedule the
    combined time approaches max(a, b): ``exposed = t_both - t_compute``
    is the serialized remainder, ``hidden = t_comm - exposed`` the part
    the chain absorbed."""
    topo = groups.get_topology()
    mesh = topo.mesh
    W = mesh.shape[axis]
    n = max(W * 2048, int(mb * 1e6 / 4) // (W * 2048) * (W * 2048))
    x = jnp.asarray(np.random.RandomState(0).randn(W, n // W), jnp.float32)
    a = jnp.asarray(np.random.RandomState(1).randn(dim, dim), jnp.float32)

    def chain_fn(a):
        y = a
        for _ in range(chain):
            y = jnp.tanh(y @ a)
        return y

    reduce_fn = shard_map(lambda t: dist.all_reduce(t, axis), mesh=mesh,
                          in_specs=P(axis), out_specs=P(axis),
                          check_vma=False)
    f_comp = jax.jit(chain_fn)
    f_comm = jax.jit(reduce_fn)
    f_both = jax.jit(lambda x, a: (reduce_fn(x), chain_fn(a)))

    t_comp = _timeit(f_comp, a, trials)
    t_comm = _timeit(f_comm, x, trials)
    jax.block_until_ready(f_both(x, a))
    t0 = time.perf_counter()
    for _ in range(trials):
        o = f_both(x, a)
    jax.block_until_ready(o)
    t_both = (time.perf_counter() - t0) / trials

    exposed = max(0.0, t_both - t_comp)
    hidden = max(0.0, t_comm - exposed)
    rep = {
        "mb": mb, "chain": chain, "dim": dim,
        "t_compute_ms": round(t_comp * 1e3, 3),
        "t_comm_ms": round(t_comm * 1e3, 3),
        "t_both_ms": round(t_both * 1e3, 3),
        "exposed_ms": round(exposed * 1e3, 3),
        "hidden_ms": round(hidden * 1e3, 3),
        "hidden_frac": round(hidden / t_comm, 3) if t_comm > 0 else 0.0,
    }
    print(f"overlap probe  {mb:.1f}MB all_reduce || {chain}x{dim} matmul: "
          f"comm {rep['t_comm_ms']}ms comp {rep['t_compute_ms']}ms "
          f"both {rep['t_both_ms']}ms -> hidden {rep['hidden_ms']}ms "
          f"({rep['hidden_frac'] * 100:.0f}%)", file=out)
    return rep


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes-mb", type=float, nargs="+",
                    default=[1, 16, 64])
    ap.add_argument("--trials", type=int, default=10)
    ap.add_argument("--axis", default="data")
    ap.add_argument("--outer", type=int, default=0,
                    help="carve a data_outer axis of this size out of "
                         "DP (zero_shard_size) so the hierarchical "
                         "all_to_all rows run — the staging decision "
                         "probe for meshes without a real DCN axis")
    ap.add_argument("--pipe", type=int, default=0,
                    help="carve a pipe axis of this size so the "
                         "ppermute_pipe row (the pipeline executors' "
                         "per-tick stage handoff) measures over a real "
                         "pipe axis")
    ap.add_argument("--json", action="store_true",
                    help="one JSON line on stdout (table -> stderr)")
    ap.add_argument("--seed-cache", action="store_true",
                    help="merge the distilled comm_link alpha-beta rows "
                         "into the kernel winner cache "
                         "(DSTPU_AUTOTUNE_CACHE or the default path) so "
                         "the auto-parallelism planner calibrates from "
                         "measured link speeds")
    ap.add_argument("--overlap-mb", type=float, default=16,
                    help="overlap probe payload (0 disables the probe)")
    args = ap.parse_args()
    dist.init_distributed()
    if args.outer > 1 or args.pipe > 1:
        import jax as _jax
        n = len(_jax.devices())
        pipe = max(args.pipe, 1)
        if n % pipe:
            raise SystemExit(f"--pipe {args.pipe} does not divide "
                             f"world size {n}")
        dp = n // pipe   # the outer axis carves the REMAINING dp grid
        if args.outer > 1 and (dp % args.outer or dp // args.outer < 1):
            raise SystemExit(f"--outer {args.outer} does not divide "
                             f"the data-parallel size {dp} left after "
                             f"--pipe {pipe}")
        groups.initialize(groups.TopologyConfig(
            pipe_parallel_size=pipe,
            zero_shard_size=(dp // args.outer
                             if args.outer > 1 else -1)))
    else:
        groups.initialize()
    out = sys.stderr if args.json else sys.stdout
    print(f"mesh: {dict(groups.get_mesh().shape)}", file=out)
    results = bench(args.sizes_mb, args.trials, args.axis, out=out)
    overlap = None
    if args.overlap_mb:
        try:
            overlap = overlap_probe(args.overlap_mb, args.trials,
                                    args.axis, out=out)
        except Exception as e:  # noqa: BLE001
            overlap = {"error": f"{type(e).__name__}: {e}"[:200]}
            print(f"overlap probe FAIL {e}", file=out)
    rows = cache_rows(results, axis=args.axis)
    if args.seed_cache:
        from deepspeed_tpu.autotuning.kernel_cache import seed_entries
        from deepspeed_tpu.autotuning.kernel_dispatch import cache_path
        n = seed_entries(rows)
        print(f"seeded {n} comm_link row(s) -> {cache_path()}", file=out)
    if args.json:
        print(json.dumps({
            "mesh": dict(groups.get_mesh().shape),
            "axis": args.axis,
            "trials": args.trials,
            "results": results,
            "cache_rows": rows,
            "overlap": overlap,
        }))


if __name__ == "__main__":
    main()
