"""jnp-vs-Pallas microbenchmarks for the v1 serving kernel tier.

The reference ships fused CUDA kernels for rmsnorm and rotary embedding
(csrc/transformer/inference/csrc/{rms_norm,apply_rotary_pos_emb}.cu);
this repo's serving models use jnp forms and claims XLA fuses them well.
This bench MEASURES that claim on the chip: per-op device time for jnp
vs the Pallas alternative at serving shapes, using the slope method
(time K chained applications inside ONE jit for two K values; the slope
removes dispatch latency and jit constants, which dominate on the axon
tunnel). Prints one JSON line per comparison.

Run: python benchmarks/kernel_microbench.py

``--from-cache [path]``: instead of the serving-tier sweep, re-time
exactly the cached autotune winners (autotuning/kernel_cache.py) for
THIS chip with the same slope harness the search used — a one-command
verification that a shipped cache's timings still hold (after a
toolchain bump, on a new chip batch, ...). Prints one JSON row per
entry with the fresh measurement next to the cached one.
"""

import json
import sys
import os
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402

from deepspeed_tpu.models.llama import _rms_norm, _rope  # noqa: E402
from deepspeed_tpu.ops.pallas.layernorm import fused_rmsnorm  # noqa: E402


def timed_chain(op, x, k, reps=3):
    """Wall time of K data-dependent applications inside one jit."""
    def chain(x):
        def body(c, _):
            return op(c), None
        y, _ = lax.scan(body, x, None, length=k)
        return jnp.sum(y.astype(jnp.float32))

    f = jax.jit(chain)
    np.asarray(f(x))                       # compile + warm
    t0 = time.perf_counter()
    for _ in range(reps):
        r = f(x)
    np.asarray(r)
    return (time.perf_counter() - t0) / reps


def per_op_ms(op, x, k1=64, k2=512):
    """Slope between two chain lengths -> per-op seconds (dispatch and
    scan constants cancel)."""
    t1 = min(timed_chain(op, x, k1) for _ in range(3))
    t2 = min(timed_chain(op, x, k2) for _ in range(3))
    return 1e3 * (t2 - t1) / (k2 - k1)


def retime_from_cache(path=None, chain_lengths=(8, 24), reps=3):
    """Re-measure every cached winner for the current device; returns
    the printed rows. A winner whose step can no longer build/run is
    reported with an error instead of aborting the sweep."""
    from deepspeed_tpu.autotuning import (KernelCache, kernel_dispatch,
                                          kernel_registry)
    from deepspeed_tpu.autotuning.kernel_autotuner import time_step
    path = path or kernel_dispatch.cache_path()
    cache = KernelCache.load(path)
    entries = cache.for_device(kernel_dispatch.device_kind())
    rows = []
    if not entries:
        rows.append({"cache": path, "note": "no cached winners for "
                     f"device {kernel_dispatch.device_kind()!r}"})
    for key, e in sorted(entries.items()):
        row = {"op": e.get("op"), "bucket": e.get("bucket"),
               "dtype": e.get("dtype"), "params": e.get("params"),
               "cached_ms": e.get("measured_ms"),
               "cached_default_ms": e.get("default_ms")}
        spec = kernel_registry.REGISTRY.get(e.get("op"))
        if spec is None:
            row["error"] = f"unknown op {e.get('op')!r}"
        else:
            try:
                step, args = spec["make_step"](
                    kernel_registry.parse_bucket(e["bucket"]),
                    e["dtype"], e["params"])
                row["retimed_ms"] = round(
                    time_step(step, args, chain_lengths, reps), 4)
            except Exception as ex:  # noqa: BLE001 — sweep must finish
                row["error"] = f"{type(ex).__name__}: {ex}"[:200]
        rows.append(row)
    for r in rows:
        print(json.dumps(r))
    return rows


def main():
    if "--from-cache" in sys.argv:
        i = sys.argv.index("--from-cache")
        path = sys.argv[i + 1] if len(sys.argv) > i + 1 \
            and not sys.argv[i + 1].startswith("-") else None
        retime_from_cache(path)
        return
    B, T, H, hd = 8, 1024, 16, 64
    D = H * hd
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(B, T, D), jnp.bfloat16)
    s = jnp.asarray(1 + 0.1 * rng.randn(D), jnp.bfloat16)
    pos = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))

    rows = []
    jnp_ms = per_op_ms(lambda c: _rms_norm(c, s, 1e-5), x)
    pal_ms = per_op_ms(lambda c: fused_rmsnorm(c, s), x)
    rows.append({"op": "rmsnorm", "shape": [B, T, D],
                 "jnp_ms": round(jnp_ms, 4), "pallas_ms": round(pal_ms, 4),
                 "winner": "jnp" if jnp_ms <= pal_ms else "pallas"})

    xh = x.reshape(B, T, H, hd)
    rope_ms = per_op_ms(
        lambda c: _rope(c, pos, 10000.0), xh)
    rows.append({"op": "rope", "shape": [B, T, H, hd],
                 "jnp_ms": round(rope_ms, 4), "pallas_ms": None,
                 "winner": "jnp",
                 "note": "no Pallas variant: rope is pure elementwise "
                         "(sin/cos fused by XLA into neighbors); a "
                         "custom call could only break that fusion"})
    for r in rows:
        print(json.dumps(r))


if __name__ == "__main__":
    main()
