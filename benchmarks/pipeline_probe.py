"""Pipeline-parallel probe: a pp>=2 virtual-mesh (or real-pod) A/B of
the schedule and host-offload levers, runnable from a single-chip bench
process (the telemetry_probe pattern — bench.py shells out to it so the
pp rows land in ``BENCH_local.json`` even when the driver exposes one
chip).

Measures, at ``--pipe`` stages on a pipe-only mesh:
  * schedule A/B: zero-bubble (zb) vs gpipe vs 1f1b wall time of real
    optimizer steps (bubble fractions attached from the analytic
    lock-step model the telemetry layer reports);
  * offload A/B: the zb schedule with the activation rings host-placed
    vs device-resident — on backends with a real host memory kind the
    rows also record the compiled program's host-copy count and the
    memory-analysis temp bytes (the live-HBM drop the offload buys);
    on CPU (single memory space) the offload rows record
    ``host_kind: null`` and measure only the identity overhead.

Prints one JSON line: {"pipe": S, "rows": {...}}.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _build(pipe, schedule, offload, args):
    import numpy as np
    import jax
    import deepspeed_tpu
    from deepspeed_tpu.models import GPT2Pipe
    from deepspeed_tpu.models.gpt2 import PRESETS
    from dataclasses import replace
    from deepspeed_tpu.utils import groups
    from deepspeed_tpu.utils.groups import TopologyConfig

    cfg = replace(PRESETS[args.preset], max_seq_len=args.seq,
                  dtype=args.dtype, remat=True,
                  pipe_microbatches=args.micro_batches,
                  use_flash_attention=False)
    groups.reset()
    topo = groups.initialize(
        TopologyConfig(pipe_parallel_size=pipe, data_parallel_size=1),
        devices=jax.devices()[:pipe], force=True)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2Pipe(cfg), topology=topo, config={
            "train_micro_batch_size_per_gpu": args.batch,
            "gradient_accumulation_steps": 1,
            "steps_per_print": 0,
            "optimizer": {"type": "AdamW", "params": {"lr": 2e-4}},
            "gradient_clipping": 1.0,
            **({"bf16": {"enabled": True}}
               if args.dtype == "bfloat16" else {}),
            "zero_optimization": {"stage": args.zero_stage},
            "pipeline": {"schedule": schedule,
                         "offload_activations": bool(offload),
                         "offload_moments": False},
        })
    rng = np.random.RandomState(0)
    batch = {"input_ids": rng.randint(
        0, cfg.vocab_size, (args.batch, args.seq)).astype(np.int32)}
    return engine, batch


def _measure(pipe, schedule, offload, args):
    import numpy as np
    import jax
    engine, batch = _build(pipe, schedule, offload, args)
    loss = None
    for _ in range(args.warmup):
        loss = engine.train_batch(batch)
    float(np.asarray(engine.state["step"]))
    t0 = time.perf_counter()
    for _ in range(args.steps):
        loss = engine.train_batch(batch)
    float(np.asarray(engine.state["step"]))
    dt = time.perf_counter() - t0
    seq = engine.model.config.max_seq_len
    row = {
        "schedule": schedule, "offload": bool(offload),
        "tokens_per_sec_chip": round(
            args.batch * seq * args.steps / dt / pipe, 1),
        "step_time_s": round(dt / args.steps, 4),
        "final_loss": float(loss),
        "pipeline": engine.pipeline_report(),
    }
    if args.hlo:
        rep = engine.verify_comm_overlap(batch)
        row["hlo"] = {
            "in_loop_by_op": rep["in_loop_by_op"],
            "host_copies": rep["host_copies"],
            "in_loop_host_copies": rep["in_loop_host_copies"],
        }
        # live-HBM proof point: XLA's own buffer assignment for the
        # step program (the offload-on/off delta is the acceptance
        # number on real accelerators)
        try:
            with jax.set_mesh(engine.mesh):
                b = jax.tree.map(engine._add_gas_dim, batch)
                b = engine._shard_batch(b, with_gas_dim=True)
                c = engine._train_step_jit.lower(
                    engine.state, b, engine._current_lr(),
                    None).compile()
            ma = c.memory_analysis()
            row["memory"] = {
                "temp_bytes": int(ma.temp_size_in_bytes),
                "host_temp_bytes": int(
                    getattr(ma, "host_temp_size_in_bytes", 0) or 0),
            }
        except Exception as e:  # noqa: BLE001 - advisory
            row["memory"] = {"error": f"{type(e).__name__}: {e}"[:200]}
    return row


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--pipe", type=int, default=2)
    ap.add_argument("--preset", default="tiny")
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--micro-batches", type=int, default=4,
                    dest="micro_batches")
    ap.add_argument("--zero-stage", type=int, default=0,
                    dest="zero_stage")
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--warmup", type=int, default=1)
    ap.add_argument("--hlo", type=int, default=1)
    ap.add_argument("--rows", default="zb,gpipe,zb_offload")
    args = ap.parse_args()

    # ensure the HOST platform can seat a pipe-only mesh (the flag only
    # affects the cpu platform, so it is harmless when a real pod runs
    # the probe; must land before the first device touch). Callers that
    # want the virtual mesh on an accelerator-attached machine also set
    # JAX_PLATFORMS=cpu in the subprocess env (bench.py does).
    if "--xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.pipe}"
        ).strip()

    import jax
    from deepspeed_tpu.runtime.swap_tensor import host_stage
    rows = {}
    plan = {
        "zb": ("zb", False),
        "1f1b": ("1f1b", False),
        "gpipe": ("gpipe", False),
        "zb_offload": ("zb", True),
        "gpipe_offload": ("gpipe", True),
    }
    for name in [r for r in args.rows.split(",") if r]:
        if name not in plan:
            rows[name] = {"error": f"unknown row {name!r}"}
            continue
        sched, off = plan[name]
        try:
            rows[name] = _measure(args.pipe, sched, off, args)
        except Exception as e:  # noqa: BLE001 - isolate rows
            rows[name] = {"error": f"{type(e).__name__}: {e}"[:300]}
    print(json.dumps({
        "pipe": args.pipe,
        "backend": jax.default_backend(),
        "host_kind": host_stage.host_memory_kind(),
        "preset": args.preset, "seq_len": args.seq,
        "global_batch": args.batch,
        "rows": rows,
    }))


if __name__ == "__main__":
    main()
