"""Dump the compiled train-step HLO and print the definitions of named
fusions (to map trace op names back to computation bodies).

Usage: python benchmarks/hlo_dump.py fusion.485 fusion.486 add_add_fusion.2
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("LIBTPU_INIT_ARGS",
                      "--xla_tpu_scoped_vmem_limit_kib=32768")

import numpy as np
import jax

import deepspeed_tpu
from deepspeed_tpu.models import GPT2, PRESETS
from deepspeed_tpu.utils import groups


def main():
    names = [a for a in sys.argv[1:] if not a.startswith("-")]
    preset = os.environ.get("BENCH_PRESET", "350M")
    seq_len = int(os.environ.get("BENCH_SEQ", "1024"))
    micro = int(os.environ.get("BENCH_MICRO_BS", "24"))
    cfg = PRESETS[preset]
    from dataclasses import replace
    cfg = replace(cfg, max_seq_len=seq_len, use_flash_attention=True,
                  flash_block_q=1024, flash_block_k=1024, flash_block_h=1,
                  remat=True,
                  remat_policy=os.environ.get("BENCH_REMAT_POLICY",
                                              "save_flash"),
                  loss_chunk=int(os.environ.get("BENCH_LOSS_CHUNK", "512")),
                  fused_loss=os.environ.get("BENCH_FUSED_LOSS", "1") == "1")
    model = GPT2(cfg)
    groups.reset()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={
            "train_micro_batch_size_per_gpu": micro,
            "gradient_accumulation_steps": 1,
            "steps_per_print": 0,
            "optimizer": {"type": "AdamW",
                          "params": {"lr": 2e-4, "weight_decay": 0.01}},
            "gradient_clipping": 1.0,
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 2},
        })
    bsz = engine.config.train_batch_size
    rng = np.random.RandomState(0)
    batch = {"input_ids": rng.randint(0, cfg.vocab_size, (bsz, seq_len))
             .astype(np.int32)}
    batch = jax.tree.map(engine._add_gas_dim, batch)
    batch = engine._shard_batch(batch, with_gas_dim=True)
    with jax.set_mesh(engine.mesh):
        compiled = engine._train_step_jit.lower(
            engine.state, batch, engine._current_lr()).compile()
    txt = compiled.as_text()
    out = os.environ.get("HLO_OUT", "/tmp/train_step.hlo")
    with open(out, "w") as f:
        f.write(txt)
    print(f"HLO written to {out} ({len(txt)} bytes)")
    if names:
        import re
        for name in names:
            # print the fusion computation the instruction calls
            pat = re.compile(rf'^\s*%?{re.escape(name)} = .*$', re.M)
            for m in pat.finditer(txt):
                print("==== instr:", m.group(0)[:400])


if __name__ == "__main__":
    main()
