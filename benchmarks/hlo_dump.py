"""Dump the compiled train-step HLO and print the definitions of named
fusions (to map trace op names back to computation bodies).

Usage: python benchmarks/hlo_dump.py fusion.485 fusion.486 add_add_fusion.2
Honors the same BENCH_* env knobs as bench.py (benchmarks/bench_engine.py).
"""

import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_engine import build_bench_engine  # noqa: E402


def main():
    names = [a for a in sys.argv[1:] if not a.startswith("-")]
    import jax
    engine, batch = build_bench_engine()
    batch = jax.tree.map(engine._add_gas_dim, batch)
    batch = engine._shard_batch(batch, with_gas_dim=True)
    with jax.set_mesh(engine.mesh):
        compiled = engine._train_step_jit.lower(
            engine.state, batch, engine._current_lr()).compile()
    txt = compiled.as_text()
    out = os.environ.get("HLO_OUT", "/tmp/train_step.hlo")
    with open(out, "w") as f:
        f.write(txt)
    print(f"HLO written to {out} ({len(txt)} bytes)")
    for name in names:
        pat = re.compile(rf'^\s*%?{re.escape(name)} = .*$', re.M)
        for m in pat.finditer(txt):
            print("==== instr:", m.group(0)[:400])


if __name__ == "__main__":
    main()
