"""Aggregate device-op self times from a jax.profiler Chrome trace.

Usage: python benchmarks/trace_summary.py /tmp/dstpu_trace [n_steps]
       python benchmarks/trace_summary.py /tmp/dstpu_trace --steps 3 --json

Thin CLI over ``deepspeed_tpu.profiling.step_trace`` (the parsing that
used to live here, promoted to a library): prints per-op self time
(ms/step) sorted descending plus coarse-family and planner-term rollups,
or the full versioned ``StepDecomposition`` JSON with ``--json``.
For modeled-vs-measured drift against the planner, see
``python -m deepspeed_tpu.profiling.reconcile``.
"""

import argparse
import collections
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepspeed_tpu.profiling import step_trace  # noqa: E402


def build_parser():
    p = argparse.ArgumentParser(
        description="device-op self-time summary for a jax.profiler "
                    "trace")
    p.add_argument("root", nargs="?", default="/tmp/dstpu_trace",
                   help="trace dir (searched recursively) or file")
    # positional steps kept for the historical calling convention
    p.add_argument("steps_pos", nargs="?", type=int, default=None,
                   help=argparse.SUPPRESS)
    p.add_argument("--steps", type=int, default=3,
                   help="steps the capture covered (default 3)")
    p.add_argument("--top", type=int, default=45,
                   help="op rows to print (default 45)")
    p.add_argument("--json", action="store_true",
                   help="emit the StepDecomposition JSON instead of "
                        "the table")
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)
    steps = args.steps_pos if args.steps_pos is not None else args.steps
    path = step_trace.find_trace_file(args.root)
    if path is None:
        raise SystemExit(f"no trace under {args.root}")
    d = step_trace.decompose(step_trace.load_trace_events(path),
                             steps=max(1, steps), trace_path=path)
    if d is None:
        raise SystemExit(f"trace {path} carries no recognizable "
                         f"device/op track")
    if args.json:
        sys.stdout.write(d.to_json())
        return 0

    print(f"device tracks: {d.device_tracks}"
          + (" (CPU-client fallback)" if d.cpu_fallback else ""))
    print(f"total device time: {d.total_device_ms * d.steps:.1f} ms "
          f"over {d.steps} steps = {d.total_device_ms:.1f} ms/step\n")
    print(f"{'ms/step':>9}  {'count':>6}  op")
    for row in d.per_op[:args.top]:
        print(f"{row['ms']:9.2f}  {row['count'] // d.steps:6d}  "
              f"{row['op'][:100]}")

    fams = collections.Counter()
    for row in d.per_op:
        fams[row["family"]] += row["ms"]
    print("\nfamilies (ms/step):")
    for fam, dur in fams.most_common():
        print(f"{dur:9.2f}  {fam}")

    print("\nplanner terms (exposed ms/step):")
    for term in step_trace.DECOMP_TERMS:
        v = d.terms.get(term, 0.0)
        if v > 0:
            print(f"{v:9.2f}  {term}")
    for key, v in sorted(d.unmodeled.items()):
        if v > 0:
            print(f"{v:9.2f}  {key} (unmodeled)")
    if d.collective_total_ms > 0:
        print(f"\ncollectives: {d.collective_total_ms:.2f} ms/step "
              f"({d.collective_exposed_ms:.2f} exposed, "
              f"{d.collective_hidden_ms:.2f} hidden)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
