"""Aggregate device-op self times from a jax.profiler Chrome trace.

Usage: python benchmarks/trace_summary.py /tmp/dstpu_trace [n_steps]
Prints per-op-name total duration (ms) sorted descending, grouped by a
coarse family (matmul/fusion/pallas/...), divided by n_steps.
"""

import collections
import glob
import gzip
import json
import re
import sys


def main():
    root = sys.argv[1] if len(sys.argv) > 1 else "/tmp/dstpu_trace"
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    paths = glob.glob(f"{root}/**/*.trace.json.gz", recursive=True)
    if not paths:
        raise SystemExit(f"no trace under {root}")
    with gzip.open(sorted(paths)[-1], "rt") as f:
        trace = json.load(f)
    events = trace["traceEvents"]

    # find device-side track pids (TensorCore / device compute threads)
    pid_names = {}
    tid_names = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pid_names[e["pid"]] = e["args"].get("name", "")
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            tid_names[(e["pid"], e["tid"])] = e["args"].get("name", "")
    dev_pids = {p for p, n in pid_names.items()
                if "TPU" in n or "/device" in n.lower() or "Core" in n}
    # only the "XLA Ops" thread carries leaf device ops; Steps/Modules
    # tracks are whole-step envelopes that would double count
    op_tids = {k for k, n in tid_names.items()
               if k[0] in dev_pids and n == "XLA Ops"}

    # self time: duration minus nested children on the same (pid, tid)
    by_tid = collections.defaultdict(list)
    for e in events:
        if e.get("ph") != "X" or (e["pid"], e.get("tid")) not in op_tids:
            continue
        by_tid[(e["pid"], e.get("tid"))].append(e)

    per_op = collections.Counter()
    per_op_n = collections.Counter()
    total = 0.0
    for evs in by_tid.values():
        evs.sort(key=lambda e: (e["ts"], -e.get("dur", 0)))
        stack = []  # (end_ts, child_time_accum index into selfs)
        selfs = []
        for e in evs:
            ts, dur = e["ts"], e.get("dur", 0)
            while stack and stack[-1][0] <= ts:
                stack.pop()
            if stack:
                selfs[stack[-1][1]][1] -= dur
            selfs.append([e, dur])
            stack.append((ts + dur, len(selfs) - 1))
        for e, sdur in selfs:
            name = e.get("name", "?")
            dur = max(sdur, 0) / 1000.0  # us -> ms
            per_op[name] += dur
            per_op_n[name] += 1
            total += dur

    print(f"device tracks: {[pid_names[p] for p in dev_pids]}")
    print(f"total device time: {total:.1f} ms over {steps} steps "
          f"= {total / steps:.1f} ms/step\n")
    print(f"{'ms/step':>9}  {'count':>6}  op")
    for name, dur in per_op.most_common(45):
        print(f"{dur / steps:9.2f}  {per_op_n[name] // steps:6d}  "
              f"{name[:100]}")

    # coarse families
    fams = collections.Counter()
    for name, dur in per_op.items():
        n = name.lower()
        if "custom-call" in n or "pallas" in n or "flash" in n:
            fam = "pallas/custom-call"
        elif re.search(r"convolution|dot|einsum", n):
            fam = "matmul"
        elif "fusion" in n:
            fam = "fusion(elementwise/other)"
        elif "copy" in n or "transpose" in n or "bitcast" in n:
            fam = "copy/layout"
        elif "scatter" in n or "gather" in n or "dynamic" in n:
            fam = "gather/scatter/DUS"
        else:
            fam = "other"
        fams[fam] += dur
    print("\nfamilies (ms/step):")
    for fam, dur in fams.most_common():
        print(f"{dur / steps:9.2f}  {fam}")


if __name__ == "__main__":
    main()
