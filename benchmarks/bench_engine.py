"""Shared engine setup for the bench-adjacent tools (profile_step,
hlo_dump): ONE place reads the BENCH_* env knobs and builds the exact
engine/batch `bench.py` measures, so the tools can never drift from the
measured config."""

import os

os.environ.setdefault("LIBTPU_INIT_ARGS",
                      "--xla_tpu_scoped_vmem_limit_kib=32768")

import numpy as np  # noqa: E402


def build_bench_config():
    """The headline bench model config from the BENCH_* env knobs —
    the single source bench.py and the tools share (every knob, incl.
    the backward flash blocks and LN/unroll experiments)."""
    from deepspeed_tpu.models import PRESETS
    from dataclasses import replace

    preset = os.environ.get("BENCH_PRESET", "350M")
    seq_len = int(os.environ.get("BENCH_SEQ", "1024"))
    return replace(
        PRESETS[preset], max_seq_len=seq_len,
        use_flash_attention=os.environ.get("BENCH_FLASH", "1") == "1",
        flash_block_q=int(os.environ.get("BENCH_FLASH_BQ", "1024")),
        flash_block_k=int(os.environ.get("BENCH_FLASH_BK", "1024")),
        flash_block_h=int(os.environ.get("BENCH_FLASH_BH", "1")),
        flash_block_q_bwd=int(os.environ.get("BENCH_FLASH_BQ_BWD", "0")),
        flash_block_k_bwd=int(os.environ.get("BENCH_FLASH_BK_BWD", "0")),
        remat=os.environ.get("BENCH_REMAT", "1") == "1",
        remat_policy=os.environ.get("BENCH_REMAT_POLICY", "save_flash"),
        scan_unroll=int(os.environ.get("BENCH_SCAN_UNROLL", "1")),
        fused_layernorm={"0": False, "1": True, "bwd": "bwd",
                         "auto": "auto"}.get(
            os.environ.get("BENCH_FUSED_LN", "0"), False),
        loss_chunk=int(os.environ.get("BENCH_LOSS_CHUNK", "512")),
        fused_loss=os.environ.get("BENCH_FUSED_LOSS", "1") == "1",
        fused_loss_kernel=os.environ.get("BENCH_FUSED_LOSS_KERNEL",
                                         "1") == "1")


def build_bench_engine():
    """Returns (engine, batch) for the headline bench config, honoring
    the same BENCH_* env knobs (incl. BENCH_ZERO_STAGE/BENCH_OFFLOAD)
    as bench.py."""
    import jax  # noqa: F401  (device init after LIBTPU_INIT_ARGS)
    import deepspeed_tpu
    from deepspeed_tpu.models import GPT2
    from deepspeed_tpu.utils import groups

    cfg = build_bench_config()
    seq_len = cfg.max_seq_len
    micro = int(os.environ.get("BENCH_MICRO_BS", "24"))
    stage = int(os.environ.get("BENCH_ZERO_STAGE", "2"))
    offload = os.environ.get("BENCH_OFFLOAD", "")
    if offload not in ("", "cpu", "nvme"):
        raise SystemExit(f"BENCH_OFFLOAD must be ''|cpu|nvme, "
                         f"got {offload!r}")
    model = GPT2(cfg)
    groups.reset()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={
            "train_micro_batch_size_per_gpu": micro,
            "gradient_accumulation_steps": 1,
            "steps_per_print": 0,
            "optimizer": {"type": "AdamW",
                          "params": {"lr": 2e-4, "weight_decay": 0.01}},
            "gradient_clipping": 1.0,
            "bf16": {"enabled": True},
            "zero_optimization": (
                {"stage": stage,
                 "offload_optimizer": (
                     {"device": "nvme",
                      "nvme_path": os.environ.get("BENCH_NVME_PATH",
                                                  "/tmp/dstpu_nvme")}
                     if offload == "nvme" else {"device": "cpu"})}
                if offload else {"stage": stage}),
        })
    bsz = engine.config.train_batch_size
    rng = np.random.RandomState(0)
    batch = {"input_ids": rng.randint(0, cfg.vocab_size, (bsz, seq_len))
             .astype(np.int32)}
    return engine, batch
