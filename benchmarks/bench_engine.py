"""Shared engine setup for the bench-adjacent tools (profile_step,
hlo_dump): ONE place reads the BENCH_* env knobs and builds the exact
engine/batch `bench.py` measures, so the tools can never drift from the
measured config."""

import os

os.environ.setdefault("LIBTPU_INIT_ARGS",
                      "--xla_tpu_scoped_vmem_limit_kib=32768")

# BENCH_COMM_OVERLAP=1: arm the comm-overlap XLA flags (latency-hiding
# scheduler + async collectives) via the import-time env hook BEFORE the
# backend initializes. Only effective for the FIRST engine of a process
# — in-process variant re-timings change the program-level annotations
# but inherit the headline's flags (full-flag A/B runs per-variant
# subprocesses, __graft_entry__.measured_multichip).
if os.environ.get("BENCH_COMM_OVERLAP") == "1":
    os.environ.setdefault("DSTPU_COMM_OVERLAP", "1")

import numpy as np  # noqa: E402


def build_bench_config():
    """The headline bench model config from the BENCH_* env knobs —
    the single source bench.py and the tools share (every knob, incl.
    the backward flash blocks and LN/unroll experiments)."""
    from deepspeed_tpu.models import PRESETS
    from dataclasses import replace

    preset = os.environ.get("BENCH_PRESET", "350M")
    seq_len = int(os.environ.get("BENCH_SEQ", "1024"))

    # BENCH_AUTOTUNE=1: every tunable kernel knob goes to "auto" so the
    # measured-dispatch winner cache picks variants (the engine's
    # autotune block below sets the mode); any explicitly-set BENCH_*
    # knob still wins over "auto". BENCH_AUTOTUNE=0 pins the r05
    # defaults AND autotune mode off (the drift sentinel).
    tune = os.environ.get("BENCH_AUTOTUNE", "") == "1"

    def knob(env, default, parse=int):
        v = os.environ.get(env)
        if v is None:
            return "auto" if tune else parse(default)
        return parse(v)

    cfg = replace(
        PRESETS[preset], max_seq_len=seq_len,
        use_flash_attention=os.environ.get("BENCH_FLASH", "1") == "1",
        flash_block_q=knob("BENCH_FLASH_BQ", "1024"),
        flash_block_k=knob("BENCH_FLASH_BK", "1024"),
        flash_block_h=knob("BENCH_FLASH_BH", "1"),
        flash_block_q_bwd=knob("BENCH_FLASH_BQ_BWD", "0"),
        flash_block_k_bwd=knob("BENCH_FLASH_BK_BWD", "0"),
        remat=os.environ.get("BENCH_REMAT", "1") == "1",
        remat_policy=os.environ.get("BENCH_REMAT_POLICY", "save_flash"),
        scan_unroll=int(os.environ.get("BENCH_SCAN_UNROLL", "1")),
        fused_layernorm={"0": False, "1": True, "bwd": "bwd",
                         "auto": "auto"}.get(
            knob("BENCH_FUSED_LN", "0", parse=str), False),
        loss_chunk=int(os.environ.get("BENCH_LOSS_CHUNK", "512")),
        fused_loss=os.environ.get("BENCH_FUSED_LOSS", "1") == "1",
        fused_loss_kernel=os.environ.get("BENCH_FUSED_LOSS_KERNEL",
                                         "1") == "1",
        # layout-owning Pallas MLP projection matmul (ops/pallas/
        # mlp_matmul.py): 0 (XLA, default) | down | both | auto
        mlp_kernel={"0": False, "auto": "auto", "down": "down",
                    "both": "both"}.get(
            knob("BENCH_MLP_KERNEL", "0", parse=str), False),
        mlp_kernel_fuse_dw=os.environ.get("BENCH_MLP_FUSE_DW", "1") == "1",
        # query-major fused flash backward (dkv VMEM-resident retune)
        flash_bwd_qmajor=(
            "auto" if tune and "BENCH_FLASH_BWD_QMAJOR" not in os.environ
            else os.environ.get("BENCH_FLASH_BWD_QMAJOR", "0") == "1"),
        # long-context backend: BENCH_ATTN_BACKEND=ring routes attention
        # through sequence/ring.py (zigzag context parallelism) whenever
        # the engine runs seq-sharded (BENCH_SP below); 'dense' default
        attention_backend=os.environ.get("BENCH_ATTN_BACKEND", "dense"))
    # BENCH_MODEL=moe: the dropless-MoE training point — GPT2MoE over
    # the same preset dims with the ragged (grouped-GEMM) backend;
    # BENCH_MOE_KERNEL picks the expert-product engine (1 = the Pallas
    # grouped kernel, 0 = lax.ragged_dot, unset/auto = winner cache) —
    # the moe_kernel_on/off A/B lever
    if os.environ.get("BENCH_MODEL", "") == "moe":
        import dataclasses
        from deepspeed_tpu.models import GPT2MoEConfig
        cfg = GPT2MoEConfig(
            **dataclasses.asdict(cfg),
            num_experts=int(os.environ.get("BENCH_MOE_EXPERTS", "4")),
            moe_top_k=int(os.environ.get("BENCH_MOE_TOPK", "2")),
            moe_backend="ragged",
            moe_grouped_kernel={"1": True, "0": False}.get(
                os.environ.get("BENCH_MOE_KERNEL", ""), "auto"))
    return cfg


def build_bench_engine():
    """Returns (engine, batch) for the headline bench config, honoring
    the same BENCH_* env knobs (incl. BENCH_ZERO_STAGE/BENCH_OFFLOAD)
    as bench.py."""
    import jax  # noqa: F401  (device init after LIBTPU_INIT_ARGS)
    import deepspeed_tpu
    from deepspeed_tpu.models import GPT2, GPT2MoE, GPT2MoEConfig
    from deepspeed_tpu.utils import groups

    cfg = build_bench_config()
    seq_len = cfg.max_seq_len
    preset = os.environ.get("BENCH_PRESET", "350M")
    # 1.3B on one 16 GB chip needs the memory knobs: micro 8, bf16 Adam
    # moments, bf16 grad accumulation (the update still computes fp32)
    big = preset == "1.3B"
    micro = int(os.environ.get("BENCH_MICRO_BS", "8" if big else "24"))
    stage = int(os.environ.get("BENCH_ZERO_STAGE", "2"))
    offload = os.environ.get("BENCH_OFFLOAD", "")
    moments = os.environ.get("BENCH_MOMENTS_DTYPE",
                             "bfloat16" if big else "")
    gdtype = os.environ.get("BENCH_GRAD_DTYPE", "bf16" if big else "")
    if offload not in ("", "cpu", "nvme"):
        raise SystemExit(f"BENCH_OFFLOAD must be ''|cpu|nvme, "
                         f"got {offload!r}")
    model = (GPT2MoE(cfg) if isinstance(cfg, GPT2MoEConfig)
             else GPT2(cfg))
    groups.reset()
    # BENCH_SP: sequence-parallel (ring) axis size — 'auto' = all visible
    # devices when the ring backend is selected (one chip -> sp=1, where
    # the ring path degrades to the flash kernel: the ring_on/off A/B is
    # then a long-seq baseline pair; on a pod it measures the real ring)
    topo = None
    sp = os.environ.get("BENCH_SP", "")
    if sp in ("", "auto"):
        sp_n = (len(jax.devices())
                if cfg.attention_backend == "ring" else 1)
    else:
        sp_n = int(sp)
    if sp_n > 1:
        from deepspeed_tpu.utils.groups import TopologyConfig
        topo = groups.initialize(TopologyConfig(seq_parallel_size=sp_n))
    opt_params = {"lr": 2e-4, "weight_decay": 0.01}
    if moments:
        opt_params["moments_dtype"] = moments
    # comm_overlap block (runtime/zero/overlap.py): ''/auto = engine
    # default (on iff dp>1), 1/0 force. BENCH_COMM_BUCKET_MB tunes the
    # layer-granular reduce gate in isolation.
    ov = os.environ.get("BENCH_COMM_OVERLAP", "")
    overlap_cfg = {}
    if ov in ("0", "1"):
        overlap_cfg["enabled"] = ov == "1"
    if os.environ.get("BENCH_COMM_BUCKET_MB"):
        overlap_cfg["bucket_mb"] = int(os.environ["BENCH_COMM_BUCKET_MB"])
    if os.environ.get("BENCH_COMM_PREFETCH"):
        overlap_cfg["prefetch"] = os.environ["BENCH_COMM_PREFETCH"] == "1"
    # measured kernel dispatch (autotuning/kernel_dispatch.py):
    # BENCH_AUTOTUNE=1 searches cold keys at first trace (inside warmup,
    # so search compiles never land in the timed section) and persists
    # winners; =0 pins dispatch off (the r05-default drift sentinel);
    # unset inherits the env default (cache_only)
    at = os.environ.get("BENCH_AUTOTUNE", "")
    autotune_cfg = {}
    if at == "1":
        autotune_cfg["mode"] = os.environ.get("BENCH_AUTOTUNE_MODE",
                                              "on_first_use")
    elif at == "0":
        autotune_cfg["mode"] = "off"
    # BENCH_INT8_MATMUL=1/0: the training-side W8A8 compute lever
    # (quantize.int8_matmul — ops/pallas/quantization.int8_matmul in
    # gpt2._mlp; 'auto' defers to the mlp_int8 winner cache); unset
    # omits the quantize block entirely (byte-identical programs)
    quantize_cfg = {}
    i8 = os.environ.get("BENCH_INT8_MATMUL", "")
    if i8 in ("0", "1"):
        quantize_cfg["int8_matmul"] = i8 == "1"
    elif i8 == "auto":
        quantize_cfg["int8_matmul"] = "auto"
    # BENCH_TELEMETRY=1: arm the telemetry block (monitor/telemetry.py)
    # so bench.py can read MFU/goodput/step percentiles straight off
    # engine.telemetry_report() — no monitor backend needed
    telemetry_cfg = {}
    if os.environ.get("BENCH_TELEMETRY", "") == "1":
        telemetry_cfg = {
            "enabled": True,
            "interval_steps": int(os.environ.get(
                "BENCH_TELEMETRY_INTERVAL", "5"))}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        **({"topology": topo} if topo is not None else {}),
        config={
            "train_micro_batch_size_per_gpu": micro,
            "gradient_accumulation_steps": 1,
            "steps_per_print": 0,
            "optimizer": {"type": "AdamW", "params": opt_params},
            "gradient_clipping": 1.0,
            "bf16": {"enabled": True},
            **({"data_types": {"grad_accum_dtype": gdtype}}
               if gdtype else {}),
            "zero_optimization": (
                {"stage": stage,
                 "offload_optimizer": (
                     {"device": "nvme",
                      "nvme_path": os.environ.get("BENCH_NVME_PATH",
                                                  "/tmp/dstpu_nvme")}
                     if offload == "nvme" else {"device": "cpu"})}
                if offload else {"stage": stage}),
            **({"comm_overlap": overlap_cfg} if overlap_cfg else {}),
            **({"quantize": quantize_cfg} if quantize_cfg else {}),
            **({"autotune": autotune_cfg} if autotune_cfg else {}),
            **({"telemetry": telemetry_cfg} if telemetry_cfg else {}),
        })
    bsz = engine.config.train_batch_size
    rng = np.random.RandomState(0)
    batch = {"input_ids": rng.randint(0, cfg.vocab_size, (bsz, seq_len))
             .astype(np.int32)}
    return engine, batch
