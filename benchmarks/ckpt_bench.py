"""Checkpoint-stall benchmark — the fork's centerpiece metric.

The reference fork (VELOC/DataStates) exists to shrink the training stall
a checkpoint causes; its number is the wait-time logged by
``veloc_checkpoint_engine.py:158``. This benchmark measures, per engine:

  * submit_ms  — how long ``save_checkpoint`` blocks the training loop
  * durable_ms — time until the bytes are on disk (``wait()`` returns)
  * overlap    — training steps completed while the write ran

    python benchmarks/ckpt_bench.py [--preset 125M] [--engines sync async native]

NOTE: submit time includes the synchronous device->host gather, so on
remote-tunneled dev devices (axon) the numbers are dominated by transfer
latency, not the writer engines; compare engines on local-attached chips.
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models import GPT2, PRESETS
from deepspeed_tpu.utils import groups


def bench_engine(engine_type, preset, steps_during=4, seq=256, micro=2):
    groups.reset()
    tmp = tempfile.mkdtemp(prefix=f"ckpt_bench_{engine_type}_")
    try:
        from dataclasses import replace
        cfg = replace(PRESETS[preset], max_seq_len=seq)
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=GPT2(cfg),
            config={"train_micro_batch_size_per_gpu": micro,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
                    "bf16": {"enabled": True}, "steps_per_print": 0,
                    "zero_optimization": {"stage": 2},
                    "checkpoint_engine": {"type": engine_type,
                                          "writer_threads": 4}})
        batch = {"input_ids": np.random.RandomState(0).randint(
            0, cfg.vocab_size,
            (engine.config.train_batch_size, seq)).astype(np.int32)}
        engine.train_batch(batch)  # compile + warm state

        # measurement 1: submit + time-to-durable, nothing overlapped
        t0 = time.perf_counter()
        engine.save_checkpoint(tmp, tag="m1")
        submit = time.perf_counter() - t0
        engine.checkpoint_engine.wait()
        durable = time.perf_counter() - t0

        # measurement 2: total wall time when training overlaps the write
        # vs the sum of its parts (overlap benefit of async engines)
        t1 = time.perf_counter()
        engine.save_checkpoint(tmp, tag="m2")
        for _ in range(steps_during):
            engine.train_batch(batch)
        engine.checkpoint_engine.wait()
        overlapped_total = time.perf_counter() - t1
        engine.save_checkpoint_terminate()
        return {"engine": engine_type,
                "submit_ms": round(submit * 1e3, 1),
                "durable_ms": round(durable * 1e3, 1),
                "overlap_total_ms": round(overlapped_total * 1e3, 1),
                "steps_overlapped": steps_during}
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="125M")
    ap.add_argument("--engines", nargs="+",
                    default=["sync", "async", "native"])
    args = ap.parse_args()
    for e in args.engines:
        print(json.dumps(bench_engine(e, args.preset)))


if __name__ == "__main__":
    main()
