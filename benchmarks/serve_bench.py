"""Serving benchmark: decode throughput + per-token latency on real TPU.

The reference's FastGen identity is measured serving throughput
(BASELINE.md rows 3-5: effective throughput under SLA). This bench drives
the v2 continuous-batching engine end to end — prefill a batch of
prompts, then timed decode steps over the paged KV cache (the Pallas
paged-attention kernel) — and prints one JSON line per configuration:

    {"model": ..., "batch": N, "prompt_len": P, "decode_tokens_per_sec":
     ..., "ms_per_token": ...}

Run on the chip:  python benchmarks/serve_bench.py
Env: SERVE_MODELS=gpt2-350M,llama-1b  SERVE_BATCHES=1,8
     SERVE_PROMPT=1024  SERVE_DECODE=128
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

from deepspeed_tpu.inference.v2.engine_v2 import (  # noqa: E402
    InferenceEngineV2, RaggedInferenceEngineConfig)
from deepspeed_tpu.models import GPT2, PRESETS  # noqa: E402
from deepspeed_tpu.models.llama import Llama, LlamaConfig  # noqa: E402
from deepspeed_tpu.utils import groups  # noqa: E402


def build_model(name):
    if name == "gpt2-350M":
        from dataclasses import replace
        return GPT2(replace(PRESETS["350M"], max_seq_len=2048))
    if name == "llama-1b":
        return Llama(LlamaConfig(n_layer=16, n_head=16, n_kv_heads=8,
                                 d_model=2048, d_ff=5632, max_seq_len=2048,
                                 vocab_size=32000))
    if name == "mixtral-tiny":
        # MoE serving point: small enough to serve on one chip while
        # exercising the grouped-GEMM expert path end to end
        from deepspeed_tpu.models.mixtral import Mixtral, MixtralConfig
        return Mixtral(MixtralConfig(
            n_layer=8, n_head=16, n_kv_heads=8, d_model=1024, d_ff=3584,
            max_seq_len=2048, vocab_size=32000, num_experts=8,
            moe_top_k=2))
    raise ValueError(name)


def bench_one(name, batch, prompt_len, decode_tokens, block_size=128):
    groups.reset()
    model = build_model(name)
    engine = InferenceEngineV2(
        model,
        RaggedInferenceEngineConfig(max_batch_size=batch,
                                    kv_block_size=block_size,
                                    prompt_bucket=prompt_len))
    rng = np.random.RandomState(0)
    V = model.config.vocab_size

    def run(n_decode):
        for _ in range(batch):
            engine.put(rng.randint(0, V, (prompt_len,)),
                       max_new_tokens=n_decode, eos_token_id=-1)
        # first step admits + prefills; subsequent steps decode
        while engine.has_work:
            engine.step()
        for uid in list(engine._results):
            engine.get(uid)

    run(4)   # warm both programs (prefill bucket + decode)

    # timed: prefill separately from decode so decode rate is clean
    t0 = time.perf_counter()
    for _ in range(batch):
        engine.put(rng.randint(0, V, (prompt_len,)),
                   max_new_tokens=decode_tokens, eos_token_id=-1)
    engine.step()             # admission + prefills + first decode
    t_prefill = time.perf_counter() - t0

    steps = 0
    produced = 0          # tokens emitted INSIDE the timed window only —
    t0 = time.perf_counter()   # the first (untimed) step already decoded
    while engine.has_work:     # decode_steps_per_dispatch tokens per seq
        produced += len(engine.step())
        steps += 1
    # force completion
    for uid in list(engine._results):
        np.asarray(engine.get(uid))
    t_decode = time.perf_counter() - t0

    out = {
        "model": name,
        "batch": batch,
        "prompt_len": prompt_len,
        "decode_tokens": decode_tokens,
        # None when every token fit in the first (untimed) dispatch —
        # raise SERVE_DECODE above decode_steps_per_dispatch to measure
        "decode_tokens_per_sec": (round(produced / t_decode, 1)
                                  if produced else None),
        # a sequence's own next-token latency: decode wall / its tokens
        "ms_per_token": (round(1e3 * t_decode / (produced / batch), 3)
                         if produced else None),
        "dispatches": steps,
        "prefill_s": round(t_prefill, 3),
        "devices": len(jax.devices()),
    }
    print(json.dumps(out))
    return out


def bench_splitfuse(name, prompt_len, chunk, decode_tokens,
                    block_size=128):
    """Dynamic SplitFuse point: decode latency of a RUNNING stream while
    a long prompt chunk-prefills through the fused program — the FastGen
    no-head-of-line-blocking property (blogs/deepspeed-fastgen §3B).
    Reports decode tokens/sec of the running stream during prefill
    dispatches vs during pure-decode dispatches."""
    groups.reset()
    model = build_model(name)
    engine = InferenceEngineV2(
        model,
        RaggedInferenceEngineConfig(max_batch_size=2,
                                    kv_block_size=block_size,
                                    prompt_bucket=chunk,
                                    splitfuse_tokens=chunk))
    rng = np.random.RandomState(0)
    V = model.config.vocab_size
    # stream A: short prompt, long decode
    a = engine.put(rng.randint(0, V, (64,)), max_new_tokens=512,
                   eos_token_id=-1)
    for _ in range(3):
        engine.step()                    # A prefilled + decoding (warm)
    # measure pure-decode rate for A
    t0 = time.perf_counter()
    pure = sum(len(engine.step()) for _ in range(8))
    t_pure = time.perf_counter() - t0
    # admit the long prompt; measure A's decode rate DURING its prefill
    b = engine.put(rng.randint(0, V, (prompt_len,)),
                   max_new_tokens=decode_tokens, eos_token_id=-1)
    during = 0
    chunk_steps = 0
    t0 = time.perf_counter()
    while (any(r.uid == b for r in engine._pending)
           or b in engine._prefill_q):
        out = engine.step()
        chunk_steps += 1
        during += sum(1 for uid, _ in out if uid == a)
    t_during = time.perf_counter() - t0
    out = {
        "model": name, "mode": "splitfuse",
        "chunk_tokens": chunk, "long_prompt": prompt_len,
        "chunk_dispatches": chunk_steps,
        "stream_decode_tok_s_pure": round(pure / t_pure, 1),
        "stream_decode_tok_s_during_prefill": (
            round(during / t_during, 1) if t_during else None),
        "devices": len(jax.devices()),
    }
    print(json.dumps(out))
    return out


def bench_quant(name="llama2-7b", decode_tokens=32, block_size=128):
    """ZeRO-Inference capacity point: serve a model whose bf16 weights +
    KV cache EXCEED single-chip HBM (llama2-7b bf16 ~13.5 GB weights +
    ~4.6 GB cache > 16 GB v5e) by holding the block weights as int8 +
    per-channel scales (~6.7 GB), dequantized one layer at a time
    (reference README.md:30 ZeRO-Inference)."""
    from deepspeed_tpu.models.llama import LLAMA_PRESETS
    from dataclasses import replace
    groups.reset()
    model = Llama(replace(LLAMA_PRESETS[name], max_seq_len=2048))
    engine = InferenceEngineV2(
        model,
        RaggedInferenceEngineConfig(max_batch_size=1,
                                    kv_block_size=block_size,
                                    prompt_bucket=128,
                                    quantize_weights=True))
    rng = np.random.RandomState(0)
    V = model.config.vocab_size
    uid = engine.put(rng.randint(0, V, (128,)), max_new_tokens=4,
                     eos_token_id=-1)
    while not engine.is_done(uid):
        engine.step()           # warm (compile + first tokens)
    engine.get(uid)
    uid = engine.put(rng.randint(0, V, (128,)),
                     max_new_tokens=decode_tokens, eos_token_id=-1)
    t0 = time.perf_counter()
    while not engine.is_done(uid):
        engine.step()
    dt = time.perf_counter() - t0
    toks = engine.get(uid)
    n_params = model.config.num_params()
    out = {
        "model": name, "mode": "zero-inference-int8",
        "params_b": round(n_params / 1e9, 2),
        "weights_gb_bf16": round(n_params * 2 / 2**30, 1),
        "weights_gb_int8": round(n_params / 2**30, 1),
        "decode_tokens_per_sec": round(len(toks) / dt, 2),
        "note": ("bf16 weights + paged KV exceed the 16 GB chip; int8 "
                 "weight-only serving fits"),
        "devices": len(jax.devices()),
    }
    print(json.dumps(out))
    return out


def main():
    models = os.environ.get("SERVE_MODELS", "gpt2-350M,llama-1b").split(",")
    batches = [int(b) for b in
               os.environ.get("SERVE_BATCHES", "1,8").split(",")]
    prompt = int(os.environ.get("SERVE_PROMPT", "1024"))
    decode = int(os.environ.get("SERVE_DECODE", "128"))
    for m in models:
        for b in batches:
            bench_one(m, b, prompt, decode)
    if os.environ.get("SERVE_SPLITFUSE", "1") == "1":
        for m in models:
            bench_splitfuse(m, prompt_len=prompt,
                            chunk=int(os.environ.get("SERVE_CHUNK",
                                                     "256")),
                            decode_tokens=16)
    if os.environ.get("SERVE_QUANT", ""):
        bench_quant(os.environ["SERVE_QUANT"])


if __name__ == "__main__":
    main()
