"""Serving benchmark: decode throughput + per-token latency on real TPU.

The reference's FastGen identity is measured serving throughput
(BASELINE.md rows 3-5: effective throughput under SLA). This bench drives
the v2 continuous-batching engine end to end — prefill a batch of
prompts, then timed decode steps over the paged KV cache (the Pallas
paged-attention kernels) — and prints one JSON line per configuration.

The headline scenario is ``bench_mixed_traffic``: Poisson arrivals of
mixed long-prefill + decode-heavy requests, p50/p99 **TTFT** (submit to
first token) and **TPOT** (steady-state inter-token) reported
separately per engine variant (paged kernel on/off x SplitFuse on/off)
— the FastGen demonstration that split-fuse holds p99 TPOT flat while
long prompts stream through.

EVERY row also lands in ``SERVE_local.json`` at the repo root — written
even when a run is interrupted mid-sweep (the same lost-artifact lesson
as ``bench.py``'s BENCH_local.json: three rounds of driver artifacts
vanished).

``bench_shared_prefix`` is the prefix-cache scenario: Poisson arrivals
drawing from N prompt templates (per-request suffixes, configurable
share ratio), prefix_cache on vs off — cache-on should collapse TTFT
p50 (template prefills served from cached KV blocks) with p99 TPOT
within noise, and the row carries the engine's own hit-rate/CoW/
eviction counters.

``bench_router_traffic`` (``SERVE_REPLICAS=N``) is the serving-fleet
robustness scenario: mixed-class Poisson traffic through the replica
Router (inference/v2/router.py) as baseline / mid-run replica-kill /
mid-run drain — per-class admitted/shed/expired/replayed counts and
TTFT/TPOT percentiles per row, failover accounting asserted closed.

Run on the chip:  python benchmarks/serve_bench.py
Env: SERVE_MODELS=gpt2-350M,llama-1b  SERVE_BATCHES=1,8
     SERVE_PROMPT=1024  SERVE_DECODE=128  SERVE_MIXED=1
     SERVE_MIXED_MODEL=gpt2-350M  SERVE_EP_MOE=1
     SERVE_PREFIX=1  SERVE_PREFIX_MODEL=gpt2-350M  SERVE_PREFIX_N=24
     SERVE_PREFIX_SHARE=0.75  SERVE_REPLICAS=2  SERVE_ROUTER_N=24
     SERVE_ROUTER_MODEL=gpt2-350M  SERVE_ROUTER_RATE=2.0
     SERVE_WQ=1  SERVE_WQ_MODEL=gpt2-350M   (weight_quant off/int8/int4
     sweep — TPOT p50/p99 + weight HBM delta per variant; 0 disables)
     SERVE_SPEC=1  SERVE_SPEC_MODEL=gpt2-350M  SERVE_SPEC_KS=2,4
     (speculative decoding sweep — off baseline, oracle-draft spec_k
     rows with acceptance-rate + tokens-per-verify-step counters, and
     the adversarial random-token fallback row; 0 disables)
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

from deepspeed_tpu.inference.v2 import Overloaded, Router  # noqa: E402
from deepspeed_tpu.inference.v2.engine_v2 import (  # noqa: E402
    InferenceEngineV2, RaggedInferenceEngineConfig)
from deepspeed_tpu.models import GPT2, PRESETS  # noqa: E402
from deepspeed_tpu.models.llama import Llama, LlamaConfig  # noqa: E402
from deepspeed_tpu.utils import fault_injection, groups  # noqa: E402

# every bench row accumulates here; write_local_report() flushes the
# tree-local artifact (also mid-run on interruption — see main())
RESULTS = []


def _record(row):
    RESULTS.append(row)
    print(json.dumps(row))
    return row


def write_local_report(error=None):
    """Write SERVE_local.json at the repo root with whatever rows exist
    so far. Never raises (an unwritable tree must not mask the bench's
    own output)."""
    report = {
        "metric": "v2 serving suite (throughput + TTFT/TPOT percentiles)",
        "rows": RESULTS,
        "devices": len(jax.devices()),
        "backend": jax.default_backend(),
    }
    if error:
        report["interrupted"] = error
    try:
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "SERVE_local.json")
        with open(path, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
    except OSError as e:
        print(json.dumps({"local_artifact_error": str(e)[:200]}))
    return report


def _pct(arr, p, nd=1):
    """Guarded percentile: None instead of a crash/NaN when no request
    produced the statistic (e.g. every request finished inside its
    first dispatch, leaving no inter-token gaps)."""
    if arr is None or len(arr) == 0:
        return None
    return round(float(np.percentile(np.asarray(arr, np.float64), p)), nd)


def _poisson_drive(engine, prompts, arrivals, decode_tokens):
    """Shared open-loop driver (bench_sla + bench_mixed_traffic):
    submit ``prompts[i]`` once ``arrivals[i]`` seconds have elapsed,
    run the scheduler until drained. Returns (tok_times: uid -> [t0,
    t1, ...] per-token wall timestamps, submit: uid -> arrival_s,
    wall_s)."""
    tok_times, submit = {}, {}
    n = len(prompts)
    start = time.perf_counter()
    i = 0
    while i < n or engine.has_work:
        now = time.perf_counter() - start
        while i < n and arrivals[i] <= now:
            uid = engine.put(prompts[i], max_new_tokens=decode_tokens,
                             eos_token_id=-1)
            submit[uid] = arrivals[i]
            tok_times[uid] = []
            i += 1
        if not engine.has_work:
            time.sleep(min(0.005, max(0.0, arrivals[i] - now)))
            continue
        out = engine.step()
        t = time.perf_counter() - start
        for uid, _tok in out:
            tok_times[uid].append(t)
    return tok_times, submit, time.perf_counter() - start


def build_model(name):
    if name == "tiny":
        # smoke-test point (CPU / CI): exercises every serving program
        # in seconds; not a measurement target
        from deepspeed_tpu.models import GPT2Config
        return GPT2(GPT2Config(n_layer=2, n_head=4, d_model=64,
                               max_seq_len=1024, vocab_size=512,
                               remat=False, dtype="float32"))
    if name == "tiny-wq":
        # weight-quant smoke point: like "tiny" but d_model=128 so the
        # stacked block matmul weights clear quantize_tree's min_size
        # floor (1<<16 elements) — at d_model=64 nothing quantizes and
        # every weight_quant row would be a vacuous ratio-1.0
        from deepspeed_tpu.models import GPT2Config
        return GPT2(GPT2Config(n_layer=2, n_head=4, d_model=128,
                               max_seq_len=1024, vocab_size=512,
                               remat=False, dtype="float32"))
    if name == "gpt2-350M":
        from dataclasses import replace
        return GPT2(replace(PRESETS["350M"], max_seq_len=2048))
    if name == "llama-1b":
        return Llama(LlamaConfig(n_layer=16, n_head=16, n_kv_heads=8,
                                 d_model=2048, d_ff=5632, max_seq_len=2048,
                                 vocab_size=32000))
    if name == "llama2-7b-serve":
        from dataclasses import replace
        from deepspeed_tpu.models.llama import LLAMA_PRESETS
        return Llama(replace(LLAMA_PRESETS["llama2-7b"],
                             max_seq_len=2048))
    if name == "mixtral-tiny":
        # MoE serving point: small enough to serve on one chip while
        # exercising the grouped-GEMM expert path end to end
        from deepspeed_tpu.models.mixtral import Mixtral, MixtralConfig
        return Mixtral(MixtralConfig(
            n_layer=8, n_head=16, n_kv_heads=8, d_model=1024, d_ff=3584,
            max_seq_len=2048, vocab_size=32000, num_experts=8,
            moe_top_k=2))
    raise ValueError(name)


def bench_one(name, batch, prompt_len, decode_tokens, block_size=128):
    groups.reset()
    model = build_model(name)
    engine = InferenceEngineV2(
        model,
        RaggedInferenceEngineConfig(max_batch_size=batch,
                                    kv_block_size=block_size,
                                    prompt_bucket=prompt_len))
    rng = np.random.RandomState(0)
    V = model.config.vocab_size

    def run(n_decode):
        for _ in range(batch):
            engine.put(rng.randint(0, V, (prompt_len,)),
                       max_new_tokens=n_decode, eos_token_id=-1)
        # first step admits + prefills; subsequent steps decode
        while engine.has_work:
            engine.step()
        for uid in list(engine._results):
            engine.get(uid)

    run(4)   # warm both programs (prefill bucket + decode)

    # timed: prefill separately from decode so decode rate is clean
    t0 = time.perf_counter()
    for _ in range(batch):
        engine.put(rng.randint(0, V, (prompt_len,)),
                   max_new_tokens=decode_tokens, eos_token_id=-1)
    engine.step()             # admission + prefills + first decode
    t_prefill = time.perf_counter() - t0

    steps = 0
    produced = 0          # tokens emitted INSIDE the timed window only —
    t0 = time.perf_counter()   # the first (untimed) step already decoded
    while engine.has_work:     # decode_steps_per_dispatch tokens per seq
        produced += len(engine.step())
        steps += 1
    # force completion
    for uid in list(engine._results):
        np.asarray(engine.get(uid))
    t_decode = time.perf_counter() - t0

    out = {
        "model": name,
        "batch": batch,
        "prompt_len": prompt_len,
        "decode_tokens": decode_tokens,
        # None when every token fit in the first (untimed) dispatch —
        # raise SERVE_DECODE above decode_steps_per_dispatch to measure
        "decode_tokens_per_sec": (round(produced / t_decode, 1)
                                  if produced else None),
        # a sequence's own next-token latency: decode wall / its tokens
        "ms_per_token": (round(1e3 * t_decode / (produced / batch), 3)
                         if produced else None),
        "dispatches": steps,
        "prefill_s": round(t_prefill, 3),
        "devices": len(jax.devices()),
    }
    return _record(out)


def bench_splitfuse(name, prompt_len, chunk, decode_tokens,
                    block_size=128):
    """Dynamic SplitFuse point: decode latency of a RUNNING stream while
    a long prompt chunk-prefills through the fused program — the FastGen
    no-head-of-line-blocking property (blogs/deepspeed-fastgen §3B).
    Reports decode tokens/sec of the running stream during prefill
    dispatches vs during pure-decode dispatches."""
    groups.reset()
    model = build_model(name)
    engine = InferenceEngineV2(
        model,
        RaggedInferenceEngineConfig(max_batch_size=2,
                                    kv_block_size=block_size,
                                    prompt_bucket=chunk,
                                    splitfuse_tokens=chunk))
    rng = np.random.RandomState(0)
    V = model.config.vocab_size
    # stream A: short prompt, long decode
    a = engine.put(rng.randint(0, V, (64,)), max_new_tokens=512,
                   eos_token_id=-1)
    for _ in range(3):
        engine.step()                    # A prefilled + decoding (warm)
    # measure pure-decode rate for A
    t0 = time.perf_counter()
    pure = sum(len(engine.step()) for _ in range(8))
    t_pure = time.perf_counter() - t0
    # admit the long prompt; measure A's decode rate DURING its prefill
    b = engine.put(rng.randint(0, V, (prompt_len,)),
                   max_new_tokens=decode_tokens, eos_token_id=-1)
    during = 0
    chunk_steps = 0
    t0 = time.perf_counter()
    while (any(r.uid == b for r in engine._pending)
           or b in engine._prefill_q):
        out = engine.step()
        chunk_steps += 1
        during += sum(1 for uid, _ in out if uid == a)
    t_during = time.perf_counter() - t0
    out = {
        "model": name, "mode": "splitfuse",
        "chunk_tokens": chunk, "long_prompt": prompt_len,
        "chunk_dispatches": chunk_steps,
        "stream_decode_tok_s_pure": round(pure / t_pure, 1),
        "stream_decode_tok_s_during_prefill": (
            round(during / t_during, 1) if t_during else None),
        "devices": len(jax.devices()),
    }
    return _record(out)


def bench_quant(name="llama2-7b", decode_tokens=32, block_size=128):
    """ZeRO-Inference capacity point: serve a model whose bf16 weights +
    KV cache EXCEED single-chip HBM (llama2-7b bf16 ~13.5 GB weights +
    ~4.6 GB cache > 16 GB v5e) by holding the block weights as int8 +
    per-channel scales (~6.7 GB), dequantized one layer at a time
    (reference README.md:30 ZeRO-Inference)."""
    from deepspeed_tpu.models.llama import LLAMA_PRESETS
    from dataclasses import replace
    groups.reset()
    model = Llama(replace(LLAMA_PRESETS[name], max_seq_len=2048))
    engine = InferenceEngineV2(
        model,
        RaggedInferenceEngineConfig(max_batch_size=1,
                                    kv_block_size=block_size,
                                    prompt_bucket=128,
                                    quantize_weights=True))
    rng = np.random.RandomState(0)
    V = model.config.vocab_size
    uid = engine.put(rng.randint(0, V, (128,)), max_new_tokens=4,
                     eos_token_id=-1)
    while not engine.is_done(uid):
        engine.step()           # warm (compile + first tokens)
    engine.get(uid)
    uid = engine.put(rng.randint(0, V, (128,)),
                     max_new_tokens=decode_tokens, eos_token_id=-1)
    t0 = time.perf_counter()
    while not engine.is_done(uid):
        engine.step()
    dt = time.perf_counter() - t0
    toks = engine.get(uid)
    n_params = model.config.num_params()
    out = {
        "model": name, "mode": "zero-inference-int8",
        "params_b": round(n_params / 1e9, 2),
        "weights_gb_bf16": round(n_params * 2 / 2**30, 1),
        "weights_gb_int8": round(n_params / 2**30, 1),
        "decode_tokens_per_sec": round(len(toks) / dt, 2),
        "note": ("bf16 weights + paged KV exceed the 16 GB chip; int8 "
                 "weight-only serving fits"),
        "devices": len(jax.devices()),
    }
    return _record(out)


def _weight_quant_one(name, wq, batch, prompt_len, decode_tokens,
                      chunk, block_size, seed):
    """One fused weight-quant serving run (engine ``weight_quant`` =
    False | 'int8' | 'int4'): closed-loop batch decode with per-token
    wall timestamps -> TPOT p50/p99 across requests, plus the param
    pool's actual HBM footprint (the pool IS quantized — Int8Weight/
    Int4Weight leaves — so the bytes are counted, not projected) and
    the weight bytes a single decoded token streams."""
    groups.reset()
    model = build_model(name)
    engine = InferenceEngineV2(
        model, RaggedInferenceEngineConfig(
            max_batch_size=batch, kv_block_size=block_size,
            prompt_bucket=min(prompt_len, 512), splitfuse_tokens=chunk,
            weight_quant=wq))
    weight_bytes = sum(x.nbytes for x in jax.tree.leaves(engine.params))
    r = np.random.RandomState(seed)
    V = model.config.vocab_size
    w = engine.put(r.randint(0, V, (prompt_len,)), max_new_tokens=8,
                   eos_token_id=-1)
    while not engine.is_done(w):
        engine.step()                  # warm prefill + decode programs
    engine.get(w)

    tok_times = {}
    for _ in range(batch):
        uid = engine.put(r.randint(0, V, (prompt_len,)),
                         max_new_tokens=decode_tokens, eos_token_id=-1)
        tok_times[uid] = []
    t0 = time.perf_counter()
    produced = 0
    while engine.has_work:
        out = engine.step()
        t = time.perf_counter() - t0
        for uid, _tok in out:
            tok_times[uid].append(t)
        produced += len(out)
    wall = time.perf_counter() - t0
    for uid in list(engine._results):
        np.asarray(engine.get(uid))

    tpot = [1e3 * (ts[-1] - ts[0]) / (len(ts) - 1)
            for ts in tok_times.values()
            if len(ts) >= 2 and ts[-1] != ts[0]]
    return {
        "model": name, "mode": "weight-quant",
        "variant": {"weight_quant": wq or "off"},
        "batch": batch, "prompt_len": prompt_len,
        "decode_tokens": decode_tokens, "splitfuse_tokens": chunk,
        "weight_hbm_mb": round(weight_bytes / 2**20, 2),
        # every decode step streams the full weight pool once: the
        # HBM-bandwidth bound per generated token (per sequence)
        "weight_bytes_per_token_mb": round(weight_bytes / 2**20, 2),
        "tpot_ms_p50": _pct(tpot, 50), "tpot_ms_p99": _pct(tpot, 99),
        "decode_tokens_per_sec": (round(produced / wall, 1)
                                  if produced else None),
        "devices": len(jax.devices()),
    }


def bench_weight_quant(name="tiny", batch=4, prompt_len=128,
                       decode_tokens=32, chunk=0, block_size=64, seed=0):
    """Fused weight-only low-precision serving sweep (SERVE_WQ): the
    same closed-loop decode at weight_quant off / int8 / int4. The
    quantized rows carry their HBM delta vs the off row — the W8A16
    capacity/bandwidth claim is the ~2x (int8) / ~4x (int4) weight
    shrink with TPOT within noise of off on bandwidth-bound shapes.
    A variant that crashes records its error and the sweep continues."""
    rows = []
    off_mb = None
    for wq in (False, "int8", "int4"):
        try:
            row = _weight_quant_one(name, wq, batch, prompt_len,
                                    decode_tokens, chunk, block_size,
                                    seed)
            if wq is False:
                off_mb = row["weight_hbm_mb"]
            elif off_mb:
                row["weight_hbm_delta_mb"] = round(
                    row["weight_hbm_mb"] - off_mb, 2)
                row["weight_hbm_ratio_vs_off"] = round(
                    row["weight_hbm_mb"] / off_mb, 3)
            rows.append(_record(row))
        except Exception as e:  # noqa: BLE001 — keep sweeping
            rows.append(_record({
                "model": name, "mode": "weight-quant",
                "variant": {"weight_quant": wq or "off"},
                "error": f"{type(e).__name__}: {e}"[:300]}))
        write_local_report()           # partial sweep already durable
    return rows


def _build_draft(name):
    """Narrow draft counterpart of a bench model (~1/8 the compute of
    the target: fewer/narrower layers, same vocab)."""
    from dataclasses import replace
    from deepspeed_tpu.models import GPT2Config
    if name in ("tiny", "tiny-wq"):
        return GPT2(GPT2Config(n_layer=1, n_head=2, d_model=32,
                               max_seq_len=1024, vocab_size=512,
                               remat=False, dtype="float32"))
    if name == "gpt2-350M":
        return GPT2(replace(PRESETS["350M"], n_layer=4, n_head=8,
                            d_model=512, max_seq_len=2048))
    if name == "llama-1b":
        return Llama(LlamaConfig(n_layer=4, n_head=8, n_kv_heads=4,
                                 d_model=512, d_ff=1408,
                                 max_seq_len=2048, vocab_size=32000))
    raise ValueError(f"no draft sizing for {name}")


def _spec_one(name, spec_k, workload, batch, prompt_len, decode_tokens,
              chunk, block_size, seed):
    """One speculative serving run: closed-loop batch decode with
    per-token wall timestamps. ``spec_k=0`` = speculation off (the
    baseline row). Workloads: "shared-template" is the synthetic
    high-acceptance traffic (the draft shares the target's weights —
    the oracle-draft bound, every round commits k+1 tokens);
    "random-token" is the adversarial low-acceptance traffic (an
    independently-initialized draft + the acceptance floor pinned at
    1.0, so the per-sequence fallback latch engages after
    SPEC_MIN_ROUNDS and the row measures speculation's worst-case
    overhead over plain decode)."""
    groups.reset()
    model = build_model(name)
    spec_on = spec_k > 0
    kw = {}
    if spec_on:
        if workload == "shared-template":
            draft = build_model(name)      # oracle: same config+seed
        else:
            draft = _build_draft(name)
        kw = dict(draft_model=draft)
    engine = InferenceEngineV2(
        model, RaggedInferenceEngineConfig(
            max_batch_size=batch, kv_block_size=block_size,
            prompt_bucket=min(prompt_len, 512), splitfuse_tokens=chunk,
            spec_draft=spec_on, spec_k=max(1, spec_k)), **kw)
    if spec_on and workload == "shared-template":
        # oracle draft: share the target's weights outright — the
        # draft's argmax always equals the target's, so every round
        # commits k+1 tokens (the tokens-per-verify-step upper bound)
        engine.draft_params = engine.params
    if spec_on and workload == "random-token":
        engine._spec_floor = 1.0           # adversarial: always latch
    r = np.random.RandomState(seed)
    V = model.config.vocab_size
    w = engine.put(r.randint(0, V, (prompt_len,)), max_new_tokens=8,
                   eos_token_id=-1)
    while not engine.is_done(w):
        engine.step()                 # warm every program variant
    engine.get(w)

    tok_times = {}
    for _ in range(batch):
        uid = engine.put(r.randint(0, V, (prompt_len,)),
                         max_new_tokens=decode_tokens, eos_token_id=-1)
        tok_times[uid] = []
    t0 = time.perf_counter()
    produced = 0
    while engine.has_work:
        out = engine.step()
        t = time.perf_counter() - t0
        for uid, _tok in out:
            tok_times[uid].append(t)
        produced += len(out)
    wall = time.perf_counter() - t0
    for uid in list(engine._results):
        np.asarray(engine.get(uid))

    tpot = [1e3 * (ts[-1] - ts[0]) / (len(ts) - 1)
            for ts in tok_times.values()
            if len(ts) >= 2 and ts[-1] != ts[0]]
    tel = engine.telemetry.percentiles()
    row = {
        "model": name, "mode": "speculative",
        "variant": {"spec": "on" if spec_on else "off",
                    "spec_k": spec_k, "workload": workload},
        "batch": batch, "prompt_len": prompt_len,
        "decode_tokens": decode_tokens, "splitfuse_tokens": chunk,
        "tpot_ms_p50": _pct(tpot, 50), "tpot_ms_p99": _pct(tpot, 99),
        "decode_tokens_per_sec": (round(produced / wall, 1)
                                  if produced else None),
        # zero-verify-step guard: the telemetry only carries spec keys
        # once a verify round ran, so off rows (and spec-on rows whose
        # traffic never speculated) report None — never a NaN from a
        # 0/0 percentile window
        "spec_rounds": tel.get("spec_rounds"),
        "acceptance_rate_pct": tel.get("spec_acceptance_pct"),
        "tokens_per_verify_step": tel.get("spec_tokens_per_verify_step"),
        "devices": len(jax.devices()),
    }
    if spec_on and workload == "random-token":
        row["acceptance_floor"] = 1.0
        row["fallback_engaged"] = tel.get("spec_rounds") is not None
    da = engine.state_mgr.draft_allocator
    if da is not None:
        assert da.free_blocks == da.total_blocks, "leaked draft blocks"
    return row


def bench_speculative(name="tiny", batch=4, prompt_len=64,
                      decode_tokens=32, chunk=16, block_size=16,
                      spec_ks=(2, 4), seed=0):
    """Speculative-decoding sweep (SERVE_SPEC): plain decode baseline,
    then draft-on at each ``spec_k`` under the synthetic
    high-acceptance workload (oracle draft — the tokens-per-verify-step
    upper bound, > 1.5 expected at spec_k=4), then the adversarial
    random-token row where the acceptance-floor fallback engages and
    p99 TPOT must stay within noise of the baseline. A variant that
    crashes records its error and the sweep continues."""
    rows = []
    variants = [(0, "shared-template")]
    variants += [(k, "shared-template") for k in spec_ks]
    variants += [(max(spec_ks), "random-token")]
    for spec_k, workload in variants:
        try:
            rows.append(_record(_spec_one(
                name, spec_k, workload, batch, prompt_len,
                decode_tokens, chunk, block_size, seed)))
        except Exception as e:  # noqa: BLE001 — keep sweeping
            rows.append(_record({
                "model": name, "mode": "speculative",
                "variant": {"spec": "on" if spec_k else "off",
                            "spec_k": spec_k, "workload": workload},
                "error": f"{type(e).__name__}: {e}"[:300]}))
        write_local_report()           # partial sweep already durable
    return rows


def bench_kv_offload(name="gpt2-350M", batch=4, prompt_len=512,
                     decode_tokens=64, block_size=64, device_blocks=20,
                     quantize=False, splitfuse=0, max_batch=None):
    """ZeRO-Inference KV host offload (reference README.md:30): the
    batch's total KV footprint exceeds the device block pool; blocks
    page between host RAM and the device (inference/v2/kv_offload.py)
    with next-group H2D prefetched under the current group's compute.
    Reports decode rate resident vs offloaded + swap volumes.

    NOTE on this rig: host<->device crosses the axon tunnel
    (~60 MB/s measured round 3); on a directly attached host (PCIe
    ~10 GB/s+) the same swap traffic is ~200x cheaper. Swap volumes are
    reported so the transfer cost can be projected onto real topology.
    """
    rng = np.random.RandomState(0)

    def run(offload):
        groups.reset()
        model = build_model(name)
        V = model.config.vocab_size
        cfg = dict(max_batch_size=max_batch or batch,
                   kv_block_size=block_size,
                   prompt_bucket=min(prompt_len, 512),
                   splitfuse_tokens=splitfuse,
                   quantize_weights=quantize)
        if offload:
            cfg.update(kv_host_offload=True,
                       device_kv_blocks=device_blocks,
                       num_kv_blocks=1 + batch * -(-(
                           prompt_len + decode_tokens) // block_size))
        engine = InferenceEngineV2(model,
                                   RaggedInferenceEngineConfig(**cfg))
        for _ in range(batch):
            engine.put(rng.randint(0, V, (prompt_len,)),
                       max_new_tokens=decode_tokens, eos_token_id=-1)
        t0 = time.perf_counter()
        engine.step()                       # admit + prefill (+1st decode)
        t_prefill = time.perf_counter() - t0
        produced = 0
        t0 = time.perf_counter()
        while engine.has_work:
            produced += len(engine.step())
        for uid in list(engine._results):
            np.asarray(engine.get(uid))
        t_decode = time.perf_counter() - t0
        stats = {}
        if engine.kv_pool is not None:
            blk_bytes = (np.prod(engine.kv_pool._blk_shape) * 2
                         * engine.kv_pool.n_layer
                         * np.dtype(engine.kv_pool.dtype).itemsize)
            stats = {"swapped_in_blocks": engine.kv_pool.swapped_in,
                     "swapped_out_blocks": engine.kv_pool.swapped_out,
                     "swap_gb": round((engine.kv_pool.swapped_in
                                       + engine.kv_pool.swapped_out)
                                      * blk_bytes / 2**30, 2)}
        return (produced / t_decode if produced else None,
                t_prefill, stats)

    res_rate, res_prefill, _ = (None, None, None) if quantize \
        else run(offload=False)
    off_rate, off_prefill, stats = run(offload=True)
    total_blocks = batch * -(-(prompt_len + decode_tokens) // block_size)
    out = {
        "model": name, "mode": "kv-host-offload",
        "batch": batch, "prompt_len": prompt_len,
        "decode_tokens": decode_tokens,
        "logical_kv_blocks": total_blocks,
        "device_kv_blocks": device_blocks,
        "oversubscription": round(total_blocks / (device_blocks - 1), 2),
        "decode_tok_s_resident": (round(res_rate, 1) if res_rate
                                  else None),
        "decode_tok_s_offload": (round(off_rate, 1) if off_rate
                                 else None),
        "quantize_weights": quantize,
        **stats,
        "transport_note": "swap traffic crosses the axon tunnel "
                          "(~60 MB/s) on this rig; see docstring",
        "devices": len(jax.devices()),
    }
    return _record(out)


def bench_sla(name="gpt2-350M", rates=(1.0, 2.0, 4.0), n_requests=24,
              prompt_len=256, decode_tokens=48, sla_ms=100.0,
              splitfuse=0, block_size=64, seed=0):
    """SLA-grade serving benchmark (reference
    blogs/deepspeed-fastgen/README.md:160-186): Poisson request
    arrivals at each rate; report per-token latency p50/p95, end-to-end
    p50/p95, and goodput — completed queries/s whose mean inter-token
    latency met the SLA. The axon per-dispatch overhead is measured
    with a no-op dispatch and reported alongside so the engine cost can
    be separated from this rig's transport."""
    groups.reset()
    model = build_model(name)
    V = model.config.vocab_size

    # measure the transport's per-dispatch overhead (scalar round trip)
    one = jax.jit(lambda x: x + 1)
    one(np.float32(0)).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(10):
        float(one(np.float32(0)))
    dispatch_ms = (time.perf_counter() - t0) / 10 * 1e3

    results = []
    for rate in rates:
        groups.reset()
        engine = InferenceEngineV2(
            model, RaggedInferenceEngineConfig(
                max_batch_size=8, kv_block_size=block_size,
                prompt_bucket=min(prompt_len, 512),
                splitfuse_tokens=splitfuse))
        r = np.random.RandomState(seed)
        arrivals = np.cumsum(r.exponential(1.0 / rate, n_requests))
        prompts = [r.randint(0, V, (prompt_len,)) for _ in range(n_requests)]
        # warm EVERY program the run will hit: chunk-only, decode, and —
        # critically under SplitFuse — the FUSED chunk+decode program,
        # which only traces when a prompt chunk arrives while another
        # sequence is DECODING (without this the first mid-run overlap
        # pays a full XLA compile inside the timed window). w1 gets a
        # long decode budget so it is guaranteed still running when w2's
        # chunks dispatch.
        w1 = engine.put(prompts[0], max_new_tokens=64, eos_token_id=-1)
        for _ in range(1 + prompt_len // max(1, splitfuse or prompt_len)):
            engine.step()               # w1 fully prefilled + decoding
        w2 = engine.put(prompts[1], max_new_tokens=4, eos_token_id=-1)
        while not (engine.is_done(w1) and engine.is_done(w2)):
            engine.step()
        engine.get(w1), engine.get(w2)

        tok_times, submit, wall = _poisson_drive(
            engine, prompts[:n_requests], arrivals, decode_tokens)

        per_tok = []
        e2e = []
        met = 0
        for uid, ts in tok_times.items():
            if not ts:
                continue
            # inter-token latency: includes queueing for the first token
            gaps = np.diff([submit[uid]] + ts)
            mean_tok_ms = 1e3 * (ts[-1] - submit[uid]) / len(ts)
            per_tok.extend(1e3 * gaps)
            e2e.append(ts[-1] - submit[uid])
            if mean_tok_ms <= sla_ms:
                met += 1
        row = {
            "model": name, "mode": "sla",
            "splitfuse_tokens": splitfuse,
            "arrival_rate_qps": rate,
            "n_requests": n_requests,
            "prompt_len": prompt_len, "decode_tokens": decode_tokens,
            "token_latency_ms_p50": _pct(per_tok, 50, 1),
            "token_latency_ms_p95": _pct(per_tok, 95, 1),
            "e2e_s_p50": _pct(e2e, 50, 2),
            "e2e_s_p95": _pct(e2e, 95, 2),
            "sla_ms_per_token": sla_ms,
            "goodput_qps": round(met / wall, 2),
            "offered_qps": round(n_requests / wall, 2),
            "dispatch_overhead_ms": round(dispatch_ms, 1),
            "devices": len(jax.devices()),
        }
        results.append(_record(row))
    return results


def _mixed_one(name, rate, n_requests, long_prompt, short_prompt,
               long_every, decode_tokens, splitfuse, paged_kernel,
               block_size, max_batch, seed):
    """One mixed-traffic run; returns the TTFT/TPOT percentile row."""
    groups.reset()
    model = build_model(name)
    # the long prompt + its decode budget (and the 64-token warm-up
    # budget) must fit the model's context, whatever model/env combo
    # was asked for — clamp instead of erroring every variant
    long_prompt = min(long_prompt,
                      model.config.max_seq_len - max(decode_tokens, 64))
    short_prompt = min(short_prompt, long_prompt)
    engine = InferenceEngineV2(
        model, RaggedInferenceEngineConfig(
            max_batch_size=max_batch, kv_block_size=block_size,
            prompt_bucket=min(long_prompt, 512),
            splitfuse_tokens=splitfuse, paged_kernel=paged_kernel))
    r = np.random.RandomState(seed)
    V = model.config.vocab_size
    arrivals = np.cumsum(r.exponential(1.0 / rate, n_requests))
    prompts = [r.randint(0, V, (long_prompt if i % long_every == 0
                                else short_prompt,))
               for i in range(n_requests)]

    # warm EVERY program the mix will hit: the short and long prefill
    # shapes (bucketed path) / the chunk + FUSED chunk-while-decoding
    # programs (SplitFuse path), and the decode loop — a mid-run XLA
    # compile would land inside some request's TTFT
    w1 = engine.put(r.randint(0, V, (short_prompt,)),
                    max_new_tokens=64, eos_token_id=-1)
    for _ in range(2 + short_prompt // max(1, splitfuse or short_prompt)):
        engine.step()                  # w1 prefilled + decoding
    w2 = engine.put(r.randint(0, V, (long_prompt,)), max_new_tokens=4,
                    eos_token_id=-1)
    while not (engine.is_done(w1) and engine.is_done(w2)):
        engine.step()
    engine.get(w1), engine.get(w2)

    tok_times, submit, wall = _poisson_drive(engine, prompts, arrivals,
                                             decode_tokens)

    ttft, tpot = [], []
    first_dispatch_finishers = 0
    for uid, ts in tok_times.items():
        if not ts:
            continue
        ttft.append(1e3 * (ts[0] - submit[uid]))
        if len(ts) < 2 or ts[-1] == ts[0]:
            # the whole budget arrived in one dispatch: there is no
            # inter-token gap to measure — counted, not divided by zero
            first_dispatch_finishers += 1
            continue
        tpot.append(1e3 * (ts[-1] - ts[0]) / (len(ts) - 1))
    return {
        "model": name, "mode": "mixed-traffic",
        "variant": {"paged_kernel": "on" if paged_kernel else "off",
                    "splitfuse": "on" if splitfuse else "off"},
        "arrival_rate_qps": rate, "n_requests": n_requests,
        "long_prompt": long_prompt, "short_prompt": short_prompt,
        "long_every": long_every, "decode_tokens": decode_tokens,
        "splitfuse_tokens": splitfuse,
        "ttft_ms_p50": _pct(ttft, 50), "ttft_ms_p99": _pct(ttft, 99),
        "tpot_ms_p50": _pct(tpot, 50), "tpot_ms_p99": _pct(tpot, 99),
        "first_dispatch_finishers": first_dispatch_finishers,
        "completed": len([1 for ts in tok_times.values() if ts]),
        "wall_s": round(wall, 2),
        "devices": len(jax.devices()),
        # the engine's OWN per-request accounting (monitor/telemetry.py
        # ServingTelemetry — what a production fan-out would export),
        # next to the harness-measured percentiles as a cross-check
        "engine_telemetry": engine.telemetry_snapshot(),
    }


def bench_mixed_traffic(name="gpt2-350M", rate=2.0, n_requests=24,
                        long_prompt=1024, short_prompt=64, long_every=4,
                        decode_tokens=64, chunk=256, block_size=64,
                        max_batch=8, seed=0):
    """Sustained mixed traffic (ROADMAP item 1's harness): Poisson
    arrivals where every ``long_every``-th request carries a
    ``long_prompt``-token prompt and the rest are short decode-heavy
    requests. Reports p50/p99 TTFT and TPOT SEPARATELY for the 2x2 of
    paged kernel on/off x SplitFuse on/off — split-fuse holding p99
    TPOT flat while long prefills stream is the FastGen headline
    property; the paged-kernel pair isolates the blocked-flash chunk
    kernel's effect on both tails. A variant that crashes records its
    error and the sweep continues (partial artifacts beat lost ones)."""
    rows = []
    for splitfuse in (chunk, 0):
        for paged in (True, False):
            try:
                rows.append(_record(_mixed_one(
                    name, rate, n_requests, long_prompt, short_prompt,
                    long_every, decode_tokens, splitfuse, paged,
                    block_size, max_batch, seed)))
            except Exception as e:  # noqa: BLE001 — keep sweeping
                rows.append(_record({
                    "model": name, "mode": "mixed-traffic",
                    "variant": {"paged_kernel": "on" if paged else "off",
                                "splitfuse": "on" if splitfuse
                                else "off"},
                    "error": f"{type(e).__name__}: {e}"[:300]}))
            write_local_report()       # partial sweep already durable
    return rows


def _shared_prefix_one(name, rate, n_requests, n_templates, template_len,
                       suffix_len, share_ratio, decode_tokens, chunk,
                       block_size, max_batch, prefix_cache, seed):
    """One shared-prefix traffic run; returns the percentile row with
    the engine's prefix-cache counters (hit rate, cached tokens, CoW
    copies, evictions) measured over the driven traffic only — warm-up
    requests are snapshotted out."""
    groups.reset()
    model = build_model(name)
    template_len = min(
        template_len,
        model.config.max_seq_len - suffix_len - max(decode_tokens, 64))
    engine = InferenceEngineV2(
        model, RaggedInferenceEngineConfig(
            max_batch_size=max_batch, kv_block_size=block_size,
            prompt_bucket=min(template_len + suffix_len, 512),
            splitfuse_tokens=chunk, prefix_cache=prefix_cache))
    r = np.random.RandomState(seed)
    V = model.config.vocab_size
    templates = [r.randint(0, V, (template_len,))
                 for _ in range(n_templates)]
    arrivals = np.cumsum(r.exponential(1.0 / rate, n_requests))
    prompts = []
    shared_count = 0
    for _ in range(n_requests):
        suffix = r.randint(0, V, (suffix_len,))
        if r.rand() < share_ratio:
            shared_count += 1
            prompts.append(np.concatenate(
                [templates[r.randint(n_templates)], suffix]))
        else:
            prompts.append(r.randint(0, V, (template_len + suffix_len,)))

    # warm every program outside the driven requests' TTFT: chunk,
    # fused chunk+decode, decode — and for the cache-on variant the CoW
    # copy program (second warm-up shares the first's prompt, diverging
    # mid-block). One donor request per template then runs to
    # completion so the driven phase measures the WARM cache (hit rate
    # ~= share_ratio): inserts happen at release, so without donors the
    # first arrival of every template — plus every sharer admitted
    # while it is still in flight — is a structural miss. The cache-off
    # variant runs the identical donors, so the two rows differ only in
    # the cache.
    warm = r.randint(0, V, (template_len + suffix_len,))
    w1 = engine.put(warm, max_new_tokens=decode_tokens, eos_token_id=-1)
    for _ in range(2):
        engine.step()              # w1 prefilling/decoding
    w2 = engine.put(np.concatenate([warm[:-3], r.randint(0, V, (3,))]),
                    max_new_tokens=4, eos_token_id=-1)
    while not (engine.is_done(w1) and engine.is_done(w2)):
        engine.step()
    engine.get(w1), engine.get(w2)
    donors = [engine.put(
        np.concatenate([t, r.randint(0, V, (suffix_len,))]),
        max_new_tokens=2, eos_token_id=-1) for t in templates]
    while not all(engine.is_done(d) for d in donors):
        engine.step()
    for d in donors:
        engine.get(d)
    base = engine.prefix_cache.stats() if engine.prefix_cache else None

    tok_times, submit, wall = _poisson_drive(engine, prompts, arrivals,
                                             decode_tokens)

    ttft, tpot = [], []
    for uid, ts in tok_times.items():
        if not ts:
            continue
        ttft.append(1e3 * (ts[0] - submit[uid]))
        if len(ts) >= 2 and ts[-1] != ts[0]:
            tpot.append(1e3 * (ts[-1] - ts[0]) / (len(ts) - 1))
    row = {
        "model": name, "mode": "shared-prefix",
        "variant": {"prefix_cache": "on" if prefix_cache else "off"},
        "arrival_rate_qps": rate, "n_requests": n_requests,
        "n_templates": n_templates, "template_len": template_len,
        "suffix_len": suffix_len, "share_ratio": share_ratio,
        "shared_requests": shared_count,
        "decode_tokens": decode_tokens, "splitfuse_tokens": chunk,
        "ttft_ms_p50": _pct(ttft, 50), "ttft_ms_p99": _pct(ttft, 99),
        "tpot_ms_p50": _pct(tpot, 50), "tpot_ms_p99": _pct(tpot, 99),
        "completed": len([1 for ts in tok_times.values() if ts]),
        "wall_s": round(wall, 2),
        "devices": len(jax.devices()),
        "engine_telemetry": engine.telemetry_snapshot(),
    }
    if engine.prefix_cache is not None:
        s = engine.prefix_cache.stats()
        lookups = s["lookups"] - base["lookups"]
        hits = s["hits"] - base["hits"]
        row["cache_hit_rate"] = round(100.0 * hits / lookups, 1) \
            if lookups else 0.0
        row["cached_tokens"] = s["cached_tokens"] - base["cached_tokens"]
        row["cached_tokens_per_sec"] = round(
            row["cached_tokens"] / max(wall, 1e-9), 1)
        row["cow_copies"] = s["cow_copies"] - base["cow_copies"]
        row["prefix_evictions"] = \
            s["evicted_blocks"] - base["evicted_blocks"]
        row["tree_blocks"] = s["tree_blocks"]
    else:
        row["cache_hit_rate"] = 0.0
    return row


def bench_shared_prefix(name="gpt2-350M", rate=2.0, n_requests=24,
                        n_templates=4, template_len=512, suffix_len=64,
                        share_ratio=0.75, decode_tokens=64, chunk=256,
                        block_size=64, max_batch=8, seed=0):
    """Shared-prefix Poisson traffic (ROADMAP item 3a's harness):
    ``n_templates`` prompt templates, each request drawing a template +
    per-request suffix with probability ``share_ratio`` (else a fully
    random prompt of the same length). Reports TTFT/TPOT p50/p99 and
    the cache hit rate for prefix_cache on vs off — the pass signal is
    TTFT p50 collapsing on the cache-on row while p99 TPOT stays within
    noise (cached prefixes skip prefill chunks; decode work is
    unchanged). A variant that crashes records its error and the sweep
    continues; every row is durable in SERVE_local.json immediately."""
    rows = []
    for prefix_cache in (True, False):
        try:
            rows.append(_record(_shared_prefix_one(
                name, rate, n_requests, n_templates, template_len,
                suffix_len, share_ratio, decode_tokens, chunk,
                block_size, max_batch, prefix_cache, seed)))
        except Exception as e:  # noqa: BLE001 — keep sweeping
            rows.append(_record({
                "model": name, "mode": "shared-prefix",
                "variant": {"prefix_cache": "on" if prefix_cache
                            else "off"},
                "error": f"{type(e).__name__}: {e}"[:300]}))
        write_local_report()           # partial sweep already durable
    return rows


def _router_drive(router, prompts, arrivals, decode_tokens, classes,
                  kill_at_step=None, drain_at_step=None):
    """Open-loop Poisson driver against the ROUTER (the front-end owns
    the queue, so back-pressure shows up as typed Overloaded rejections
    at put() — counted, not crashed). Optionally arms one
    ``replica_death`` (mid-run kill) or drains replica 0 once
    ``*_at_step`` router rounds have run."""
    uids, rejected_at_put = [], 0
    n = len(prompts)
    start = time.perf_counter()
    i = 0
    steps = 0
    injected = False
    while i < n or router.has_work:
        now = time.perf_counter() - start
        while i < n and arrivals[i] <= now:
            try:
                uids.append(router.put(
                    prompts[i], max_new_tokens=decode_tokens,
                    eos_token_id=-1, klass=classes[i]))
            except Overloaded:
                rejected_at_put += 1
            i += 1
        if not router.has_work:
            time.sleep(min(0.005, max(0.0, arrivals[i] - now)))
            continue
        router.step()
        steps += 1
        if not injected and steps >= (kill_at_step or 0) > 0:
            injected = True
            fault_injection.arm("replica_death", fails=1)
        if not injected and steps >= (drain_at_step or 0) > 0:
            injected = True
            router.drain(router.replicas[0].name)
    return time.perf_counter() - start, rejected_at_put, steps


def _router_one(name, n_replicas, scenario, rate, n_requests, prompt_len,
                decode_tokens, chunk, block_size, max_batch, seed):
    """One fleet-traffic run: N in-process replica engines (shared
    weights) behind the Router, mixed-class Poisson arrivals (class =
    request index mod 3), one row with the router's per-class
    accounting + latency percentiles. ``scenario``:

      baseline      — nothing injected
      replica-kill  — one armed replica_death mid-run (failover +
                      byte-identical replay path under real traffic)
      drain         — router.drain(r0) mid-run (scale-down: finish
                      in-flight, no replay)
    """
    model = build_model(name)
    groups.reset()
    params = model.init(jax.random.key(0))
    engines = []
    for _ in range(n_replicas):
        groups.reset()
        engines.append(InferenceEngineV2(
            model, params=params,
            config=RaggedInferenceEngineConfig(
                max_batch_size=max_batch, kv_block_size=block_size,
                prompt_bucket=min(prompt_len, 512),
                splitfuse_tokens=chunk, prefix_cache=True)))
    router = Router(engines)
    r = np.random.RandomState(seed)
    V = model.config.vocab_size
    prompts = [r.randint(0, V, (prompt_len,)) for _ in range(n_requests)]
    classes = [i % 3 for i in range(n_requests)]
    arrivals = np.cumsum(r.exponential(1.0 / rate, n_requests))
    mid = max(2, n_requests // 2)
    try:
        wall, rejected_at_put, steps = _router_drive(
            router, prompts, arrivals, decode_tokens, classes,
            kill_at_step=mid if scenario == "replica-kill" else None,
            drain_at_step=mid if scenario == "drain" else None)
    finally:
        fault_injection.reset()
    snap = router.snapshot()
    # zero-drop invariant: every admitted request left through exactly
    # one typed exit (completed/expired/queued-shed); admission
    # rejections are the shed counter's remainder
    closed = (snap["completed"] + snap["expired"]
              + (snap["shed"] - rejected_at_put)) == snap["admitted"]
    return {
        "model": name, "mode": "router-traffic",
        "variant": {"fleet": n_replicas, "scenario": scenario},
        "arrival_rate_qps": rate, "n_requests": n_requests,
        "prompt_len": prompt_len, "decode_tokens": decode_tokens,
        "splitfuse_tokens": chunk,
        "queue_depth": router.resolved_queue_depth(),
        "router_steps": steps, "wall_s": round(wall, 2),
        "admitted": snap["admitted"], "completed": snap["completed"],
        "shed": snap["shed"], "expired": snap["expired"],
        "replayed": snap["replayed"], "failovers": snap["failovers"],
        "rejected_at_put": rejected_at_put,
        "accounting_closed": closed,
        "replicas": snap["replicas"],
        # per-class rows: admitted/completed/shed/expired/replayed and
        # p50/p99 TTFT+TPOT measured by the router itself
        "classes": {str(k): v for k, v in snap["classes"].items()},
        "devices": len(jax.devices()),
    }


def bench_router_traffic(name="gpt2-350M", n_replicas=2, rate=2.0,
                         n_requests=24, prompt_len=256, decode_tokens=64,
                         chunk=256, block_size=64, max_batch=8, seed=0):
    """Serving-fleet robustness sweep (SERVE_REPLICAS=N): the same
    mixed-class Poisson traffic through baseline / mid-run replica-kill
    / mid-run drain. The kill row's pass signal is failovers=1 with
    accounting_closed (every admitted request completed or left through
    a typed exit — zero drops); the drain row's is replayed=0. A
    scenario that crashes records its error and the sweep continues."""
    rows = []
    for scenario in ("baseline", "replica-kill", "drain"):
        try:
            rows.append(_record(_router_one(
                name, n_replicas, scenario, rate, n_requests, prompt_len,
                decode_tokens, chunk, block_size, max_batch, seed)))
        except Exception as e:  # noqa: BLE001 — keep sweeping
            rows.append(_record({
                "model": name, "mode": "router-traffic",
                "variant": {"fleet": n_replicas, "scenario": scenario},
                "error": f"{type(e).__name__}: {e}"[:300]}))
        write_local_report()           # partial sweep already durable
    return rows


def _disagg_one(name, fleet, scenario, rate, n_requests, short_prompt,
                long_prompt, n_interference, decode_tokens, chunk,
                block_size, max_batch, seed):
    """One disaggregated-serving run: a role-labeled fleet (shared
    weights) behind the phase-aware router, short decode-heavy klass-0
    traffic with an optional burst of long-prefill klass-1 interference
    landing mid-run. Returns one row with the router's handoff/wire
    counters and the DECODE-CLASS (klass 0) latency percentiles — the
    headline comparison is klass-0 p99 TPOT under interference:
    colocated fleets interleave the long prefill chunks into every
    decode batch, a prefill/decode split keeps the decode replicas'
    iteration time flat. ``scenario``:

      quiet              — short traffic only
      interference       — + long-prefill burst at the run's midpoint
      interference-kill  — + one armed replica_death mid-run (handoff
                           failover / colocated-degradation path under
                           real traffic; accounting must stay closed)
    """
    from deepspeed_tpu.inference.v2.replica import Replica
    model = build_model(name)
    long_prompt = min(long_prompt,
                      model.config.max_seq_len - decode_tokens)
    short_prompt = min(short_prompt, long_prompt)
    groups.reset()
    params = model.init(jax.random.key(0))
    replicas = []
    for i, role in enumerate(fleet):
        groups.reset()
        eng = InferenceEngineV2(
            model, params=params,
            config=RaggedInferenceEngineConfig(
                max_batch_size=max_batch, kv_block_size=block_size,
                prompt_bucket=min(long_prompt, 512),
                splitfuse_tokens=chunk))
        replicas.append(Replica(f"{role[:1]}{i}", eng, role=role))
    router = Router(replicas)
    r = np.random.RandomState(seed)
    V = model.config.vocab_size

    # warm every program OUTSIDE the measured traffic: each engine's
    # chunk/fused/decode programs, and for prefill/decode pairs the
    # handoff gather/scatter jits + wire codec (compiles landing inside
    # a driven request's TTFT would swamp the smoke-scale percentiles)
    for rep in replicas:
        eng = rep.engine
        w1 = eng.put(r.randint(0, V, (short_prompt,)),
                     max_new_tokens=8, eos_token_id=-1)
        for _ in range(2):
            eng.step()
        w2 = eng.put(r.randint(0, V, (long_prompt,)), max_new_tokens=2,
                     eos_token_id=-1)
        while not (eng.is_done(w1) and eng.is_done(w2)):
            eng.step()
        eng.get(w1), eng.get(w2)
    from deepspeed_tpu.inference.v2 import kv_transfer
    pre = [x for x in replicas if x.role == "prefill"]
    dec = [x for x in replicas if x.role == "decode"]
    for i, P in enumerate(pre):
        D = dec[i % len(dec)] if dec else None
        if D is None:
            break
        wu = P.engine.put(r.randint(0, V, (short_prompt,)),
                          max_new_tokens=4, eos_token_id=-1)
        P.engine.hold_decode(wu)
        while True:
            P.engine.step()
            seq = P.engine.state_mgr._seqs.get(wu)
            if seq is not None and seq.generated:
                break
        kv_transfer.import_sequence(
            D.engine, kv_transfer.export_sequence(P.engine, wu))
        P.engine.release_handoff(wu)
        while not D.engine.is_done(wu):
            D.engine.step()
        D.engine.get(wu)

    prompts = [r.randint(0, V, (short_prompt,))
               for _ in range(n_requests)]
    classes = [0] * n_requests
    arrivals = list(np.cumsum(r.exponential(1.0 / rate, n_requests)))
    if scenario != "quiet":
        # the interference burst: n_interference long prefills all
        # arriving at once at the run's midpoint
        t_burst = arrivals[n_requests // 2]
        prompts += [r.randint(0, V, (long_prompt,))
                    for _ in range(n_interference)]
        classes += [1] * n_interference
        arrivals += [t_burst] * n_interference
        order = np.argsort(np.asarray(arrivals), kind="stable")
        prompts = [prompts[i] for i in order]
        classes = [classes[i] for i in order]
        arrivals = [arrivals[i] for i in order]
    mid = max(2, len(prompts) // 2)
    try:
        wall, rejected_at_put, steps = _router_drive(
            router, prompts, np.asarray(arrivals), decode_tokens,
            classes,
            kill_at_step=mid if scenario == "interference-kill"
            else None)
    finally:
        fault_injection.reset()
    snap = router.snapshot()
    closed = (snap["completed"] + snap["expired"]
              + (snap["shed"] - rejected_at_put)) == snap["admitted"]
    k0 = snap["classes"].get(0, {})
    return {
        "model": name, "mode": "disagg-serving",
        "variant": {"fleet": "+".join(fleet), "scenario": scenario},
        "arrival_rate_qps": rate, "n_requests": len(prompts),
        "short_prompt": short_prompt, "long_prompt": long_prompt,
        "n_interference": n_interference if scenario != "quiet" else 0,
        "decode_tokens": decode_tokens, "splitfuse_tokens": chunk,
        "router_steps": steps, "wall_s": round(wall, 2),
        "admitted": snap["admitted"], "completed": snap["completed"],
        "shed": snap["shed"], "expired": snap["expired"],
        "replayed": snap["replayed"], "failovers": snap["failovers"],
        "rejected_at_put": rejected_at_put,
        "accounting_closed": closed,
        "handoffs": snap["handoffs"],
        "kv_stream_bytes": snap["kv_stream_bytes"],
        "kv_stream_ms": round(snap["kv_stream_ms"], 2),
        "kv_stream_retries": snap["kv_stream_retries"],
        "replicas": snap["replicas"],
        "roles": snap.get("roles"),
        # the headline numbers: klass-0 (short, decode-heavy) latency
        # as the router measured it — compare p99 TPOT across variants
        "decode_class": {
            "ttft_ms_p50": k0.get("ttft_ms_p50"),
            "ttft_ms_p99": k0.get("ttft_ms_p99"),
            "tpot_ms_p50": k0.get("tpot_ms_p50"),
            "tpot_ms_p99": k0.get("tpot_ms_p99"),
            "completed": k0.get("completed"),
        },
        "classes": {str(k): v for k, v in snap["classes"].items()},
        "devices": len(jax.devices()),
    }


def bench_disagg(name="gpt2-350M", rate=2.0, n_requests=24,
                 short_prompt=64, long_prompt=1024, n_interference=4,
                 decode_tokens=64, chunk=256, block_size=64,
                 max_batch=8, seed=0):
    """Disaggregated prefill/decode sweep (SERVE_DISAGG): the same
    short-request traffic through colocated vs phase-split fleets,
    quiet and under a long-prefill interference burst. The headline
    read: colocated klass-0 p99 TPOT degrades under the burst (every
    decode batch pays for the interleaved prefill chunks) while the
    1P+1D / 2P+2D fleets hold it flat, paying kv_stream_bytes over the
    wire instead. The kill variant arms one replica_death mid-run —
    its pass signal is accounting_closed with the fleet degrading to
    colocated (decode death) or failing over (prefill death). A
    variant that crashes records its error and the sweep continues."""
    variants = [
        (["colocated", "colocated"], "quiet"),
        (["colocated", "colocated"], "interference"),
        (["prefill", "decode"], "quiet"),
        (["prefill", "decode"], "interference"),
        (["prefill", "prefill", "decode", "decode"], "interference"),
        (["prefill", "decode"], "interference-kill"),
    ]
    rows = []
    for fleet, scenario in variants:
        try:
            rows.append(_record(_disagg_one(
                name, fleet, scenario, rate, n_requests, short_prompt,
                long_prompt, n_interference, decode_tokens, chunk,
                block_size, max_batch, seed)))
        except Exception as e:  # noqa: BLE001 — keep sweeping
            rows.append(_record({
                "model": name, "mode": "disagg-serving",
                "variant": {"fleet": "+".join(fleet),
                            "scenario": scenario},
                "error": f"{type(e).__name__}: {e}"[:300]}))
        write_local_report()           # partial sweep already durable
    return rows


def bench_ep_moe(decode_tokens=16, block_size=16, chunk=16,
                 expert_parallel=2):
    """EP Mixtral serving: experts sharded over the 'expert' mesh axis,
    the FFN routed through the ragged EP all_to_all path
    (moe/sharded_moe.py moe_swiglu_ragged_ep — the PR-5 fix for
    GSPMD's silent lax.ragged_dot mis-partition). Asserts greedy
    parity vs the single-shard engine and reports both decode rates;
    SplitFuse on, so the chunk program serves through EP too."""
    if len(jax.devices()) < expert_parallel:
        return _record({
            "mode": "ep-moe-serving",
            "skipped": f"needs >= {expert_parallel} devices, have "
                       f"{len(jax.devices())}"})
    from deepspeed_tpu.models.mixtral import Mixtral, MixtralConfig
    mcfg = MixtralConfig(n_layer=2, n_head=8, n_kv_heads=4, d_model=128,
                         max_seq_len=256, vocab_size=1024, remat=False,
                         num_experts=4, moe_top_k=2, dtype="float32")
    params = Mixtral(mcfg).init(jax.random.key(7))
    r = np.random.RandomState(0)
    prompts = [r.randint(0, mcfg.vocab_size, (n,))
               for n in (24, 40, 9, 33)]

    def run(ep):
        groups.reset()
        # float32 serving: the row's point is EXACT greedy parity
        # through the EP exchange; bf16 reduction reordering across the
        # all_to_all would turn rounding noise into token flips
        engine = InferenceEngineV2(
            Mixtral(mcfg), params=params,
            config=RaggedInferenceEngineConfig(
                dtype="float32", max_batch_size=4,
                kv_block_size=block_size, splitfuse_tokens=chunk,
                expert_parallel=ep))
        outs = engine.generate_all(prompts, max_new_tokens=4)  # warm
        t0 = time.perf_counter()
        outs = engine.generate_all(prompts,
                                   max_new_tokens=decode_tokens)
        dt = time.perf_counter() - t0
        produced = sum(len(o) for o in outs)
        return outs, produced / dt

    ref, rate1 = run(1)
    got, rate_ep = run(expert_parallel)
    parity = all(np.array_equal(a, b) for a, b in zip(ref, got))
    return _record({
        "mode": "ep-moe-serving", "model": "mixtral(2x128,E4)",
        "expert_parallel": expert_parallel,
        "splitfuse_tokens": chunk,
        "greedy_parity_vs_single": parity,
        "decode_tok_s_ep1": round(rate1, 1),
        "decode_tok_s_ep": round(rate_ep, 1),
        "devices": len(jax.devices()),
    })


def main():
    models = [m for m in os.environ.get(
        "SERVE_MODELS", "gpt2-350M,llama-1b").split(",") if m]
    batches = [int(b) for b in
               os.environ.get("SERVE_BATCHES", "1,8").split(",")]
    prompt = int(os.environ.get("SERVE_PROMPT", "1024"))
    decode = int(os.environ.get("SERVE_DECODE", "128"))
    for m in models:
        for b in batches:
            bench_one(m, b, prompt, decode)
    if os.environ.get("SERVE_SPLITFUSE", "1") == "1":
        for m in models:
            bench_splitfuse(m, prompt_len=prompt,
                            chunk=int(os.environ.get("SERVE_CHUNK",
                                                     "256")),
                            decode_tokens=16)
    if os.environ.get("SERVE_MIXED", "1") == "1":
        # off-TPU the paged_kernel=on variants run interpret-mode
        # Pallas — minutes per token at 350M; default to the tiny
        # smoke model AND smoke-scale traffic there so a CPU run still
        # produces all 4 percentile rows in minutes, not hours
        on_tpu = jax.default_backend() == "tpu"
        mixed_kw = {} if on_tpu else dict(
            long_prompt=96, short_prompt=16, decode_tokens=16,
            chunk=16, block_size=8, max_batch=4, rate=8.0)
        if "SERVE_MIXED_RATE" in os.environ:
            mixed_kw["rate"] = float(os.environ["SERVE_MIXED_RATE"])
        bench_mixed_traffic(
            name=os.environ.get("SERVE_MIXED_MODEL",
                                "gpt2-350M" if on_tpu else "tiny"),
            n_requests=int(os.environ.get("SERVE_MIXED_N",
                                          "24" if on_tpu else "12")),
            **mixed_kw)
    if os.environ.get("SERVE_PREFIX", "1") == "1":
        # same CPU smoke-scale discipline as SERVE_MIXED: off-TPU the
        # tiny model + small traffic still produce both rows in minutes
        on_tpu = jax.default_backend() == "tpu"
        pf_kw = {} if on_tpu else dict(
            template_len=96, suffix_len=16, decode_tokens=16, chunk=16,
            block_size=8, max_batch=4, rate=8.0, n_templates=2)
        if "SERVE_PREFIX_SHARE" in os.environ:
            pf_kw["share_ratio"] = float(os.environ["SERVE_PREFIX_SHARE"])
        bench_shared_prefix(
            name=os.environ.get("SERVE_PREFIX_MODEL",
                                "gpt2-350M" if on_tpu else "tiny"),
            n_requests=int(os.environ.get("SERVE_PREFIX_N",
                                          "24" if on_tpu else "12")),
            **pf_kw)
    n_replicas = int(os.environ.get("SERVE_REPLICAS", "0") or "0")
    if n_replicas >= 2:
        # fleet robustness rows (baseline / replica-kill / drain); same
        # CPU smoke-scale discipline as SERVE_MIXED
        on_tpu = jax.default_backend() == "tpu"
        rt_kw = {} if on_tpu else dict(
            prompt_len=48, decode_tokens=16, chunk=16, block_size=8,
            max_batch=4, rate=8.0)
        if "SERVE_ROUTER_RATE" in os.environ:
            rt_kw["rate"] = float(os.environ["SERVE_ROUTER_RATE"])
        bench_router_traffic(
            name=os.environ.get("SERVE_ROUTER_MODEL",
                                "gpt2-350M" if on_tpu else "tiny"),
            n_replicas=n_replicas,
            n_requests=int(os.environ.get("SERVE_ROUTER_N",
                                          "24" if on_tpu else "9")),
            **rt_kw)
    if os.environ.get("SERVE_DISAGG", "1") != "0":
        # disaggregated prefill/decode rows (colocated vs 1P+1D vs
        # 2P+2D under long-prefill interference); same CPU smoke-scale
        # discipline — off-TPU the tiny model produces every row in
        # minutes
        on_tpu = jax.default_backend() == "tpu"
        dg_kw = {} if on_tpu else dict(
            short_prompt=16, long_prompt=96, n_interference=3,
            decode_tokens=24, chunk=16, block_size=8, max_batch=4,
            rate=8.0)
        if "SERVE_DISAGG_RATE" in os.environ:
            dg_kw["rate"] = float(os.environ["SERVE_DISAGG_RATE"])
        bench_disagg(
            name=os.environ.get("SERVE_DISAGG_MODEL",
                                "gpt2-350M" if on_tpu else "tiny"),
            n_requests=int(os.environ.get("SERVE_DISAGG_N",
                                          "24" if on_tpu else "10")),
            **dg_kw)
    if os.environ.get("SERVE_EP_MOE", "1") == "1":
        bench_ep_moe()
    if os.environ.get("SERVE_WQ", "1") != "0":
        # fused weight-only serving rows (off / int8 / int4); same CPU
        # smoke-scale discipline — off-TPU the tiny model produces all
        # three rows in minutes
        on_tpu = jax.default_backend() == "tpu"
        wq_kw = {} if on_tpu else dict(
            batch=4, prompt_len=64, decode_tokens=16, block_size=16)
        bench_weight_quant(
            name=os.environ.get("SERVE_WQ_MODEL",
                                "gpt2-350M" if on_tpu else "tiny-wq"),
            **wq_kw)
    if os.environ.get("SERVE_SPEC", "1") != "0":
        # speculative decoding rows (off / spec_k sweep / adversarial
        # fallback); same CPU smoke-scale discipline — off-TPU the tiny
        # model produces every row in minutes
        on_tpu = jax.default_backend() == "tpu"
        sp_kw = {} if on_tpu else dict(
            batch=4, prompt_len=64, decode_tokens=24, chunk=16,
            block_size=16)
        bench_speculative(
            name=os.environ.get("SERVE_SPEC_MODEL",
                                "gpt2-350M" if on_tpu else "tiny"),
            spec_ks=tuple(int(k) for k in os.environ.get(
                "SERVE_SPEC_KS", "2,4").split(",")),
            **sp_kw)
    if os.environ.get("SERVE_QUANT", ""):
        bench_quant(os.environ["SERVE_QUANT"])
    if os.environ.get("SERVE_KV_OFFLOAD", "") == "1":
        bench_kv_offload()
    if os.environ.get("SERVE_KV_OFFLOAD", "") == "7b":
        # the headline ZeRO-Inference capacity point: llama2-7b int8
        # weights + a KV footprint the chip cannot hold resident —
        # 6 streams x 2048 ctx = ~6 GB KV paging through a ~2 GB pool
        bench_kv_offload(name="llama2-7b-serve", batch=6,
                         prompt_len=1920, decode_tokens=64,
                         block_size=64, device_blocks=66,
                         quantize=True, splitfuse=256, max_batch=2)
    if os.environ.get("SERVE_SLA", "") == "1":
        sf = int(os.environ.get("SERVE_SLA_SPLITFUSE", "0"))
        bench_sla(splitfuse=sf)


if __name__ == "__main__":
    try:
        main()
    except BaseException as e:       # incl. KeyboardInterrupt/SystemExit
        write_local_report(error=f"{type(e).__name__}: {e}"[:300])
        raise
    else:
        write_local_report()
